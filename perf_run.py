"""Full-scale scheduler scalability run.

Reference scenario (test/performance/scheduler/default_generator_config.yaml:
5 cohorts x 6 CQs, 350 small + 100 medium + 50 large per CQ = 15,000
workloads / 30 CQs), driven through the full manager on a virtual clock,
checked against the carried-over rangespec queueing-dynamics bounds
(default_rangespec.yaml:8-30). Runs the pure-CPU scheduler and the
solver-enabled scheduler and writes PERF_r{N}.json.

Usage: python perf_run.py [--scale 1.0] [--out PERF_r02.json]
"""

import argparse
import json
import sys
import time


def run_mode(label, scale, solver, config="default", backend=None):
    from kueue_tpu.perf import (
        Runner, check, default_generator_config, default_rangespec, generate,
        north_star_generator_config, north_star_rangespec,
        refuse_cross_backend)
    if config == "north-star":
        load = generate(north_star_generator_config(), scale=scale,
                        num_flavors=32)
    else:
        load = generate(default_generator_config(), scale=scale)
    t0 = time.monotonic()
    result = Runner(load, solver=solver).run()
    # the rangespec's queueing-dynamics bounds are calibrated for the
    # default 15k scenario; the north-star spec carries the
    # backend-independent compile-storm bound (zero mid-traffic
    # compiles after the governor's warmup — solver/COMPILE.md)
    spec = (default_rangespec() if config == "default"
            else north_star_rangespec())
    # Bench-env honesty (ROADMAP bench-env note): a rangespec that
    # declares its calibration backend refuses to judge a run from a
    # different one — rangespec_ok becomes None (not judged), never a
    # phantom pass/regression.
    refusal = (refuse_cross_backend(spec, backend)
               if spec is not None else None)
    if backend and backend.get("cpu_fallback") and solver is not None:
        # Standing r05 debt (ROADMAP item 2): every headline number
        # measured on cpu_fallback needs a device re-baseline before it
        # can be compared — recorded into the witness manifest.
        from kueue_tpu.perf import checker as checkerpkg
        checkerpkg.record_refusal(
            f"perf_run.{config}.{label}.e2e_baseline", "e2e_rebaseline",
            "headline numbers measured on cpu_fallback — device "
            "re-baseline required before comparison", "tpu")
    if spec is None or refusal is not None:
        violations = []
        if refusal is not None:
            # Device-witness debt: a refused comparison is a bound this
            # environment could not witness — consolidated into the
            # artifact's manifest so a future device run knows exactly
            # what it must re-judge.
            from kueue_tpu.perf import checker as checkerpkg
            checkerpkg.record_refusal(
                f"perf_run.{config}.{label}", "rangespec", refusal,
                spec.backend)
    else:
        violations = check(result, spec)
    out = {
        "mode": label,
        "scale": scale,
        # stamped on EVERY headline row, not just the file header: a
        # row read in isolation must still be attributable
        **(backend or {}),
        "total_workloads": result.total,
        "admitted": result.admitted,
        "finished": result.finished,
        "cycles": result.cycles,
        "wall_s": round(result.wall_s, 1),
        "virtual_makespan_s": round(result.virtual_makespan_s, 1),
        "admissions_per_wall_second": round(result.admissions_per_wall_second, 1),
        "cycle_p50_ms": round(result.cycle_p50_ms, 1),
        "cycle_p99_ms": round(result.cycle_p99_ms, 1),
        "cycle_time_total_s": round(result.cycle_time_total_s, 1),
        "class_avg_tta_s": {
            cls: round(st.avg, 2) for cls, st in result.class_stats.items()},
        "class_p99_tta_s": {
            cls: round(st.p99, 2) for cls, st in result.class_stats.items()},
        "cq_class_avg_usage_pct": {
            cls: round(pct, 1)
            for cls, pct in result.cq_class_avg_usage_pct.items()},
        "rangespec_violations": violations,
        "rangespec_ok": (None if spec is None or refusal is not None
                         else not violations),
        "rangespec_refused": refusal,
        # engine/pipelining engagement + per-phase solver time: the
        # perf claims must be checkable (VERDICT r4 missing #4)
        "engine_cycles": result.engine_cycles,
        "pipelined_hit_rate": (round(result.pipelined_hit_rate, 3)
                               if result.pipelined_hit_rate is not None
                               else None),
        # speculative-pipeline outcomes: validated commits vs
        # mis-speculation aborts by validation reason
        "speculation": result.speculation,
        "solver_phase_s": result.solver_phase_s,
        "solver_counters": result.solver_counters,
        # per-cycle transport (decision-only fetch / donated uploads):
        # average wire bytes per dispatch/collect
        "upload_bytes_per_cycle": (
            round(result.upload_bytes_per_cycle, 1)
            if result.upload_bytes_per_cycle is not None else None),
        "fetch_bytes_per_cycle": (
            round(result.fetch_bytes_per_cycle, 1)
            if result.fetch_bytes_per_cycle is not None else None),
        # snapshot-build cost as its own metric (incremental
        # journal-replay snapshots): p50/p99 per cache.snapshot() call
        # plus which path (incremental/full/light) served each one
        "snapshot_build_p50_ms": round(result.snapshot_build_p50_ms, 3),
        "snapshot_build_p99_ms": round(result.snapshot_build_p99_ms, 3),
        "snapshot_counts": result.snapshot_counts,
        # encode-phase cost as its own metric (workload encode arena):
        # p50/p99 per solver prepare() — O(changed) row re-encodes plus
        # the vectorized slot gather
        "encode_p50_ms": round(result.encode_p50_ms, 3),
        "encode_p99_ms": round(result.encode_p99_ms, 3),
        # per-cycle phase latency from the flight-recorder histograms
        # (cycle_phase_seconds merged across routes; bucket-estimated)
        "phase_p50_ms": {k: round(v, 3)
                         for k, v in result.phase_p50_ms.items()},
        "phase_p99_ms": {k: round(v, 3)
                         for k, v in result.phase_p99_ms.items()},
        # compile-storm accounting (solver/COMPILE.md): program variants
        # that first executed inside a measured cycle (the north-star
        # rangespec pins this at 0), plus the governor's warmup summary
        "mid_traffic_compiles": result.mid_traffic_compiles,
        "warmup": result.warmup,
    }
    print(json.dumps(out), file=sys.stderr, flush=True)
    return out


def main():
    from kueue_tpu.utils.runtime import (
        enable_compilation_cache, ensure_live_backend, tune_gc)
    tune_gc()  # manager-binary GC profile (applies to every measured mode)
    enable_compilation_cache()  # amortize remote compiles across runs
    backend = ensure_live_backend()
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--modes", default="cpu,solver")
    ap.add_argument("--config", default="default",
                    choices=("default", "north-star"))
    args = ap.parse_args()

    if args.config == "north-star":
        scenario = ("north_star_generator_config (250 cohorts x 8 CQs = "
                    "2,000 CQs x 32 flavors, 50,000 workloads at scale=1; "
                    "BASELINE.json config #5)")
        rangespec = ("compile-storm bound only (zero mid-traffic compiles "
                     "after warmup; no published reference "
                     "queueing-dynamics bounds at this scale)")
    else:
        scenario = ("reference default_generator_config "
                    "(5 cohorts x 6 CQs, 15k workloads at scale=1)")
        rangespec = ("reference default_rangespec queueing-dynamics "
                     "bounds (large<=11s, medium<=90s, small<=233s avg "
                     "TTA; cq usage>=55%)")
    from kueue_tpu.perf import checker as checkerpkg
    checkerpkg.reset_witness_debt()
    results = {"scenario": scenario, "rangespec": rangespec, **backend,
               "runs": []}
    for mode in args.modes.split(","):
        if mode == "cpu":
            results["runs"].append(
                run_mode("cpu", args.scale, None, config=args.config,
                         backend=backend))
        elif mode == "solver":
            from kueue_tpu.solver import BatchSolver
            results["runs"].append(
                run_mode("solver", args.scale, BatchSolver(),
                         config=args.config, backend=backend))
        else:
            ap.error(f"unknown mode {mode!r} (expected 'cpu' or 'solver')")
    # Device-witness debt manifest (consolidated): every rangespec this
    # run REFUSED on cpu_fallback/cross-backend grounds — the exact
    # gate list a future device-backend run must witness.
    results["device_witness_debt"] = checkerpkg.witness_debt()
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
    print(json.dumps({
        "perf": "scalability_harness",
        **backend,
        # backend + cpu_fallback ride on every headline row so a row
        # quoted in isolation stays attributable (bench-env honesty).
        "runs": [{k: r[k] for k in ("mode", "admitted", "wall_s",
                                    "admissions_per_wall_second",
                                    "rangespec_ok", "backend",
                                    "cpu_fallback")}
                 for r in results["runs"]],
    }))


if __name__ == "__main__":
    main()
