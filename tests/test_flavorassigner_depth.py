"""Flavor-assignment depth suite.

Transliteration of roughly 40 of the ~70 cases in the reference's
pkg/scheduler/flavorassigner/flavorassigner_test.go tables
(TestAssignFlavors:51-1976, TestReclaimBeforePriorityPreemption:1981-2131)
driving FlavorAssigner.assign against a snapshot whose cohort aggregates
are overridden exactly as the reference harness does
(flavorassigner_test.go:1957-1963). Covered: fit/preempt/no-fit
classification, borrowing & lending limits, taints/tolerations,
node-affinity matching, multi-resource-group and pods-resource cases,
reclaim-before-priority-preemption. Not yet transliterated:
partial-admission x podset-reducer interplay and the LastState-dependent
fungibility-resume cases (exercised instead by tests/test_solver.py's
resume suites and tests/test_scheduler.py).
"""

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import (
    Affinity, NodeAffinity, NodeSelector, NodeSelectorRequirement,
    NodeSelectorTerm, RESOURCE_PODS, Taint, parse_quantity,
)
from kueue_tpu.cache import Cache
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler.flavorassigner import (
    FIT, NO_FIT, PREEMPT, FlavorAssigner,
)
from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
)

CPU = "cpu"
MEM = "memory"
GPU = "example.com/gpu"

SPOT_TOLERATION = dict(key="instance", value="spot", effect="NoSchedule")


def fixture_flavors():
    """flavorassigner_test.go:52-67."""
    return {
        "default": make_flavor("default"),
        "one": make_flavor("one", node_labels={"type": "one"}),
        "two": make_flavor("two", node_labels={"type": "two"}),
        "b_one": make_flavor("b_one", node_labels={"b_type": "one"}),
        "b_two": make_flavor("b_two", node_labels={"b_type": "two"}),
        "tainted": make_flavor("tainted", taints=[
            Taint(key="instance", value="spot", effect="NoSchedule")]),
    }


def fq(flavor, **resources):
    """flavor_quotas but allowing the gpu resource via 'gpu' shorthand."""
    out = flavor_quotas(flavor, **{k: v for k, v in resources.items()
                                   if k != "gpu"})
    if "gpu" in resources:
        spec = resources["gpu"]
        if isinstance(spec, tuple):
            nominal, borrowing = spec[0], spec[1] if len(spec) > 1 else None
        else:
            nominal, borrowing = spec, None
        out.resources.append(api.ResourceQuota(
            name=GPU, nominal_quota=parse_quantity(nominal, GPU),
            borrowing_limit=(parse_quantity(borrowing, GPU)
                             if borrowing is not None else None)))
    return out


def frq(pairs):
    """{(flavor, res): qty-string} -> {FlavorResource: int}."""
    return {FlavorResource(f, r): parse_quantity(q, r)
            for (f, r), q in pairs.items()}


def run_assign(cq_wrapper, pod_sets, cq_usage=None, cohort_requestable=None,
               cohort_usage=None, reclaimable=None, fair=False,
               extra_cqs=(), extra_usage=None, flavors=None):
    flavors = flavors or fixture_flavors()
    cache = Cache()
    for f in flavors.values():
        cache.add_or_update_resource_flavor(f)
    cq = cq_wrapper.obj()
    cache.add_cluster_queue(cq)
    for other in extra_cqs:
        cache.add_cluster_queue(other.obj())
    snapshot = cache.snapshot()
    cq_snap = snapshot.cluster_queues[cq.metadata.name]

    if cohort_requestable is not None:
        assert cq_snap.cohort is not None
        cq_snap.cohort.resource_node.subtree_quota = frq(cohort_requestable)
        cq_snap.cohort.resource_node.usage = frq(cohort_usage or {})
    if cq_usage:
        cq_snap.resource_node.usage = frq(cq_usage)
    if extra_usage:
        for name, usage in extra_usage.items():
            snapshot.cluster_queues[name].add_usage(frq(usage))

    w = WorkloadWrapper("wl")
    for spec in pod_sets:
        spec = dict(spec)
        tolerate = spec.pop("_tolerate_spot", False)
        w.pod_set(**spec)
        if tolerate:
            w.toleration(**SPOT_TOLERATION)
    wl = w.obj()
    if reclaimable:
        wl.status.reclaimable_pods = [
            api.ReclaimablePod(name=n, count=c) for n, c in reclaimable.items()]
    info = wlpkg.Info(wl, cluster_queue=cq.metadata.name)

    # the reference's testOracle: reclaim possible iff not borrowing
    # (flavorassigner_test.go:45-49)
    def oracle(cq_, wl_, fr, q):
        return not cq_.borrowing_with(fr, q)

    assigner = FlavorAssigner(info, cq_snap, flavors,
                              enable_fair_sharing=fair, oracle=oracle)
    return assigner.assign()


def flavors_of(assignment, ps=0):
    return {res: (fa.name, fa.mode, fa.tried_flavor_idx)
            for res, fa in (assignment.pod_sets[ps].flavors or {}).items()}


def usage_of(assignment):
    return dict(assignment.usage)


class TestAssignFlavors:
    def test_single_flavor_fits(self):
        a = run_assign(
            ClusterQueueWrapper("cq").resource_group(
                flavor_quotas("default", cpu="1", memory="2Mi")),
            [dict(count=1, cpu="1", memory="1Mi")])
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("default", FIT, -1),
                                 MEM: ("default", FIT, -1)}
        assert usage_of(a) == frq({("default", CPU): "1",
                                   ("default", MEM): "1Mi"})

    def test_single_flavor_fits_tainted_flavor(self):
        cqw = ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("tainted", cpu="4"))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="1")
        w.toleration(**SPOT_TOLERATION)
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, snapshot.cluster_queues["cq"],
                           fixture_flavors()).assign()
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("tainted", FIT, -1)}

    def test_single_flavor_used_resources_preempt(self):
        a = run_assign(
            ClusterQueueWrapper("cq").resource_group(
                flavor_quotas("default", cpu="4")),
            [dict(count=1, cpu="2")],
            cq_usage={("default", CPU): "3"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("default", PREEMPT, -1)}
        assert usage_of(a) == frq({("default", CPU): "2"})

    def test_multiple_resource_groups_fits(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="2"),
                            flavor_quotas("two", cpu="4"))
            .resource_group(flavor_quotas("b_one", memory="1Gi"),
                            flavor_quotas("b_two", memory="5Gi")),
            [dict(count=1, cpu="3", memory="10Mi")])
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("two", FIT, -1),
                                 MEM: ("b_one", FIT, 0)}

    def test_multiple_resource_groups_one_preempt_other_nofit(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="3"))
            .resource_group(flavor_quotas("b_one", memory="1Mi")),
            [dict(count=1, cpu="3", memory="10Mi")],
            cq_usage={("one", CPU): "1"})
        assert a.representative_mode() == NO_FIT
        assert a.pod_sets[0].flavors is None
        assert usage_of(a) == {}

    def test_multiple_rg_multiple_resources_fits(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="2", memory="1Gi"),
                            flavor_quotas("two", cpu="4", memory="15Mi"))
            .resource_group(fq("b_one", gpu="4"), fq("b_two", gpu="2")),
            [dict(count=1, cpu="3", memory="10Mi", **{GPU: "3"})])
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("two", FIT, -1),
                                 MEM: ("two", FIT, -1),
                                 GPU: ("b_one", FIT, 0)}

    def test_multiple_rg_fits_with_different_modes(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("one", cpu="2", memory="1Gi"),
                            flavor_quotas("two", cpu="4", memory="15Mi"))
            .resource_group(fq("b_one", gpu="4")),
            [dict(count=1, cpu="3", memory="10Mi", **{GPU: "3"})],
            cq_usage={("two", MEM): "10Mi"},
            cohort_requestable={("one", CPU): "2", ("one", MEM): "1Gi",
                                ("two", CPU): "4", ("two", MEM): "15Mi",
                                ("b_one", GPU): "4"},
            cohort_usage={("two", MEM): "10Mi", ("b_one", GPU): "2"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("two", FIT, -1),
                                 MEM: ("two", PREEMPT, -1),
                                 GPU: ("b_one", PREEMPT, -1)}

    def test_multiple_resources_in_group_nofit(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="2", memory="1Gi"),
                            flavor_quotas("two", cpu="4", memory="5Mi")),
            [dict(count=1, cpu="3", memory="10Mi")])
        assert a.representative_mode() == NO_FIT
        assert a.pod_sets[0].flavors is None

    def test_skips_tainted_flavor(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("tainted", cpu="4"),
                            flavor_quotas("two", cpu="4")),
            [dict(count=1, cpu="3")])
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("two", FIT, -1)}

    def test_fits_node_selector(self):
        cqw = (ClusterQueueWrapper("cq")
               .resource_group(flavor_quotas("one", cpu="4"),
                               flavor_quotas("two", cpu="4")))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="1")
        # ignored1 key is not a flavor label key => ignored
        w.node_selector("type", "two")
        w.node_selector("ignored1", "foo")
        spec = w.wl.spec.pod_sets[0].template.spec
        spec.affinity = Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(node_selector_terms=[NodeSelectorTerm(
                match_expressions=[NodeSelectorRequirement(
                    key="ignored2", operator="In", values=["bar"])])])))
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, snapshot.cluster_queues["cq"],
                           fixture_flavors()).assign()
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("two", FIT, -1)}

    def test_fits_node_affinity(self):
        cqw = (ClusterQueueWrapper("cq")
               .resource_group(flavor_quotas("one", cpu="4", memory="1Gi"),
                               flavor_quotas("two", cpu="4", memory="1Gi")))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="1", memory="1Mi")
        w.affinity_in("type", "two")
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, snapshot.cluster_queues["cq"],
                           fixture_flavors()).assign()
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("two", FIT, -1),
                                 MEM: ("two", FIT, -1)}

    def test_node_affinity_ored_terms_fit_any_flavor(self):
        cqw = (ClusterQueueWrapper("cq")
               .resource_group(flavor_quotas("one", cpu="4"),
                               flavor_quotas("two", cpu="4")))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="1")
        spec = w.wl.spec.pod_sets[0].template.spec
        spec.affinity = Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(node_selector_terms=[
                NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
                    key="ignored2", operator="In", values=["bar"])]),
                NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
                    key="cpuType", operator="In", values=["two"])]),
            ])))
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, snapshot.cluster_queues["cq"],
                           fixture_flavors()).assign()
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("one", FIT, 0)}

    def test_doesnt_fit_node_affinity(self):
        cqw = (ClusterQueueWrapper("cq")
               .resource_group(flavor_quotas("one", cpu="4"),
                               flavor_quotas("two", cpu="4")))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="1")
        w.affinity_in("type", "three")
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, snapshot.cluster_queues["cq"],
                           fixture_flavors()).assign()
        assert a.representative_mode() == NO_FIT
        reasons = a.pod_sets[0].reasons
        assert any("one" in r and "affinity" in r for r in reasons)
        assert any("two" in r and "affinity" in r for r in reasons)

    def test_multiple_specs_fit_different_flavors(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="4"),
                            flavor_quotas("two", cpu="10")),
            [dict(name="driver", count=1, cpu="5"),
             dict(name="worker", count=1, cpu="3")])
        assert a.representative_mode() == FIT
        assert flavors_of(a, 0) == {CPU: ("two", FIT, -1)}
        assert flavors_of(a, 1) == {CPU: ("one", FIT, 0)}

    def test_multiple_specs_fits_borrowing(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("default", cpu=("2", "98"),
                                          memory="2Gi")),
            [dict(name="driver", count=1, cpu="4", memory="1Gi"),
             dict(name="worker", count=1, cpu="6", memory="4Gi")],
            cohort_requestable={("default", CPU): "200",
                                ("default", MEM): "200Gi"})
        assert a.representative_mode() == FIT
        assert a.borrowing
        assert flavors_of(a, 0) == {CPU: ("default", FIT, -1),
                                    MEM: ("default", FIT, -1)}
        assert usage_of(a) == frq({("default", CPU): "10",
                                   ("default", MEM): "5Gi"})

    def test_not_enough_space_to_borrow(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("one", cpu="1")),
            [dict(count=1, cpu="2")],
            cohort_requestable={("one", CPU): "10"},
            cohort_usage={("one", CPU): "9"})
        assert a.representative_mode() == NO_FIT
        assert any("cohort" in r for r in a.pod_sets[0].reasons)

    def test_past_max_can_preempt_in_cq(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("one", cpu=("2", "8"))),
            [dict(count=1, cpu="2")],
            cq_usage={("one", CPU): "9"},
            cohort_requestable={("one", CPU): "100"},
            cohort_usage={("one", CPU): "9"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("one", PREEMPT, -1)}

    def test_past_min_can_preempt_in_cq(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="2")),
            [dict(count=1, cpu="2")],
            cq_usage={("one", CPU): "1"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("one", PREEMPT, -1)}

    def test_past_min_can_preempt_in_cohort_and_cq(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("one", cpu="3")),
            [dict(count=1, cpu="2")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "10"},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("one", PREEMPT, -1)}

    def test_can_only_preempt_flavors_matching_affinity(self):
        cqw = (ClusterQueueWrapper("cq")
               .resource_group(flavor_quotas("one", cpu="4"),
                               flavor_quotas("two", cpu="4")))
        cache = Cache()
        for f in fixture_flavors().values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(cqw.obj())
        snapshot = cache.snapshot()
        cq_snap = snapshot.cluster_queues["cq"]
        cq_snap.resource_node.usage = frq({("one", CPU): "3",
                                           ("two", CPU): "3"})
        w = WorkloadWrapper("wl")
        w.pod_set(count=1, cpu="2")
        w.node_selector("type", "two")
        info = wlpkg.Info(w.obj(), cluster_queue="cq")
        a = FlavorAssigner(info, cq_snap, fixture_flavors()).assign()
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a) == {CPU: ("two", PREEMPT, -1)}

    def test_each_podset_preempts_a_different_flavor(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="4"),
                            flavor_quotas("tainted", cpu="10")),
            [dict(name="launcher", count=1, cpu="2"),
             dict(name="workers", count=10, cpu="1",
                  _tolerate_spot=True)],
            cq_usage={("one", CPU): "3", ("tainted", CPU): "3"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a, 0) == {CPU: ("one", PREEMPT, -1)}
        assert flavors_of(a, 1) == {CPU: ("tainted", PREEMPT, -1)}
        assert usage_of(a) == frq({("one", CPU): "2",
                                   ("tainted", CPU): "10"})

    def test_resource_not_listed_in_cq(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("one", cpu="4")),
            [dict(count=1, **{GPU: "2"})])
        assert a.representative_mode() == NO_FIT
        assert a.pod_sets[0].flavors is None

    def test_num_pods_fit(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", pods="3", cpu="10")),
            [dict(count=3, cpu="1")])
        assert a.representative_mode() == FIT
        assert flavors_of(a) == {CPU: ("default", FIT, -1),
                                 RESOURCE_PODS: ("default", FIT, -1)}
        assert usage_of(a) == {FlavorResource("default", RESOURCE_PODS): 3,
                               FlavorResource("default", CPU): 3000}

    def test_num_pods_dont_fit(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", pods="2", cpu="10")),
            [dict(count=3, cpu="1")])
        assert a.representative_mode() == NO_FIT

    def test_with_reclaimable_pods(self):
        a = run_assign(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", pods="3", cpu="10")),
            [dict(name="main", count=5, cpu="1")],
            reclaimable={"main": 2})
        assert a.representative_mode() == FIT
        assert a.pod_sets[0].count == 3
        assert usage_of(a) == {FlavorResource("default", RESOURCE_PODS): 3,
                               FlavorResource("default", CPU): 3000}

    # --- FlavorFungibility policies (flavorassigner_test.go:1223-1783) ---

    def _fungibility_cq(self, when_borrow=None, when_preempt=None,
                        quotas=None):
        cqw = ClusterQueueWrapper("cq")
        if when_borrow or when_preempt:
            cqw.flavor_fungibility(
                when_can_borrow=when_borrow or api.BORROW,
                when_can_preempt=when_preempt or api.TRY_NEXT_FLAVOR)
        cqw.resource_group(*(quotas or (
            flavor_quotas("one", pods="10", cpu="10"),
            flavor_quotas("two", pods="10", cpu="10"))))
        return cqw

    def test_preempt_before_try_next_flavor(self):
        a = run_assign(
            self._fungibility_cq(api.BORROW, api.PREEMPT),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a)[CPU] == ("one", PREEMPT, 0)
        assert flavors_of(a)[RESOURCE_PODS] == ("one", FIT, 0)

    def test_preempt_try_next_flavor(self):
        a = run_assign(
            self._fungibility_cq(),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert flavors_of(a)[CPU] == ("two", FIT, -1)

    def test_borrow_try_next_flavor_found_first(self):
        a = run_assign(
            self._fungibility_cq(
                api.TRY_NEXT_FLAVOR, api.TRY_NEXT_FLAVOR,
                quotas=(flavor_quotas("one", pods="10", cpu=("10", "1")),
                        flavor_quotas("two", pods="10", cpu="1")))
            .cohort("test-cohort"),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "11", ("one", RESOURCE_PODS): 10,
                                ("two", CPU): "1", ("two", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", FIT, -1)

    def test_borrow_try_next_flavor_found_second(self):
        a = run_assign(
            self._fungibility_cq(
                api.TRY_NEXT_FLAVOR, api.TRY_NEXT_FLAVOR,
                quotas=(flavor_quotas("one", pods="10", cpu=("10", "1")),
                        flavor_quotas("two", pods="10", cpu="10")))
            .cohort("test-cohort"),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "11", ("one", RESOURCE_PODS): 10,
                                ("two", CPU): "10", ("two", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert not a.borrowing
        assert flavors_of(a)[CPU] == ("two", FIT, -1)

    def test_borrow_before_try_next_flavor(self):
        a = run_assign(
            self._fungibility_cq(
                quotas=(flavor_quotas("one", pods="10", cpu=("10", "1")),
                        flavor_quotas("two", pods="10", cpu="10")))
            .cohort("test-cohort"),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "11", ("one", RESOURCE_PODS): 10,
                                ("two", CPU): "10", ("two", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", FIT, 0)

    def test_borrow_while_preempt_when_can_borrow(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                        borrow_within_cohort=api.BorrowWithinCohort(
                            policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
            .flavor_fungibility(when_can_borrow=api.BORROW,
                                when_can_preempt=api.PREEMPT)
            .resource_group(flavor_quotas("one", cpu=("0", "12")),
                            flavor_quotas("two", cpu="12")),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "12", ("two", CPU): "12"},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == PREEMPT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", PREEMPT, 0)

    def test_borrow_while_preempt_no_borrowing_limit(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                        borrow_within_cohort=api.BorrowWithinCohort(
                            policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
            .flavor_fungibility(when_can_borrow=api.BORROW,
                                when_can_preempt=api.PREEMPT)
            .resource_group(flavor_quotas("one", cpu="0"),
                            flavor_quotas("two", cpu="12")),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "12", ("two", CPU): "12"},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == PREEMPT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", PREEMPT, 0)

    def test_borrow_while_preempt_try_next_flavor(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                        borrow_within_cohort=api.BorrowWithinCohort(
                            policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
            .flavor_fungibility(when_can_borrow=api.TRY_NEXT_FLAVOR,
                                when_can_preempt=api.PREEMPT)
            .resource_group(flavor_quotas("one", cpu=("0", "12")),
                            flavor_quotas("two", cpu="12")),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "12", ("two", CPU): "12"},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == FIT
        assert flavors_of(a)[CPU] == ("two", FIT, -1)

    def test_borrowing_limit_exceeds_cohort_quota(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                        borrow_within_cohort=api.BorrowWithinCohort(
                            policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
            .resource_group(flavor_quotas("one", cpu=("0", "12"))),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "11"},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == NO_FIT

    def test_lend_try_next_flavor_found_second(self):
        a = run_assign(
            self._fungibility_cq(
                api.TRY_NEXT_FLAVOR, api.TRY_NEXT_FLAVOR,
                quotas=(flavor_quotas("one", pods="10",
                                      cpu=("10", None, "1")),
                        flavor_quotas("two", pods="10",
                                      cpu=("10", None, "0"))))
            .cohort("test-cohort"),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "11", ("one", RESOURCE_PODS): 10,
                                ("two", CPU): "10", ("two", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert flavors_of(a)[CPU] == ("two", FIT, -1)

    def test_lend_try_next_flavor_found_first(self):
        a = run_assign(
            self._fungibility_cq(
                api.TRY_NEXT_FLAVOR, api.TRY_NEXT_FLAVOR,
                quotas=(flavor_quotas("one", pods="10",
                                      cpu=("10", None, "1")),
                        flavor_quotas("two", pods="10",
                                      cpu=("1", None, "0"))))
            .cohort("test-cohort"),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "11", ("one", RESOURCE_PODS): 10,
                                ("two", CPU): "1", ("two", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "2"})
        assert a.representative_mode() == FIT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", FIT, -1)

    def test_quota_exhausted_can_preempt_in_cohort_and_cq(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .resource_group(flavor_quotas("one", pods="10",
                                          cpu=("10", None, "0"))),
            [dict(count=1, cpu="9")],
            cq_usage={("one", CPU): "2"},
            cohort_requestable={("one", CPU): "10",
                                ("one", RESOURCE_PODS): 10},
            cohort_usage={("one", CPU): "10"})
        assert a.representative_mode() == PREEMPT
        assert flavors_of(a)[CPU] == ("one", PREEMPT, -1)
        assert flavors_of(a)[RESOURCE_PODS] == ("one", FIT, -1)

    def test_fair_sharing_reclaim_any_stays_on_first_flavor(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
            .flavor_fungibility(when_can_borrow=api.BORROW,
                                when_can_preempt=api.PREEMPT)
            .resource_group(flavor_quotas("one", cpu="0"),
                            flavor_quotas("two", cpu="12")),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "12", ("two", CPU): "12"},
            cohort_usage={("one", CPU): "10"},
            fair=True)
        assert a.representative_mode() == PREEMPT
        assert a.borrowing
        assert flavors_of(a)[CPU] == ("one", PREEMPT, 0)

    def test_fair_sharing_reclaim_never_goes_to_second_flavor(self):
        a = run_assign(
            ClusterQueueWrapper("cq").cohort("test-cohort")
            .preemption(reclaim_within_cohort=api.PREEMPTION_NEVER)
            .flavor_fungibility(when_can_borrow=api.BORROW,
                                when_can_preempt=api.PREEMPT)
            .resource_group(flavor_quotas("one", cpu="0"),
                            flavor_quotas("two", cpu="12")),
            [dict(count=1, cpu="12")],
            cohort_requestable={("one", CPU): "12", ("two", CPU): "12"},
            cohort_usage={("one", CPU): "10"},
            fair=True)
        assert a.representative_mode() == FIT
        assert flavors_of(a)[CPU] == ("two", FIT, -1)


class TestReclaimBeforePriorityPreemption:
    """flavorassigner_test.go:1981-2131: with whenCanPreempt=TryNextFlavor
    the assigner prefers a flavor where reclaim (not in-CQ priority
    preemption) is possible."""

    def _run(self, requests, test_usage, other_usage, fungibility=None):
        flavors = {n: make_flavor(n) for n in ("uno", "due", "tre")}
        test_cq = (ClusterQueueWrapper("test-cq").cohort("cohort")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                       reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY))
        if fungibility is None:
            test_cq.flavor_fungibility(when_can_preempt=api.TRY_NEXT_FLAVOR)
        else:
            test_cq.flavor_fungibility(when_can_preempt=fungibility)
        qs = []
        for n in ("uno", "due", "tre"):
            qs.append(api.FlavorQuotas(name=n, resources=[
                api.ResourceQuota(name="compute", nominal_quota=10),
                api.ResourceQuota(name="gpu", nominal_quota=10)]))
        test_cq.cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["compute", "gpu"], flavors=qs))

        other_cq = ClusterQueueWrapper("other-cq").cohort("cohort")
        zeros = []
        for n in ("uno", "due", "tre"):
            zeros.append(api.FlavorQuotas(name=n, resources=[
                api.ResourceQuota(name="compute", nominal_quota=0),
                api.ResourceQuota(name="gpu", nominal_quota=0)]))
        other_cq.cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["compute", "gpu"], flavors=zeros))

        cache = Cache()
        for f in flavors.values():
            cache.add_or_update_resource_flavor(f)
        cache.add_cluster_queue(test_cq.obj())
        cache.add_cluster_queue(other_cq.obj())
        snapshot = cache.snapshot()
        snapshot.cluster_queues["other-cq"].add_usage(
            {FlavorResource(f, r): q for (f, r), q in other_usage.items()})
        test_snap = snapshot.cluster_queues["test-cq"]
        test_snap.add_usage(
            {FlavorResource(f, r): q for (f, r), q in test_usage.items()})

        w = WorkloadWrapper("wl")
        w.pod_set(count=1, **requests)
        info = wlpkg.Info(w.obj(), cluster_queue="test-cq")

        def oracle(cq_, wl_, fr, q):
            return not cq_.borrowing_with(fr, q)

        a = FlavorAssigner(info, test_snap, flavors, oracle=oracle).assign()
        return (a.representative_mode(),
                {res: fa.name
                 for res, fa in (a.pod_sets[0].flavors or {}).items()})

    def test_select_first_flavor_which_fits(self):
        mode, flv = self._run({"gpu": 10}, {("uno", "gpu"): 1},
                              {("due", "gpu"): 1})
        assert mode == FIT and flv == {"gpu": "tre"}

    def test_select_first_flavor_where_reclaim_possible(self):
        mode, flv = self._run({"gpu": 10}, {("uno", "gpu"): 1},
                              {("due", "gpu"): 1, ("tre", "gpu"): 1})
        assert mode == PREEMPT and flv == {"gpu": "due"}

    def test_select_first_flavor_when_fungibility_disabled(self):
        mode, flv = self._run({"gpu": 10}, {("uno", "gpu"): 1},
                              {("due", "gpu"): 1, ("tre", "gpu"): 1},
                              fungibility=api.PREEMPT)
        assert mode == PREEMPT and flv == {"gpu": "uno"}

    def test_select_first_flavor_where_priority_preemption_possible(self):
        mode, flv = self._run({"gpu": 10},
                              {("uno", "gpu"): 1, ("due", "gpu"): 1,
                               ("tre", "gpu"): 1}, {})
        assert mode == PREEMPT and flv == {"gpu": "uno"}

    def test_select_second_flavor_where_reclaim_possible_compute_fits(self):
        mode, flv = self._run(
            {"gpu": 10, "compute": 10},
            {("uno", "gpu"): 1, ("uno", "compute"): 1,
             ("due", "compute"): 1},
            {("due", "gpu"): 1, ("tre", "gpu"): 1})
        assert mode == PREEMPT and flv == {"gpu": "tre", "compute": "tre"}
