"""Cycle flight recorder + operator debug surface (kueue_tpu/obs).

Covers: the recorder's ring/disabled-path contracts, well-formed traces
from a full KueueManager run (the tier-1 smoke the ISSUE asks for),
reconciliation between per-trace span sums and the cycle_phase_seconds
histograms (acceptance criterion), solver-phase spans on the device
route, fault annotations, and the status producers the /debug/*
endpoints and Dumper share.

ISSUE 14 additions: the workload journey ledger (causally-stamped span
timelines, LRU bounds under a 50k-workload storm, exemplar retention,
reconcile-by-construction with the wait-time histograms, the
requeue-amplification metric, burn rates) and the aging watch
(EWMA-slope trend monitors flagging injected slow leaks while staying
silent on clean runs).
"""

import io
import math

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.manager import KueueManager
from kueue_tpu.obs import (
    AgingWatch,
    CycleTrace,
    DebugEndpoints,
    FlightRecorder,
    JourneyLedger,
    TrendMonitor,
    arena_status,
    breaker_status,
    router_status,
)

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


def make_mgr(clock, solver=None, cfg=None):
    m = KueueManager(cfg=cfg, clock=clock, solver=solver)
    m.store.create(make_flavor("default"))
    m.store.create(ClusterQueueWrapper("cq").resource_group(
        flavor_quotas("default", cpu=4)).obj())
    m.store.create(make_local_queue("lq", "default", "cq"))
    m.run_until_idle()
    return m


def submit_n(mgr, n, prefix="w"):
    for i in range(n):
        mgr.store.create(WorkloadWrapper(f"{prefix}{i}").queue("lq")
                         .creation(100 + i).request("cpu", "1").obj())


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = FlightRecorder(capacity=3)
        for i in range(10):
            tr = rec.begin_cycle(i)
            rec.span("apply", tr.t0, 0.001)
            rec.finish(tr)
        traces = rec.traces()
        assert len(traces) == 3
        assert [t.cycle_id for t in traces] == [7, 8, 9]
        assert rec.cycles_recorded == 10

    def test_disabled_records_nothing(self):
        rec = FlightRecorder(enabled=False)
        assert rec.begin_cycle(1) is None
        rec.span("encode", 0.0, 1.0)     # no open trace: no-op
        rec.annotate("fault", "boom")
        rec.finish(None)
        assert rec.traces() == [] and rec.last() is None

    def test_span_offsets_and_phase_sums(self):
        rec = FlightRecorder()
        tr = rec.begin_cycle(7)
        rec.span("encode", tr.t0 + 0.010, 0.005)
        rec.span("dispatch", tr.t0 + 0.015, 0.020)
        rec.span("dispatch.scatter", tr.t0 + 0.016, 0.004)  # nested
        rec.span("encode", tr.t0 + 0.040, 0.001)
        rec.finish(tr)
        sums = tr.phase_sums()
        # nested (dotted) spans are inside their parent: not re-summed
        assert sums == pytest.approx({"encode": 0.006, "dispatch": 0.020})
        d = tr.to_dict()
        assert d["cycle"] == 7
        names = [s["name"] for s in d["spans"]]
        assert names == ["encode", "dispatch", "dispatch.scatter", "encode"]
        assert d["spans"][0]["start_ms"] == pytest.approx(10.0, abs=0.01)

    def test_slowest_ordering(self):
        rec = FlightRecorder()
        durations = [0.03, 0.01, 0.05, 0.02]
        for i, dur in enumerate(durations):
            tr = rec.begin_cycle(i)
            rec.finish(tr)
            tr.duration_s = dur  # pin: finish stamps real elapsed time
        slow = rec.slowest(2)
        assert [t.cycle_id for t in slow] == [2, 0]

    def test_unfinished_trace_discarded_on_next_begin(self):
        rec = FlightRecorder()
        rec.begin_cycle(1)          # never finished (cycle died)
        tr2 = rec.begin_cycle(2)
        rec.span("apply", tr2.t0, 0.001)
        rec.finish(tr2)
        assert [t.cycle_id for t in rec.traces()] == [2]

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)


class TestManagerTraces:
    """Tier-1 smoke: a full KueueManager run yields well-formed traces."""

    def test_cpu_run_produces_traces(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        assert traces, "no cycle traces recorded"
        for t in traces:
            assert t.route == "cpu-forced"  # no solver configured
            assert t.regime in ("fit", "preempt")
            assert t.duration_s > 0
            assert t.admitted is not None and t.admitted >= 0
            names = {n for n, _s, _d in t.spans}
            assert {"snapshot", "nominate", "apply", "requeue"} <= names
            for _name, start, dur in t.spans:
                assert start >= 0 and dur >= 0
                assert start + dur <= t.duration_s + 1e-6
        assert sum(t.admitted for t in traces) == 4  # 4-cpu quota

    def test_sums_reconcile_with_histograms(self, clock):
        """Acceptance criterion: per-cycle span sums == the
        cycle_phase_seconds histogram totals (same producer)."""
        mgr = make_mgr(clock)
        submit_n(mgr, 5)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        want: dict = {}
        for t in traces:
            for phase, secs in t.phase_sums().items():
                want[phase] = want.get(phase, 0.0) + secs
        h = mgr.metrics.cycle_phase_seconds
        pi = h.label_names.index("phase")
        got: dict = {}
        for key, (_counts, total, _n) in h.series.items():
            got[key[pi]] = got.get(key[pi], 0.0) + total
        assert set(got) == set(want)
        for phase, secs in want.items():
            assert got[phase] == pytest.approx(secs, rel=1e-9)

    def test_cycle_heads_and_breaker_gauge(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        assert mgr.metrics.cycle_heads.count(route="cpu-forced") > 0
        assert mgr.metrics.breaker_state.value() == 0  # closed

    def test_recorder_disabled_by_config(self, clock):
        cfg = cfgpkg.Configuration()
        cfg.observability.flight_recorder_enable = False
        mgr = make_mgr(clock, cfg=cfg)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        assert mgr.scheduler.recorder.traces() == []
        # admissions unaffected; histograms stay dark, the breaker
        # gauge still updates (a metrics concern, not a tracing one)
        assert mgr.metrics.cycle_heads.count(route="cpu-forced") == 0
        assert mgr.metrics.breaker_state.value() == 0
        assert math.isnan(mgr.metrics.phase_percentile("apply", 0.5))

    def test_capacity_config(self, clock):
        cfg = cfgpkg.Configuration()
        cfg.observability.flight_recorder_capacity = 2
        mgr = make_mgr(clock, cfg=cfg)
        submit_n(mgr, 8)
        mgr.schedule_until_settled()
        assert len(mgr.scheduler.recorder.traces()) <= 2
        assert mgr.scheduler.recorder.cycles_recorded > 2

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            cfgpkg.load({"observability": {"flightRecorderCapacity": 0}})


class TestSolverTraces:
    def _solver_mgr(self, clock):
        from kueue_tpu.solver import BatchSolver
        cfg = cfgpkg.Configuration()
        cfg.solver.min_heads = 0
        cfg.solver.routing = "always"
        cfg.solver.pipeline = False
        return make_mgr(clock, solver=BatchSolver(), cfg=cfg)

    def test_device_route_spans(self, clock):
        mgr = self._solver_mgr(clock)
        submit_n(mgr, 4)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        dev = [t for t in traces if t.route == "device"]
        assert dev, [t.route for t in traces]
        names = {n for t in dev for n, _s, _d in t.spans}
        # solver phases flow through the same trace as scheduler phases
        assert {"encode", "route", "snapshot", "apply"} <= names
        assert {"dispatch", "fetch", "decode"} <= names
        # phase_s cumulative totals (perf artifacts) kept in lockstep
        phase_s = mgr.scheduler.solver.phase_s
        for phase in ("encode", "dispatch", "fetch", "decode"):
            span_total = sum(d for t in traces for n, _s, d in t.spans
                             if n == phase)
            assert span_total == pytest.approx(phase_s[phase], rel=1e-9)

    def test_fault_annotation_lands_in_trace(self, clock):
        from kueue_tpu.resilience import faultinject
        from kueue_tpu.resilience.faultinject import RAISE, FaultInjector
        mgr = self._solver_mgr(clock)
        submit_n(mgr, 3)
        injector = FaultInjector(
            {faultinject.SITE_DISPATCH: {0: RAISE}})
        faultinject.install(injector)
        try:
            mgr.schedule_until_settled()
        finally:
            faultinject.uninstall()
        faulted = [t for t in mgr.scheduler.recorder.traces() if t.faults]
        assert faulted
        notes = [a for t in faulted for a in t.annotations
                 if a["kind"] == "fault"]
        assert notes and notes[0]["site"] in ("solve", "dispatch")
        assert "breaker" in notes[0]


def _mk_info(i: int, cq: str = "cq"):
    """A minimal real Info for direct ledger drives."""
    from kueue_tpu.core import workload as wlpkg
    wl = (WorkloadWrapper(f"storm{i}").queue("lq").creation(100 + i)
          .request("cpu", "1").obj())
    info = wlpkg.Info(wl)
    info.cluster_queue = cq
    return info


class TestJourneyLedger:
    """Direct ledger drives: LRU bounds, repeat collapse, exemplars,
    burn rates, close() leak contract."""

    def test_validation(self):
        with pytest.raises(ValueError):
            JourneyLedger(capacity=0)
        with pytest.raises(ValueError):
            JourneyLedger(exemplars=0)
        with pytest.raises(ValueError):
            cfgpkg.load({"observability": {"journeyLedgerCapacity": 0}})
        with pytest.raises(ValueError):
            cfgpkg.load({"observability": {"journeyExemplars": 0}})

    def test_lru_eviction_under_50k_storm(self):
        """Acceptance: LRU eviction exercised under a 50k-workload
        storm — the active set never exceeds capacity and the overflow
        is counted, not leaked."""
        from kueue_tpu.metrics import Registry
        led = JourneyLedger(capacity=1000, metrics=Registry())
        led.begin_cycle(1, (1, 0, 0))
        n = 50_000
        for i in range(n):
            led.note_queue_delta("upsert", f"default/storm{i}",
                                 _mk_info(i))
        st = led.status()
        assert st["active"] == 1000
        assert st["started"] == n
        assert st["lru_evictions"] == n - 1000
        assert led.metrics.journey_ledger_evictions_total.value() \
            == n - 1000
        led.close()
        assert led.retained == 0

    def test_repeat_collapse_bounds_flood_timelines(self):
        """A 40-cycle requeue loop reads as ONE span covering cycles
        [n, n+39], not 40 allocations — and requeues_total still counts
        every event."""
        from kueue_tpu.queue import RequeueReason
        led = JourneyLedger()
        info = _mk_info(0)
        led.begin_cycle(5, (1, 0, 0))
        led.note_queue_delta("upsert", info.key, info)
        for c in range(40):
            led.begin_cycle(5 + c, (1, 0, 0))
            led.requeued(info, "nominated",
                         RequeueReason.FAILED_AFTER_NOMINATION,
                         "Workload no longer fits")
        j = led.journey(info.key)
        kinds = [s.kind for s in j.spans]
        assert kinds == ["queued", "requeued"]
        f = j.spans[-1].fields
        assert f["repeats"] == 40
        assert j.spans[-1].cycle == 5 and f["last_cycle"] == 44
        assert j.requeues == 40 and led.requeues_total == 40
        # a DIFFERENT reason breaks the collapse
        led.requeued(info, "nominated", RequeueReason.GENERIC, "other")
        assert [s.kind for s in led.journey(info.key).spans] \
            == ["queued", "requeued", "requeued"]

    def test_mid_cycle_arrival_stays_monotone(self):
        """A workload created AFTER the cycle's begin stamp reuses the
        cycle-start timestamp for its requeue spans: the append-order
        clamp keeps the timeline monotone (no false 'out of time
        order' gate failures under a real clock)."""
        from kueue_tpu.api.meta import FakeClock
        from kueue_tpu.queue import RequeueReason
        clk = FakeClock(100.0)
        led = JourneyLedger(clock=clk)
        led.begin_cycle(1, (1, 0, 0))       # _cycle_t = 100.0
        wl = (WorkloadWrapper("late").queue("lq").creation(100.5)
              .request("cpu", "1").obj())
        from kueue_tpu.core import workload as wlpkg
        info = wlpkg.Info(wl)
        info.cluster_queue = "cq"
        led.note_queue_delta("upsert", info.key, info)   # queued@100.5
        led.requeued(info, "nominated", RequeueReason.GENERIC)
        j = led.journey(info.key)
        assert [s.t for s in j.spans] == [100.5, 100.5]  # clamped
        clk.advance(10.0)
        led.quota_reserved(wl, "cq", 10.0, admitted=True)
        ok, why = led.slowest(1)[0].timeline_complete()
        assert ok, why

    def test_span_cap_keeps_arrival_anchor(self):
        from kueue_tpu.obs.journey import MAX_SPANS_PER_JOURNEY
        from kueue_tpu.queue import RequeueReason
        led = JourneyLedger()
        info = _mk_info(0)
        led.begin_cycle(1, (1, 0, 0))
        led.note_queue_delta("upsert", info.key, info)
        for c in range(MAX_SPANS_PER_JOURNEY + 50):
            led.begin_cycle(1 + c, (1, 0, 0))
            # distinct messages defeat the collapse on purpose
            led.requeued(info, "nominated", RequeueReason.GENERIC,
                         f"msg{c}")
        j = led.journey(info.key)
        assert len(j.spans) == MAX_SPANS_PER_JOURNEY
        assert j.spans[0].kind == "queued"    # the anchor survives
        assert j.dropped_spans == 51

    def test_lru_evicted_journey_resumes_with_class_and_anchor(self):
        """Review-pass contract: past the capacity bound, a journey
        re-created mid-life recovers its SLI class from the seal hook
        (the TTA folds into the RIGHT histogram) and its first span is
        marked ``resumed`` so timeline_complete stays honest instead
        of minting a false violation."""
        from kueue_tpu.metrics import Registry
        from kueue_tpu.obs.journey import CLASS_LABEL
        from kueue_tpu.queue import RequeueReason
        reg = Registry()
        led = JourneyLedger(capacity=1, metrics=reg)
        led.begin_cycle(1, (1, 0, 0))
        prod = _mk_info(0)
        prod.obj.metadata.labels = {CLASS_LABEL: "prod"}
        led.note_queue_delta("upsert", prod.key, prod)
        # a second arrival LRU-evicts prod's journey
        led.note_queue_delta("upsert", "default/other", _mk_info(1))
        assert led.lru_evictions == 1
        # prod resumes mid-life through the requeue hook...
        led.requeued(prod, "nominated", RequeueReason.GENERIC)
        # ...and seals with its real class recovered from the workload
        led.quota_reserved(prod.obj, "cq", 12.0, admitted=True)
        assert reg.journey_tta_seconds.count(cls="prod") == 1
        assert reg.journey_tta_seconds.count(cls="standard") == 0
        j = led.slowest(1)[0]
        assert j.spans[0].fields.get("resumed") is True
        ok, why = j.timeline_complete()
        assert ok, why

    def test_exemplars_keep_k_slowest_and_violations(self):
        led = JourneyLedger(exemplars=2)
        led.set_objectives({"standard": 25.0})
        led.begin_cycle(1, (1, 0, 0))
        ttas = [10.0, 50.0, 5.0, 30.0, 20.0]
        for i, tta in enumerate(ttas):
            info = _mk_info(i)
            led.note_queue_delta("upsert", info.key, info)
            led.quota_reserved(info.obj, "cq", tta, admitted=True)
        slow = led.slowest()
        assert [j.tta_s for j in slow] == [50.0, 30.0]
        assert {j.tta_s for j in led.violations()} == {50.0, 30.0}
        assert led.journeys_completed == 5
        assert led.status()["active"] == 0   # sealed journeys fold out
        # burn rate moved: 2 violations of 5 with alpha 0.1
        assert led.burn_rates()["standard"] > 0

    def test_burn_rate_gauge_prices_objectives(self):
        from kueue_tpu.metrics import Registry
        from kueue_tpu.perf.checker import SLOSpec, journey_objectives
        reg = Registry()
        led = JourneyLedger(metrics=reg, error_budget=0.05,
                            burn_alpha=1.0)
        led.set_objectives(journey_objectives(
            SLOSpec(class_max_p99_tta_s={"standard": 10.0})))
        led.begin_cycle(1, (1, 0, 0))
        info = _mk_info(0)
        led.note_queue_delta("upsert", info.key, info)
        led.quota_reserved(info.obj, "cq", 99.0, admitted=True)  # violates
        # alpha=1: ewma == 1.0 -> burn = 1.0 / 0.05 = 20
        assert reg.slo_burn_rate.value(cls="standard") \
            == pytest.approx(20.0)
        info2 = _mk_info(1)
        led.note_queue_delta("upsert", info2.key, info2)
        led.quota_reserved(info2.obj, "cq", 1.0, admitted=True)  # ok
        assert reg.slo_burn_rate.value(cls="standard") \
            == pytest.approx(0.0)


class TestJourneyManager:
    """Full-manager journeys: the end-to-end acceptance contract."""

    def test_slowest_journey_answers_why_from_debug_journeys(self, clock):
        """Acceptance: from /debug/journeys alone, the slowest
        workload's timeline explains its admission — first span
        ``queued``, last an admission, every span stamped with cycle id
        + generation token, monotone — no gaps."""
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        for _ in range(8):
            mgr.schedule_once()
            clock.advance(5.0)
            # release quota so the backlog admits over several cycles
            from kueue_tpu.api import kueue as api
            from kueue_tpu.api.meta import Condition, set_condition
            from kueue_tpu.core import workload as wlpkg
            for wl in mgr.store.list("Workload"):
                if wlpkg.is_admitted(wl) and not wlpkg.is_finished(wl):
                    set_condition(wl.status.conditions, Condition(
                        type=api.WORKLOAD_FINISHED, status="True",
                        reason="Succeeded", message="done"), clock.now())
                    mgr.store.update(wl)
            mgr.run_until_idle()
        endpoints = DebugEndpoints(mgr.scheduler, mgr.metrics)
        payload = endpoints.handle("/debug/journeys", {"n": "1"})
        assert payload["completed"] == 6
        assert payload["unstamped_spans"] == 0
        slowest = payload["slowest"][0]
        assert slowest["tta_s"] > 0
        spans = slowest["spans"]
        assert spans[0]["kind"] == "queued"
        assert spans[-1]["kind"] in ("quota-reserved", "admitted")
        prev_c = None
        for s in spans:
            assert isinstance(s["cycle"], int)
            assert s["generation"], s
            if prev_c is not None:
                assert s["cycle"] >= prev_c
            prev_c = s["cycle"]
        # the ledger's own completeness predicate agrees
        j = mgr.journey_ledger.journey(slowest["workload"])
        ok, why = j.timeline_complete()
        assert ok, why

    def test_histograms_fed_from_sealed_journeys(self, clock):
        """Satellite regression: histogram totals == completed-journey
        count — one emission site, /metrics and /debug/journeys can
        never disagree."""
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        mgr.schedule_until_settled()
        led = mgr.journey_ledger
        adm_count = sum(
            s[2] for s in mgr.metrics.admission_wait_time.series.values())
        qr_count = sum(
            s[2] for s in
            mgr.metrics.quota_reserved_wait_time.series.values())
        tta_count = sum(
            s[2] for s in mgr.metrics.journey_tta_seconds.series.values())
        assert adm_count == led.journeys_completed == tta_count
        assert qr_count == led.quota_reservations
        assert adm_count == 4   # 4-cpu quota admits 4 of 6

    def test_requeue_amplification_flood(self, clock):
        """Satellite: a requeue flood drives requeues_per_admission —
        the gauge matches the ledger ratio and exceeds the clean
        baseline."""
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        # never finish anything: quota stays full after 4 admits, every
        # later popped head requeues
        for _ in range(6):
            mgr.schedule_once()
            clock.advance(5.0)
            # cohort flush so parked-inadmissible entries re-pop and
            # requeue again (the flood shape)
            mgr.queues.queue_inadmissible_workloads({"cq"})
        led = mgr.journey_ledger
        assert led.requeues_total > 0
        want = led.requeues_total / max(led.journeys_completed, 1)
        assert mgr.metrics.requeues_per_admission.value() \
            == pytest.approx(want)
        assert want > 0

    def test_eviction_reopens_journey(self, clock):
        """A sealed journey folds out of the active set; the eviction
        starts a successor anchored at ``evicted``, the re-queue
        appends its own ``queued`` span, and the re-admission seals a
        COMPLETE timeline (the review-pass contract for preemption-
        heavy storms)."""
        from kueue_tpu.api import kueue as api
        from kueue_tpu.api.meta import find_condition
        mgr = make_mgr(clock)
        submit_n(mgr, 1)
        mgr.schedule_until_settled()
        led = mgr.journey_ledger
        assert led.journeys_completed == 1
        # deactivate -> the eviction path stamps a successor journey
        wl = mgr.store.get("Workload", "default", "w0")
        wl.spec.active = False
        mgr.store.update(wl)
        mgr.run_until_idle()
        j = led.journey("default/w0")
        assert j is not None
        assert j.spans[0].kind == "evicted"   # post-admission anchor
        assert j.sealed_t is None             # re-opened
        # reactivate: the harness-side eviction completion + requeue
        clock.advance(5.0)
        wl = mgr.store.get("Workload", "default", "w0")
        from kueue_tpu.core import workload as wlpkg
        ev = find_condition(wl.status.conditions, api.WORKLOAD_EVICTED)
        wlpkg.unset_quota_reservation_with_condition(
            wl, "Pending", "evicted", clock.now())
        wlpkg.set_requeued_condition(wl, ev.reason, ev.message, False,
                                     clock.now())
        wl.spec.active = True
        mgr.store.update(wl)
        mgr.run_until_idle()
        mgr.schedule_until_settled()
        assert led.journeys_completed == 2    # the re-admission sealed
        j2 = led.journey("default/w0")
        kinds = [s.kind for s in j2.spans]
        assert kinds[0] == "evicted" and "queued" in kinds
        ok, why = j2.timeline_complete()
        assert ok, (why, kinds)

    def test_journeys_disabled_by_config(self, clock):
        cfg = cfgpkg.Configuration()
        cfg.observability.journey_enable = False
        mgr = make_mgr(clock, cfg=cfg)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        assert mgr.journey_ledger is None
        assert mgr.scheduler.journeys is None
        # the wait-time histograms keep their direct call sites
        adm = sum(s[2] for s in
                  mgr.metrics.admission_wait_time.series.values())
        assert adm == 3
        # /debug/journeys reports detached; ?wl= is a 404 (None)
        endpoints = DebugEndpoints(mgr.scheduler, mgr.metrics)
        assert endpoints.handle("/debug/journeys", {})["attached"] is False
        assert endpoints.handle("/debug/journeys", {"wl": "w0"}) is None

    def test_zero_retained_after_shutdown(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        mgr.schedule_until_settled()
        led = mgr.journey_ledger
        assert led.retained > 0
        mgr.shutdown(checkpoint=False)
        assert led.retained == 0


class TestAgingWatch:
    def test_monitor_flags_injected_leak_within_window(self):
        """Acceptance: a +1/sample leak flips the verdict to leaking
        within warmup + window samples; the clean source never does."""
        mon = TrendMonitor("leak", slope_threshold=0.05, window=12,
                           warmup=8)
        for _ in range(40):
            mon.sample(3.0)          # clean: flat
        assert mon.verdict() == "ok"
        for i in range(8 + 12):      # leak: +1 per sample
            mon.sample(3.0 + i)
        assert mon.verdict() == "leaking"

    def test_clean_sawtooth_stays_ok(self):
        """A compacting WAL shape (grow then drop) must not flag on
        slope — the EWMA absorbs the sawtooth."""
        mon = TrendMonitor("wal", slope_threshold=None, bound=200.0)
        v = 0.0
        for i in range(100):
            v = 0.0 if i % 10 == 0 else v + 10.0
            mon.sample(v)
        assert mon.verdict() == "ok"
        mon.sample(500.0)            # compaction stall: bound trips
        assert mon.verdict() == "over-bound"

    def test_watch_guards_dead_sources(self):
        watch = AgingWatch()

        def boom():
            raise RuntimeError("dead source")
        watch.add("bad", boom, slope_threshold=0.1)
        watch.sample()
        assert watch.monitors["bad"].sample_errors == 1
        assert watch.failing == []

    def test_manager_handout_leak_flagged_clean_run_silent(self, clock):
        """Acceptance: the aging watch flags a scripted handout leak
        within its EWMA window while staying silent on the clean run.
        Cycles sample the watch at each seal; the leak takes one
        un-released snapshot per cycle."""
        mgr = make_mgr(clock)
        mon = mgr.aging_watch.monitors["live_handouts"]

        def cycles(n, leak=False):
            for i in range(n):
                submit_n(mgr, 1, prefix=f"c{mgr.scheduler.attempt_count}-")
                mgr.schedule_once()
                clock.advance(1.0)
                if leak:
                    mgr.cache.snapshot()   # taken, never released
        cycles(mon.warmup + mon.window + 4)
        assert mon.verdict() == "ok", mon.status()
        assert mgr.aging_watch.failing == []
        leak_start = mon.samples
        cycles(mon.warmup + mon.window + 8, leak=True)
        assert mon.verdict() == "leaking", mon.status()
        assert "live_handouts" in mgr.aging_watch.failing
        # flagged within the EWMA window (bounded detection latency)
        assert mon.samples - leak_start <= mon.warmup + mon.window + 8

    def test_verdict_walks_warming_ok_growing_leaking(self):
        """The full verdict ladder in one monitor's life: warming
        through the warmup, ok while flat, growing once the slope EWMA
        crosses the threshold, leaking only after WINDOW consecutive
        above-threshold samples."""
        mon = TrendMonitor("walk", slope_threshold=0.05, alpha=0.5,
                           window=6, warmup=4)
        for _ in range(4):
            mon.sample(10.0)
            assert mon.verdict() == "warming"
        mon.sample(10.0)
        assert mon.verdict() == "ok"
        v, seen = 10.0, []
        while mon.verdict() != "leaking":
            v += 1.0
            mon.sample(v)
            seen.append(mon.verdict())
        # never leaking before the window sustained it — every
        # intermediate verdict is growing
        assert seen[:-1] == ["growing"] * (len(seen) - 1)
        assert mon.sustained >= mon.window

    def test_over_bound_outranks_every_other_verdict(self):
        """A level past the hard bound is a violation NOW — even
        during warmup (a fresh process may legitimately grow, but
        never past the ceiling), and regardless of slope."""
        mon = TrendMonitor("ceil", slope_threshold=0.05, bound=100.0,
                           window=6, warmup=4)
        mon.sample(150.0)
        assert mon.samples <= mon.warmup     # still warming by count
        assert mon.verdict() == "over-bound"
        mon.sample(50.0)                     # back under: re-judged
        assert mon.verdict() == "warming"

    def test_slope_ewma_decays_back_to_ok_after_leak(self):
        """Verdicts are live, not latched: once the growth stops, the
        slope EWMA decays below the threshold and a leaking monitor
        returns to ok — the soak gate reads the END state."""
        mon = TrendMonitor("decay", slope_threshold=0.05, alpha=0.5,
                           window=4, warmup=2)
        v = 0.0
        for _ in range(12):
            v += 1.0
            mon.sample(v)
        assert mon.verdict() == "leaking"
        flats = 0
        while mon.verdict() != "ok":
            mon.sample(v)
            flats += 1
            assert flats < 20, "slope EWMA never decayed"
        assert mon.sustained == 0

    def test_dead_source_counted_per_pass_never_failing(self):
        """A raising source is counted on EVERY sampling pass and
        skipped — it must neither kill the cycle nor read as a leak —
        while healthy monitors alongside keep sampling."""
        watch = AgingWatch()

        def boom():
            raise RuntimeError("dead source")
        watch.add("bad", boom, slope_threshold=0.1)
        watch.add("good", lambda: 1.0, slope_threshold=0.1, warmup=0)
        for _ in range(3):
            watch.sample()
        assert watch.monitors["bad"].sample_errors == 3
        assert watch.monitors["bad"].samples == 0
        assert watch.monitors["good"].samples == 3
        assert watch.failing == []
        assert watch.gate()["ok"] is True

    def test_gate_contract_and_status_carry_it_verbatim(self):
        """gate() is the one machine-readable verdict every consumer
        (soak harness, scenario counters, /debug/aging) shares:
        warming/growing count green, leaking flips ok to False, and
        status() embeds the same dict."""
        watch = AgingWatch()
        watch.add("flat", lambda: 5.0, slope_threshold=0.1, warmup=0)
        leak = {"v": 0.0}

        def leaking():
            leak["v"] += 1.0
            return leak["v"]
        watch.add("leak", leaking, slope_threshold=0.05, alpha=0.5,
                  window=4, warmup=2)
        watch.sample()
        g = watch.gate()
        assert set(g) == {"ok", "failing", "verdicts"}
        assert g["ok"] is True and g["failing"] == []    # warming=green
        for _ in range(12):
            watch.sample()
        g = watch.gate()
        assert g["ok"] is False and g["failing"] == ["leak"]
        assert g["verdicts"]["leak"] == "leaking"
        assert g["verdicts"]["flat"] == "ok"
        assert watch.status()["gate"] == g

    def test_aging_endpoint_payload(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 2)
        mgr.schedule_until_settled()
        endpoints = DebugEndpoints(mgr.scheduler, mgr.metrics)
        payload = endpoints.handle("/debug/aging", {})
        assert payload["attached"] is True
        assert payload["samples_taken"] > 0
        assert "live_handouts" in payload["monitors"]
        assert "rss_kb" in payload["monitors"]
        assert "requeue_amplification" in payload["monitors"]
        assert "generation" in payload


class TestStatusSurface:
    def test_breaker_status(self, clock):
        mgr = make_mgr(clock)
        st = breaker_status(mgr.scheduler)
        assert st["state"] == "closed" and st["route"] == "device"
        assert st["next_probe_in_s"] == 0.0
        mgr.scheduler.breaker.record_fault(clock.now())
        mgr.scheduler.breaker.record_fault(clock.now())
        mgr.scheduler.breaker.record_fault(clock.now())
        st = breaker_status(mgr.scheduler)
        assert st["state"] == "open" and st["route"] == "cpu-breaker"
        assert st["next_probe_in_s"] > 0
        assert st["trips"] == 1

    def test_router_status(self, clock):
        mgr = make_mgr(clock)
        mgr.scheduler.solver_routing = "adaptive"
        mgr.scheduler._cycle_regime = "fit"
        mgr.scheduler._route_record("cpu", 10, 0.5)
        mgr.scheduler._route_record("cpu", 20, 0.5)
        rt = router_status(mgr.scheduler)
        assert rt["routing"] == "adaptive"
        info = rt["regimes"]["cpu/fit"]
        assert len(info["samples"]) == 2
        assert info["median_rate_per_s"] == pytest.approx(40.0)

    def test_arena_status(self, clock):
        from kueue_tpu.solver import BatchSolver
        cfg = cfgpkg.Configuration()
        cfg.solver.min_heads = 0
        mgr = make_mgr(clock, solver=BatchSolver(), cfg=cfg)
        submit_n(mgr, 4)
        mgr.schedule_until_settled()
        st = arena_status(mgr.scheduler.solver)
        assert st["bound"] is True
        assert st["cap"] >= st["occupied"] >= 0
        assert st["encoded_rows"] > 0

    def test_dumper_includes_solver_plane(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        buf = io.StringIO()
        mgr.dumper(out=buf).write()
        text = buf.getvalue()
        assert "-- breaker --" in text and "state=closed" in text
        assert "-- router --" in text
        assert "-- last cycle trace --" in text
        assert "span snapshot" in text
