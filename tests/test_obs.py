"""Cycle flight recorder + operator debug surface (kueue_tpu/obs).

Covers: the recorder's ring/disabled-path contracts, well-formed traces
from a full KueueManager run (the tier-1 smoke the ISSUE asks for),
reconciliation between per-trace span sums and the cycle_phase_seconds
histograms (acceptance criterion), solver-phase spans on the device
route, fault annotations, and the status producers the /debug/*
endpoints and Dumper share.
"""

import io
import math

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.manager import KueueManager
from kueue_tpu.obs import (
    CycleTrace,
    FlightRecorder,
    arena_status,
    breaker_status,
    router_status,
)

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


def make_mgr(clock, solver=None, cfg=None):
    m = KueueManager(cfg=cfg, clock=clock, solver=solver)
    m.store.create(make_flavor("default"))
    m.store.create(ClusterQueueWrapper("cq").resource_group(
        flavor_quotas("default", cpu=4)).obj())
    m.store.create(make_local_queue("lq", "default", "cq"))
    m.run_until_idle()
    return m


def submit_n(mgr, n, prefix="w"):
    for i in range(n):
        mgr.store.create(WorkloadWrapper(f"{prefix}{i}").queue("lq")
                         .creation(100 + i).request("cpu", "1").obj())


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = FlightRecorder(capacity=3)
        for i in range(10):
            tr = rec.begin_cycle(i)
            rec.span("apply", tr.t0, 0.001)
            rec.finish(tr)
        traces = rec.traces()
        assert len(traces) == 3
        assert [t.cycle_id for t in traces] == [7, 8, 9]
        assert rec.cycles_recorded == 10

    def test_disabled_records_nothing(self):
        rec = FlightRecorder(enabled=False)
        assert rec.begin_cycle(1) is None
        rec.span("encode", 0.0, 1.0)     # no open trace: no-op
        rec.annotate("fault", "boom")
        rec.finish(None)
        assert rec.traces() == [] and rec.last() is None

    def test_span_offsets_and_phase_sums(self):
        rec = FlightRecorder()
        tr = rec.begin_cycle(7)
        rec.span("encode", tr.t0 + 0.010, 0.005)
        rec.span("dispatch", tr.t0 + 0.015, 0.020)
        rec.span("dispatch.scatter", tr.t0 + 0.016, 0.004)  # nested
        rec.span("encode", tr.t0 + 0.040, 0.001)
        rec.finish(tr)
        sums = tr.phase_sums()
        # nested (dotted) spans are inside their parent: not re-summed
        assert sums == pytest.approx({"encode": 0.006, "dispatch": 0.020})
        d = tr.to_dict()
        assert d["cycle"] == 7
        names = [s["name"] for s in d["spans"]]
        assert names == ["encode", "dispatch", "dispatch.scatter", "encode"]
        assert d["spans"][0]["start_ms"] == pytest.approx(10.0, abs=0.01)

    def test_slowest_ordering(self):
        rec = FlightRecorder()
        durations = [0.03, 0.01, 0.05, 0.02]
        for i, dur in enumerate(durations):
            tr = rec.begin_cycle(i)
            rec.finish(tr)
            tr.duration_s = dur  # pin: finish stamps real elapsed time
        slow = rec.slowest(2)
        assert [t.cycle_id for t in slow] == [2, 0]

    def test_unfinished_trace_discarded_on_next_begin(self):
        rec = FlightRecorder()
        rec.begin_cycle(1)          # never finished (cycle died)
        tr2 = rec.begin_cycle(2)
        rec.span("apply", tr2.t0, 0.001)
        rec.finish(tr2)
        assert [t.cycle_id for t in rec.traces()] == [2]

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)


class TestManagerTraces:
    """Tier-1 smoke: a full KueueManager run yields well-formed traces."""

    def test_cpu_run_produces_traces(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 6)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        assert traces, "no cycle traces recorded"
        for t in traces:
            assert t.route == "cpu-forced"  # no solver configured
            assert t.regime in ("fit", "preempt")
            assert t.duration_s > 0
            assert t.admitted is not None and t.admitted >= 0
            names = {n for n, _s, _d in t.spans}
            assert {"snapshot", "nominate", "apply", "requeue"} <= names
            for _name, start, dur in t.spans:
                assert start >= 0 and dur >= 0
                assert start + dur <= t.duration_s + 1e-6
        assert sum(t.admitted for t in traces) == 4  # 4-cpu quota

    def test_sums_reconcile_with_histograms(self, clock):
        """Acceptance criterion: per-cycle span sums == the
        cycle_phase_seconds histogram totals (same producer)."""
        mgr = make_mgr(clock)
        submit_n(mgr, 5)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        want: dict = {}
        for t in traces:
            for phase, secs in t.phase_sums().items():
                want[phase] = want.get(phase, 0.0) + secs
        h = mgr.metrics.cycle_phase_seconds
        pi = h.label_names.index("phase")
        got: dict = {}
        for key, (_counts, total, _n) in h.series.items():
            got[key[pi]] = got.get(key[pi], 0.0) + total
        assert set(got) == set(want)
        for phase, secs in want.items():
            assert got[phase] == pytest.approx(secs, rel=1e-9)

    def test_cycle_heads_and_breaker_gauge(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        assert mgr.metrics.cycle_heads.count(route="cpu-forced") > 0
        assert mgr.metrics.breaker_state.value() == 0  # closed

    def test_recorder_disabled_by_config(self, clock):
        cfg = cfgpkg.Configuration()
        cfg.observability.flight_recorder_enable = False
        mgr = make_mgr(clock, cfg=cfg)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        assert mgr.scheduler.recorder.traces() == []
        # admissions unaffected; histograms stay dark, the breaker
        # gauge still updates (a metrics concern, not a tracing one)
        assert mgr.metrics.cycle_heads.count(route="cpu-forced") == 0
        assert mgr.metrics.breaker_state.value() == 0
        assert math.isnan(mgr.metrics.phase_percentile("apply", 0.5))

    def test_capacity_config(self, clock):
        cfg = cfgpkg.Configuration()
        cfg.observability.flight_recorder_capacity = 2
        mgr = make_mgr(clock, cfg=cfg)
        submit_n(mgr, 8)
        mgr.schedule_until_settled()
        assert len(mgr.scheduler.recorder.traces()) <= 2
        assert mgr.scheduler.recorder.cycles_recorded > 2

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            cfgpkg.load({"observability": {"flightRecorderCapacity": 0}})


class TestSolverTraces:
    def _solver_mgr(self, clock):
        from kueue_tpu.solver import BatchSolver
        cfg = cfgpkg.Configuration()
        cfg.solver.min_heads = 0
        cfg.solver.routing = "always"
        cfg.solver.pipeline = False
        return make_mgr(clock, solver=BatchSolver(), cfg=cfg)

    def test_device_route_spans(self, clock):
        mgr = self._solver_mgr(clock)
        submit_n(mgr, 4)
        mgr.schedule_until_settled()
        traces = mgr.scheduler.recorder.traces()
        dev = [t for t in traces if t.route == "device"]
        assert dev, [t.route for t in traces]
        names = {n for t in dev for n, _s, _d in t.spans}
        # solver phases flow through the same trace as scheduler phases
        assert {"encode", "route", "snapshot", "apply"} <= names
        assert {"dispatch", "fetch", "decode"} <= names
        # phase_s cumulative totals (perf artifacts) kept in lockstep
        phase_s = mgr.scheduler.solver.phase_s
        for phase in ("encode", "dispatch", "fetch", "decode"):
            span_total = sum(d for t in traces for n, _s, d in t.spans
                             if n == phase)
            assert span_total == pytest.approx(phase_s[phase], rel=1e-9)

    def test_fault_annotation_lands_in_trace(self, clock):
        from kueue_tpu.resilience import faultinject
        from kueue_tpu.resilience.faultinject import RAISE, FaultInjector
        mgr = self._solver_mgr(clock)
        submit_n(mgr, 3)
        injector = FaultInjector(
            {faultinject.SITE_DISPATCH: {0: RAISE}})
        faultinject.install(injector)
        try:
            mgr.schedule_until_settled()
        finally:
            faultinject.uninstall()
        faulted = [t for t in mgr.scheduler.recorder.traces() if t.faults]
        assert faulted
        notes = [a for t in faulted for a in t.annotations
                 if a["kind"] == "fault"]
        assert notes and notes[0]["site"] in ("solve", "dispatch")
        assert "breaker" in notes[0]


class TestStatusSurface:
    def test_breaker_status(self, clock):
        mgr = make_mgr(clock)
        st = breaker_status(mgr.scheduler)
        assert st["state"] == "closed" and st["route"] == "device"
        assert st["next_probe_in_s"] == 0.0
        mgr.scheduler.breaker.record_fault(clock.now())
        mgr.scheduler.breaker.record_fault(clock.now())
        mgr.scheduler.breaker.record_fault(clock.now())
        st = breaker_status(mgr.scheduler)
        assert st["state"] == "open" and st["route"] == "cpu-breaker"
        assert st["next_probe_in_s"] > 0
        assert st["trips"] == 1

    def test_router_status(self, clock):
        mgr = make_mgr(clock)
        mgr.scheduler.solver_routing = "adaptive"
        mgr.scheduler._cycle_regime = "fit"
        mgr.scheduler._route_record("cpu", 10, 0.5)
        mgr.scheduler._route_record("cpu", 20, 0.5)
        rt = router_status(mgr.scheduler)
        assert rt["routing"] == "adaptive"
        info = rt["regimes"]["cpu/fit"]
        assert len(info["samples"]) == 2
        assert info["median_rate_per_s"] == pytest.approx(40.0)

    def test_arena_status(self, clock):
        from kueue_tpu.solver import BatchSolver
        cfg = cfgpkg.Configuration()
        cfg.solver.min_heads = 0
        mgr = make_mgr(clock, solver=BatchSolver(), cfg=cfg)
        submit_n(mgr, 4)
        mgr.schedule_until_settled()
        st = arena_status(mgr.scheduler.solver)
        assert st["bound"] is True
        assert st["cap"] >= st["occupied"] >= 0
        assert st["encoded_rows"] > 0

    def test_dumper_includes_solver_plane(self, clock):
        mgr = make_mgr(clock)
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        buf = io.StringIO()
        mgr.dumper(out=buf).write()
        text = buf.getvalue()
        assert "-- breaker --" in text and "state=closed" in text
        assert "-- router --" in text
        assert "-- last cycle trace --" in text
        assert "span snapshot" in text
