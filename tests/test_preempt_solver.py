"""Differential tests: device-side preemption target selection vs the
CPU preemptor (the conformance oracle).

Every scenario runs the full scheduler twice — CPU-only and
solver-enabled — and requires identical admitted AND evicted sets
(reference semantics: preemption.go:116-310).
"""

import random

import pytest

from kueue_tpu.api import kueue as api
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas


def run_both(setup, existing, workloads, cycles=1, fair_sharing=False):
    envs = [build_env(setup, solver=False, fair_sharing=fair_sharing),
            build_env(setup, solver=True, fair_sharing=fair_sharing)]
    for env in envs:
        for w in existing():
            env.admit_existing(w)
        for w in workloads():
            env.submit(w)
        for _ in range(cycles):
            env.cycle()
    return envs


def assert_preemption_differential(setup, existing, workloads, cycles=1):
    cpu_env, tpu_env = run_both(setup, existing, workloads, cycles)
    assert tpu_env.scheduler.preemption_fallbacks == 0, \
        "device preemption silently fell back to CPU"
    cpu_evicted = set(cpu_env.client.evicted)
    tpu_evicted = set(tpu_env.client.evicted)
    assert cpu_evicted == tpu_evicted, \
        f"CPU evicted {sorted(cpu_evicted)}, solver evicted {sorted(tpu_evicted)}"
    assert admitted_map(cpu_env) == admitted_map(tpu_env)
    # reasons must match too
    for key in cpu_evicted:
        c_reasons = [c.reason for c in cpu_env.client.evicted[key].status.conditions
                     if c.type == api.WORKLOAD_PREEMPTED]
        t_reasons = [c.reason for c in tpu_env.client.evicted[key].status.conditions
                     if c.type == api.WORKLOAD_PREEMPTED]
        assert c_reasons == t_reasons, (key, c_reasons, t_reasons)
    return cpu_env, tpu_env


class TestDevicePreemption:
    def test_within_cq_priority(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq")

        def existing():
            return [WorkloadWrapper("low").queue("lq").priority(1)
                    .pod_set(count=1, cpu="8").reserve("cq").obj()]

        def workloads():
            return [WorkloadWrapper("high").queue("lq").priority(10)
                    .pod_set(count=1, cpu="8").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert set(cpu_env.client.evicted) == {"default/low"}

    def test_minimal_set_not_all_candidates(self):
        """Three 3-cpu victims, preemptor needs 4: exactly two removed
        then one filled back — the minimal set is 2... or 1+fit? Both
        paths must agree exactly."""
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                       .resource_group(flavor_quotas("default", cpu="9")).obj(),
                       "lq")

        def existing():
            return [WorkloadWrapper(f"low{i}").queue("lq").priority(i)
                    .pod_set(count=1, cpu="3").reserve("cq", now=float(i)).obj()
                    for i in range(3)]

        def workloads():
            return [WorkloadWrapper("high").queue("lq").priority(10)
                    .pod_set(count=1, cpu="4").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert len(cpu_env.client.evicted) == 2  # 4 needed, 3+3 removed

    def test_fill_back(self):
        """Victims of different sizes: the greedy scan over-removes, the
        fill-back returns the small one."""
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq")

        def existing():
            # candidate order: prio asc -> small(1) first, then big(2)
            return [
                WorkloadWrapper("small").queue("lq").priority(1)
                .pod_set(count=1, cpu="2").reserve("cq", now=1.0).obj(),
                WorkloadWrapper("big").queue("lq").priority(2)
                .pod_set(count=1, cpu="8").reserve("cq", now=2.0).obj(),
            ]

        def workloads():
            return [WorkloadWrapper("high").queue("lq").priority(10)
                    .pod_set(count=1, cpu="8").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        # removing small doesn't fit; removing big fits; fill-back returns small
        assert set(cpu_env.client.evicted) == {"default/big"}

    def test_reclaim_within_cohort(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                       .resource_group(flavor_quotas("default", cpu="6")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("default", cpu="6")).obj(),
                       "lq-b")

        def existing():
            return [WorkloadWrapper("borrower").queue("lq-b").priority(5)
                    .pod_set(count=1, cpu="10").reserve("b").obj()]

        def workloads():
            return [WorkloadWrapper("claimant").queue("lq-a").priority(1)
                    .pod_set(count=1, cpu="6").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert set(cpu_env.client.evicted) == {"default/borrower"}

    def test_reclaim_skips_non_borrowing_cq(self):
        """Candidates in a cohort CQ that is not borrowing are skipped
        dynamically during the scan."""
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                                   reclaim_within_cohort=api.PREEMPTION_ANY)
                       .resource_group(flavor_quotas("default", cpu="6")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("default", cpu="6")).obj(),
                       "lq-b")

        def existing():
            return [
                WorkloadWrapper("in-quota").queue("lq-b").priority(0)
                .pod_set(count=1, cpu="5").reserve("b", now=1.0).obj(),
                WorkloadWrapper("own-low").queue("lq-a").priority(0)
                .pod_set(count=1, cpu="6").reserve("a", now=2.0).obj(),
            ]

        def workloads():
            return [WorkloadWrapper("claimant").queue("lq-a").priority(9)
                    .pod_set(count=1, cpu="6").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert set(cpu_env.client.evicted) == {"default/own-low"}

    def test_borrow_within_cohort_threshold(self):
        """borrowWithinCohort: candidates below the priority threshold are
        preemptible while borrowing; ones at/above flip allow_borrowing."""
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .preemption(reclaim_within_cohort=api.PREEMPTION_ANY,
                                   borrow_within_cohort=api.BorrowWithinCohort(
                                       policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY,
                                       max_priority_threshold=5))
                       .resource_group(flavor_quotas("default", cpu="4")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("default", cpu="8")).obj(),
                       "lq-b")

        def existing():
            return [WorkloadWrapper("victim").queue("lq-b").priority(2)
                    .pod_set(count=1, cpu="10").reserve("b").obj()]

        def workloads():
            # needs 6 = borrow 2 beyond nominal while preempting
            return [WorkloadWrapper("preemptor").queue("lq-a").priority(10)
                    .pod_set(count=1, cpu="6").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert set(cpu_env.client.evicted) == {"default/victim"}

    def test_nested_tree_reclaim(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cohort("root")
            env.add_cohort("left", "root")
            env.add_cohort("right", "root")
            env.add_cq(ClusterQueueWrapper("a").cohort("left")
                       .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("right")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq-b")

        def existing():
            return [WorkloadWrapper("borrower").queue("lq-b").priority(0)
                    .pod_set(count=1, cpu="14").reserve("b").obj()]

        def workloads():
            return [WorkloadWrapper("claimant").queue("lq-a").priority(10)
                    .pod_set(count=1, cpu="10").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing, workloads)
        assert set(cpu_env.client.evicted) == {"default/borrower"}

    def test_preemption_then_admission_cycles(self):
        """Multi-cycle: eviction completes, then the preemptor admits."""
        from tests.wrappers import finish_eviction
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                       .resource_group(flavor_quotas("default", cpu="8")).obj(),
                       "lq")

        envs = []
        for solver in (False, True):
            env = build_env(setup, solver=solver)
            low = (WorkloadWrapper("low").queue("lq").priority(1)
                   .pod_set(count=1, cpu="8").reserve("cq").obj())
            env.admit_existing(low)
            env.submit(WorkloadWrapper("high").queue("lq").priority(10)
                       .pod_set(count=1, cpu="8").obj())
            env.cycle()
            assert "default/low" in env.client.evicted
            # finish the eviction: remove the victim from cache, requeue
            env.cache.delete_workload(low)
            env.cycle()
            envs.append(env)
        assert admitted_map(envs[0]) == admitted_map(envs[1])
        assert "default/high" in admitted_map(envs[1])


class TestDevicePreemptionFuzz:
    @pytest.mark.parametrize("seed", range(24))
    def test_random_preemption_differential(self, seed):
        rng = random.Random(9000 + seed)
        n_cohorts = rng.randint(1, 2)
        n_cqs = rng.randint(2, 5)
        policies = [api.PREEMPTION_NEVER, api.PREEMPTION_LOWER_PRIORITY,
                    api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY]
        reclaims = [api.PREEMPTION_NEVER, api.PREEMPTION_ANY,
                    api.PREEMPTION_LOWER_PRIORITY]

        cq_specs = []
        for i in range(n_cqs):
            cohort = (f"cohort-{rng.randrange(n_cohorts)}"
                      if rng.random() < 0.85 else "")
            bwc = None
            if cohort and rng.random() < 0.3:
                bwc = api.BorrowWithinCohort(
                    policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY,
                    max_priority_threshold=rng.choice([None, 3, 6]))
            cq_specs.append((f"cq{i}", cohort, rng.choice(["4", "8", "12"]),
                             rng.choice(policies), rng.choice(reclaims), bwc))

        def setup(env):
            env.add_flavor("default")
            for name, cohort, nominal, wcq, rwc, bwc in cq_specs:
                w = ClusterQueueWrapper(name)
                if cohort:
                    w = w.cohort(cohort)
                w = w.preemption(within_cluster_queue=wcq,
                                 reclaim_within_cohort=rwc,
                                 borrow_within_cohort=bwc)
                env.add_cq(w.resource_group(
                    flavor_quotas("default", cpu=nominal)).obj(), f"lq-{name}")

        existing_specs = []
        for i in range(rng.randint(1, 6)):
            cq = rng.randrange(n_cqs)
            existing_specs.append(
                (f"old{i}", f"cq{cq}", rng.randint(0, 6),
                 rng.choice(["2", "4", "6", "9"]), float(i)))

        pending_specs = []
        for i in range(rng.randint(1, 5)):
            cq = rng.randrange(n_cqs)
            pending_specs.append(
                (f"new{i}", f"lq-cq{cq}", rng.randint(2, 10),
                 rng.choice(["2", "4", "7", "10"]), float(100 + i)))

        def existing():
            return [WorkloadWrapper(n).queue(f"lq-{cq}").priority(p)
                    .pod_set(count=1, cpu=c).reserve(cq, now=ts).obj()
                    for n, cq, p, c, ts in existing_specs]

        def workloads():
            return [WorkloadWrapper(n).queue(q).priority(p).creation(ts)
                    .pod_set(count=1, cpu=c).obj()
                    for n, q, p, c, ts in pending_specs]

        assert_preemption_differential(setup, existing, workloads, cycles=2)


class TestFairSharingThroughSolverPath:
    """Fair-sharing preemption stays on the CPU preemptor (the DRF heap
    is not on device yet — see solver/preempt.py), but the
    solver-configured scheduler must route it there and produce decisions
    identical to the CPU-only scheduler, with zero device fallbacks
    (routing is a gate decision, not a failure)."""

    def _setup(self, env):
        env.add_flavor("default")
        for name in ("a", "b", "c"):
            env.add_cq(
                ClusterQueueWrapper(name).cohort("all")
                .preemption(
                    within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_ANY)
                .resource_group(flavor_quotas("default", cpu="3")).obj(),
                f"lq-{name}")

    def test_fair_preemption_differential(self):
        def existing():
            out = []
            for i in range(3):
                out.append(WorkloadWrapper(f"a{i}").queue("lq-a").creation(i)
                           .pod_set(count=1, cpu=1).reserve("a").obj())
            for i in range(5):
                out.append(WorkloadWrapper(f"b{i}").queue("lq-b").creation(i)
                           .pod_set(count=1, cpu=1).reserve("b").obj())
            out.append(WorkloadWrapper("c0").queue("lq-c").creation(0)
                       .pod_set(count=1, cpu=1).reserve("c").obj())
            return out

        def workloads():
            # c is furthest under nominal; b borrows the most -> fair
            # sharing reclaims from b (preemption_test.go:1532-1546)
            return [WorkloadWrapper("c_incoming").queue("lq-c").creation(100)
                    .pod_set(count=1, cpu=1).obj()]

        cpu_env, tpu_env = run_both(self._setup, existing, workloads,
                                    fair_sharing=True)
        assert tpu_env.scheduler.preemption_fallbacks == 0
        cpu_ev = set(cpu_env.client.evicted)
        tpu_ev = set(tpu_env.client.evicted)
        assert cpu_ev == tpu_ev and cpu_ev, (cpu_ev, tpu_ev)
        for key in cpu_ev:
            reasons = [c.reason
                       for c in tpu_env.client.evicted[key].status.conditions
                       if c.type == api.WORKLOAD_PREEMPTED]
            assert reasons == [api.IN_COHORT_FAIR_SHARING_REASON], reasons


class TestFairPreemptionsOnDevice:
    """fairPreemptions' DRF-heap loop on device (solver/fairpreempt.py)
    vs the CPU oracle (preemption.go:312-437), across strategy configs
    (S2-a then S2-b default, each alone, reversed), the second-strategy
    retry pass, borrowWithinCohort thresholds, and randomized scenarios.
    Zero preemption_fallbacks required: the device path must carry these
    cycles itself."""

    @staticmethod
    def _setup(num_cqs=4, quota="4", bwc=None):
        def setup(env):
            env.add_flavor("default")
            for i in range(num_cqs):
                w = (ClusterQueueWrapper(f"cq{i}").cohort("all")
                     .preemption(
                         within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                         reclaim_within_cohort=api.PREEMPTION_ANY,
                         borrow_within_cohort=(
                             api.BorrowWithinCohort(policy=bwc)
                             if bwc else None))
                     .resource_group(flavor_quotas("default", cpu=quota)))
                env.add_cq(w.obj(), f"lq-cq{i}")
        return setup

    def _run_pair(self, setup, existing, workloads, fs_strategies,
                  cycles=1):
        envs = []
        for solver in (False, True):
            env = build_env(setup, solver=solver, fair_sharing=True,
                            fs_strategies=fs_strategies)
            for w in existing():
                env.admit_existing(w)
            for w in workloads():
                env.submit(w)
            for _ in range(cycles):
                env.cycle()
            envs.append(env)
        cpu_env, dev_env = envs
        assert dev_env.scheduler.preemption_fallbacks == 0
        assert set(cpu_env.client.evicted) == set(dev_env.client.evicted), (
            sorted(cpu_env.client.evicted), sorted(dev_env.client.evicted))
        assert admitted_map(cpu_env) == admitted_map(dev_env)
        # preemption reasons must agree too
        for key, wl in cpu_env.client.evicted.items():
            r_cpu = [c.reason for c in wl.status.conditions
                     if c.type == api.WORKLOAD_PREEMPTED]
            r_dev = [c.reason
                     for c in dev_env.client.evicted[key].status.conditions
                     if c.type == api.WORKLOAD_PREEMPTED]
            assert r_cpu == r_dev, (key, r_cpu, r_dev)
        return cpu_env, dev_env

    @pytest.mark.parametrize("strategies", [
        None,                                           # S2-a then S2-b
        ["LessThanOrEqualToFinalShare"],                # S2-a only
        ["LessThanInitialShare"],                       # S2-b only
        ["LessThanInitialShare", "LessThanOrEqualToFinalShare"],
    ])
    def test_strategy_orders(self, strategies):
        """Uneven borrowing across the cohort; the incoming workload's CQ
        is under nominal, so fair sharing reclaims from the heaviest
        borrower first."""
        def existing():
            out = []
            counts = {0: 2, 1: 7, 2: 5, 3: 1}  # cq1 borrows most
            for qi, n in counts.items():
                for i in range(n):
                    out.append(WorkloadWrapper(f"w{qi}-{i}")
                               .queue(f"lq-cq{qi}").creation(float(i))
                               .pod_set(count=1, cpu=1)
                               .reserve(f"cq{qi}").obj())
            return out

        def workloads():
            return [WorkloadWrapper("inc").queue("lq-cq3").creation(100.0)
                    .priority(5).pod_set(count=1, cpu=2).obj()]

        cpu_env, _ = self._run_pair(self._setup(), existing, workloads,
                                    strategies)
        assert cpu_env.client.evicted, "scenario produced no preemption"

    def test_retry_pass_fires(self):
        """The preemptor's own CQ would remain the top borrower, so S2-a
        refuses every candidate and only the S2-b retry pass (preemptee's
        INITIAL share) finds targets — exercised through the device."""
        def existing():
            out = []
            # every CQ slightly over nominal; incoming needs a big chunk
            for qi in range(4):
                for i in range(5):
                    out.append(WorkloadWrapper(f"w{qi}-{i}")
                               .queue(f"lq-cq{qi}").creation(float(i))
                               .pod_set(count=1, cpu=1)
                               .reserve(f"cq{qi}").obj())
            return out

        def workloads():
            # large ask from cq0: its new share exceeds everyone's final
            # share, S2-a fails, S2-b compares against initial shares
            return [WorkloadWrapper("big").queue("lq-cq0").creation(100.0)
                    .priority(50).pod_set(count=1, cpu=4).obj()]

        cpu_env, _ = self._run_pair(self._setup(quota="4"), existing,
                                    workloads, None)
        # the scenario must be meaningful on the CPU oracle side
        # (either preempts via retry or legitimately finds nothing)

    def test_borrow_within_cohort_threshold(self):
        """Low-priority victims below the borrowWithinCohort threshold are
        preemptable regardless of the share strategy (reason
        InCohortReclaimWhileBorrowing)."""
        def existing():
            out = []
            for qi, n in {0: 1, 1: 6}.items():
                for i in range(n):
                    out.append(WorkloadWrapper(f"w{qi}-{i}")
                               .queue(f"lq-cq{qi}").creation(float(i))
                               .priority(-5 if qi == 1 else 0)
                               .pod_set(count=1, cpu=1)
                               .reserve(f"cq{qi}").obj())
            return out

        def workloads():
            return [WorkloadWrapper("inc").queue("lq-cq0").creation(100.0)
                    .priority(10).pod_set(count=1, cpu=3).obj()]

        setup = self._setup(num_cqs=2, quota="4",
                            bwc=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY)
        cpu_env, _ = self._run_pair(setup, existing, workloads, None)
        assert cpu_env.client.evicted

    @pytest.mark.parametrize("seed", range(8))
    def test_random_fair_differential(self, seed):
        rng = random.Random(1000 + seed)
        n_cqs = rng.randint(2, 5)
        quota = rng.choice(["2", "3", "4"])
        strategies = rng.choice([None, ["LessThanOrEqualToFinalShare"],
                                 ["LessThanInitialShare"]])

        victims = []
        for qi in range(n_cqs):
            for i in range(rng.randint(0, 6)):
                victims.append((f"w{qi}-{i}", qi, rng.randint(-2, 4),
                                float(i), rng.choice([1, 1, 2])))
        incoming = []
        for j in range(rng.randint(1, 3)):
            incoming.append((f"inc{j}", rng.randrange(n_cqs),
                             rng.randint(3, 8), 100.0 + j,
                             rng.choice([1, 2, 3])))

        def existing():
            return [WorkloadWrapper(name).queue(f"lq-cq{qi}").priority(p)
                    .creation(ts).pod_set(count=1, cpu=c)
                    .reserve(f"cq{qi}").obj()
                    for name, qi, p, ts, c in victims]

        def workloads():
            return [WorkloadWrapper(name).queue(f"lq-cq{qi}").priority(p)
                    .creation(ts).pod_set(count=1, cpu=c).obj()
                    for name, qi, p, ts, c in incoming]

        self._run_pair(self._setup(num_cqs=n_cqs, quota=quota), existing,
                       workloads, strategies, cycles=2)

    def test_zero_own_candidate_max_share_preemptor(self):
        """The preemptor's CQ is itself the top borrower but offers NO
        own candidates (within_cluster_queue=Never); victims sit in a
        lower-share peer below the borrowWithinCohort threshold. The
        device scan must not stall on the candidate-less max-share CQ
        (kernel regression: zero-candidate CQs are never poppable)."""
        def setup(env):
            env.add_flavor("default")
            for name in ("a", "b"):
                env.add_cq(
                    ClusterQueueWrapper(name).cohort("all")
                    .preemption(
                        within_cluster_queue=api.PREEMPTION_NEVER,
                        reclaim_within_cohort=api.PREEMPTION_ANY,
                        borrow_within_cohort=api.BorrowWithinCohort(
                            policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
                    .resource_group(flavor_quotas("default", cpu="4")).obj(),
                    f"lq-{name}")

        def existing():
            out = []
            # CQ a: heavy borrower (6 of 4) — all high priority (no own
            # candidates for a lower-priority preemptor anyway, and
            # within_cluster_queue=Never forbids them regardless)
            for i in range(6):
                out.append(WorkloadWrapper(f"a{i}").queue("lq-a").creation(i)
                           .priority(50).pod_set(count=1, cpu=1)
                           .reserve("a").obj())
            # CQ b: mild borrower with low-priority victims below the
            # threshold
            for i in range(2):
                out.append(WorkloadWrapper(f"b{i}").queue("lq-b").creation(i)
                           .priority(-10).pod_set(count=1, cpu=1)
                           .reserve("b").obj())
            return out

        def workloads():
            # incoming on CQ a (the max-share CQ): its own CQ has no
            # candidates; targets must come from b's below-threshold pool
            return [WorkloadWrapper("inc").queue("lq-a").creation(100.0)
                    .priority(5).pod_set(count=1, cpu=1).obj()]

        envs = []
        for solver in (False, True):
            env = build_env(setup, solver=solver, fair_sharing=True)
            for w in existing():
                env.admit_existing(w)
            for w in workloads():
                env.submit(w)
            env.cycle()
            envs.append(env)
        cpu_env, dev_env = envs
        assert dev_env.scheduler.preemption_fallbacks == 0
        assert set(cpu_env.client.evicted) == set(dev_env.client.evicted), (
            sorted(cpu_env.client.evicted), sorted(dev_env.client.evicted))
        assert admitted_map(cpu_env) == admitted_map(dev_env)
