"""Snapshot-backed query plane (kueue_tpu/obs/queryplane.py, ISSUE 12):
sealed-view lifecycle, reader-held handout accounting, lazy position
tables, staleness stamping, and the randomized concurrent
read-vs-cycle interleaving contract."""

import random
import threading

import pytest

from kueue_tpu.api.meta import FakeClock
from kueue_tpu.manager import KueueManager
from kueue_tpu.obs.queryplane import QueryPlane
from kueue_tpu.visibility import VisibilityAPI

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


def _mk_manager(clock, cqs=2, quota=2, cohort=None):
    m = KueueManager(clock=clock)
    m.store.create(make_flavor("default"))
    for c in range(cqs):
        w = ClusterQueueWrapper(f"cq{c}")
        if cohort:
            w = w.cohort(cohort)
        m.store.create(w.resource_group(flavor_quotas("default",
                                                      cpu=quota)).obj())
        m.store.create(make_local_queue(f"lq{c}", "default", f"cq{c}"))
    m.run_until_idle()
    return m


def _submit(mgr, n, lq="lq0", prefix="w", cpu="1"):
    for i in range(n):
        mgr.store.create(WorkloadWrapper(f"{prefix}{i}").queue(lq)
                         .creation(100 + i).request("cpu", cpu).obj())
    mgr.run_until_idle()


def _bump_quota(mgr, cq="cq0", cpu=3):
    obj = mgr.store.get("ClusterQueue", "", cq)
    obj.spec.resource_groups[0].flavors[0].resources[0].nominal_quota = \
        cpu * 1000
    mgr.store.update(obj)
    mgr.run_until_idle()


class TestSealedViewLifecycle:
    def test_warming_until_first_publish(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        assert qp is not None and qp.warming
        assert qp.acquire() is None
        assert qp.token_lag() is None
        _submit(mgr, 1)
        mgr.schedule_once()
        assert not qp.warming
        view = qp.acquire()
        assert view is not None and view.cycle_id > 0
        assert view.generation == mgr.cache.generation_token()
        qp.release(view)

    def test_every_cycle_seal_publishes(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 3)
        before = qp.cycles_published
        mgr.schedule_once()
        assert qp.cycles_published == before + 1
        mgr.schedule_once()
        assert qp.cycles_published == before + 2

    def test_publish_without_snapshot_shares_previous_handout(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 2)
        mgr.schedule_once()
        v1 = qp.acquire()
        snap = v1.snapshot
        assert snap is not None
        qp.release(v1)
        # a light/pipelined seal carries no fresh snapshot: the new
        # view shares the previous handout, released exactly once
        taken = mgr.cache.handouts_taken
        qp.publish(999, "drain", [], snapshot=None)
        assert mgr.cache.handouts_taken == taken
        v2 = qp.acquire()
        assert v2.cycle_id == 999 and v2.snapshot is snap
        qp.release(v2)
        assert mgr.cache.live_handouts == 1  # still held, not leaked
        qp.close()
        assert mgr.cache.live_handouts == 0

    def test_borrow_defers_release_across_publish(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 3)
        mgr.schedule_once()
        held = qp.acquire()
        held_snap = held.snapshot
        mgr.schedule_once()   # new seal retires the borrowed view
        assert mgr.cache.live_handouts == 2  # old held by reader + new
        # the retired view's handout returns only when the borrow does
        assert held.retired
        qp.release(held)
        assert mgr.cache.live_handouts == 1
        assert held.snapref is None  # released exactly once
        # and the released snapshot really went back to the cache
        assert held_snap is not qp.acquire().snapshot

    def test_shutdown_closes_plane_and_releases(self):
        mgr = _mk_manager(FakeClock(1000.0))
        _submit(mgr, 2)
        mgr.schedule_once()
        assert mgr.cache.live_handouts == 1
        mgr.shutdown(checkpoint=False)
        assert mgr.cache.live_handouts == 0
        assert mgr.cache.handouts_taken == mgr.cache.handouts_released

    def test_parked_seal_snapshot_never_strands(self):
        """A cycle that raised between _retire_cycle_snapshot and
        _finish_trace leaves its handout parked in _seal_snapshot; the
        next cycle's start (and Scheduler.stop) must release it, not
        silently drop the reference — or live_handouts could never
        return to zero (code-review finding)."""
        mgr = _mk_manager(FakeClock(1000.0))
        _submit(mgr, 4)
        mgr.schedule_once()
        assert mgr.cache.live_handouts == 1  # the plane's sealed view
        # simulate the escaped-exception window: a handout parked for a
        # seal that never happened
        mgr.scheduler._seal_snapshot = mgr.cache.snapshot()
        assert mgr.cache.live_handouts == 2
        mgr.schedule_once()  # cycle start flushes the parked handout
        assert mgr.cache.live_handouts == 1
        mgr.scheduler._seal_snapshot = mgr.cache.snapshot()
        mgr.scheduler.stop()  # stop() flushes too
        assert mgr.scheduler._seal_snapshot is None
        mgr.shutdown(checkpoint=False)
        assert mgr.cache.live_handouts == 0

    def test_snapshotless_seals_keep_the_transitioning_witness(self):
        """A pipelined stretch publishes many seals against ONE shared
        snapshot. A workload nominated (and admitted) by any of those
        seals must stay answerable as found=True/transitioning for the
        whole stretch — the order chain accumulates until the next
        full-snapshot seal resets it (code-review finding)."""
        mgr = _mk_manager(FakeClock(1000.0), quota=2)
        qp = mgr.query_plane
        _submit(mgr, 1)
        mgr.schedule_once()   # sync seal: snapshot + order ["w0"]
        # simulate a pipelined stretch: snapshot-less seals with other
        # cycles' orders (w0 admitted in the sealed sync cycle above,
        # so it is in neither the shared snapshot nor the live index)
        qp.publish(101, "device-pipelined", ["default/x1"], snapshot=None)
        qp.publish(102, "device-pipelined", ["default/x2"], snapshot=None)
        view = qp.acquire()
        try:
            st = qp.workload_status(view, "default", "w0")
            assert st["found"] is True
            assert st["status"] in ("transitioning", "admitted")
            # a name no seal ever nominated stays unknown
            st = qp.workload_status(view, "default", "zzz")
            assert st["found"] is False and st["status"] == "unknown"
        finally:
            qp.release(view)
        # the next FULL-snapshot seal resets the chain
        _submit(mgr, 1, prefix="y")
        mgr.schedule_once()
        assert len(qp._order_chain) == 1
        mgr.shutdown(checkpoint=False)
        assert mgr.cache.live_handouts == 0

    def test_scheduler_without_plane_releases_as_before(self):
        # the bare-Scheduler path (benches, conformance envs) keeps the
        # immediate release + shell recycling behavior
        from tests.test_scheduler import simple_env
        from tests.wrappers import WorkloadWrapper as WW
        env = simple_env()
        env.submit(WW("w").queue("lq").pod_set(count=1, cpu="1").obj())
        env.cycle()
        assert env.scheduler.query_plane is None
        assert env.cache.live_handouts == 0


class TestPositionTables:
    def test_tables_materialize_once_per_view(self):
        mgr = _mk_manager(FakeClock(1000.0), quota=1)
        qp = mgr.query_plane
        _submit(mgr, 4)
        mgr.schedule_until_settled()   # w0 admits, w1..w3 pending
        view = qp.acquire()
        try:
            built = qp.tables_built
            rows1 = qp.pending_cq(view, "cq0", 100, 0)
            assert qp.tables_built == built + 1
            rows2 = qp.pending_cq(view, "cq0", 100, 0)
            assert qp.tables_built == built + 1  # cached, not rebuilt
            assert [r.name for r in rows1] == [r.name for r in rows2] \
                == ["w1", "w2", "w3"]
            assert [r.position_in_cluster_queue for r in rows1] == [0, 1, 2]
        finally:
            qp.release(view)

    def test_parity_with_live_visibility_api_when_quiescent(self):
        mgr = _mk_manager(FakeClock(1000.0), quota=1)
        qp = mgr.query_plane
        mgr.store.create(make_local_queue("lq0b", "default", "cq0"))
        mgr.run_until_idle()
        for i in range(3):
            mgr.store.create(WorkloadWrapper(f"a{i}").queue("lq0")
                             .creation(200 + 2 * i)
                             .request("cpu", "2").obj())
            mgr.store.create(WorkloadWrapper(f"b{i}").queue("lq0b")
                             .creation(201 + 2 * i)
                             .request("cpu", "2").obj())
        mgr.schedule_until_settled()   # nothing admits (2cpu vs 1)
        live = VisibilityAPI(mgr.queues)
        view = qp.acquire()
        try:
            lsum = live.pending_workloads_cq("cq0")
            rows = qp.pending_cq(view, "cq0", 1000, 0)
            assert [(p.name, p.position_in_cluster_queue,
                     p.position_in_local_queue) for p in lsum.items] \
                == [(r.name, r.position_in_cluster_queue,
                     r.position_in_local_queue) for r in rows]
            # LQ projection parity incl. offset/limit semantics
            lsum = live.pending_workloads_lq("default", "lq0b",
                                             limit=2, offset=1)
            rows = qp.pending_lq(view, "default", "lq0b", 2, 1)
            assert [p.name for p in lsum.items] == [r.name for r in rows]
            assert [p.position_in_local_queue for p in lsum.items] \
                == [r.position_in_local_queue for r in rows]
            assert qp.pending_lq(view, "default", "nope", 10, 0) == []
        finally:
            qp.release(view)

    def test_nominate_rank_rides_the_seal(self):
        mgr = _mk_manager(FakeClock(1000.0), quota=1)
        qp = mgr.query_plane
        _submit(mgr, 3)
        mgr.schedule_once()   # w0 admits; later cycles nominate w1/w2
        mgr.schedule_once()
        view = qp.acquire()
        try:
            rows = qp.pending_cq(view, "cq0", 100, 0)
            ranked = [r for r in rows if r.nominate_rank is not None]
            # the head the sealed cycle nominated carries its rank
            assert ranked and ranked[0].nominate_rank == 0
        finally:
            qp.release(view)

    def test_workload_status_prefers_view_tables(self):
        mgr = _mk_manager(FakeClock(1000.0), quota=1)
        qp = mgr.query_plane
        _submit(mgr, 3)
        mgr.schedule_until_settled()
        view = qp.acquire()
        try:
            qp.pending_cq(view, "cq0", 100, 0)  # materialize
            st = qp.workload_status(view, "default", "w1")
            assert st["status"] == "pending"
            assert st["position_in_cluster_queue"] == 0
            st = qp.workload_status(view, "default", "w0")
            assert st["status"] == "admitted"
            st = qp.workload_status(view, "default", "zzz")
            assert st["found"] is False and st["status"] == "unknown"
            # admitted membership resolves through the lazy per-view
            # key->CQ index, one dict probe, not an O(CQs) scan
            assert view.snap_index["default/w0"] == "cq0"
        finally:
            qp.release(view)

    def test_just_admitted_answers_transitioning_not_unknown(self):
        """A workload nominated AND admitted in the sealed cycle sits
        in none of the view's tables or its (seal-time) snapshot — it
        must answer found=True/\"transitioning\" (the nominate-order
        column proves the view heard of it), never the same payload a
        nonexistent name gets (code-review finding)."""
        mgr = _mk_manager(FakeClock(1000.0), quota=2)
        qp = mgr.query_plane
        _submit(mgr, 1)
        mgr.schedule_once()   # w0 admits in the very cycle this view seals
        view = qp.acquire()
        try:
            st = qp.workload_status(view, "default", "w0")
            assert st["found"] is True
            assert st["status"] in ("transitioning", "admitted")
        finally:
            qp.release(view)
        # the NEXT sealed view resolves it to admitted proper (an idle
        # tick publishes nothing — a fresh head forces a real seal)
        _submit(mgr, 1, prefix="x")
        mgr.schedule_once()
        view = qp.acquire()
        try:
            st = qp.workload_status(view, "default", "w0")
            assert st["found"] and st["status"] == "admitted"
        finally:
            qp.release(view)


class TestStaleness:
    def test_token_lag_bounded_by_one_seal(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 2)
        mgr.schedule_once()
        assert qp.token_lag() == 0
        # a structural edit after the seal: the view lags ONE generation
        _bump_quota(mgr, cpu=3)
        assert qp.token_lag() == 1
        view = qp.acquire()
        assert view.generation != mgr.cache.generation_token()
        qp.release(view)
        # ...until the very next cycle seal catches up
        mgr.schedule_once()
        assert qp.token_lag() == 0
        view = qp.acquire()
        assert view.generation == mgr.cache.generation_token()
        qp.release(view)

    def test_stamp_and_status_surface(self):
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 1)
        mgr.schedule_once()
        view = qp.acquire()
        try:
            stamp = view.stamp()
            assert stamp["generation"] == \
                list(mgr.cache.generation_token())
            assert stamp["cycle"] == view.cycle_id
            assert stamp["age_s"] >= 0
        finally:
            qp.release(view)
        st = qp.status()
        assert not st["warming"] and st["token_lag"] == 0
        assert st["cycles_published"] >= 1
        assert st["holds_snapshot_handout"] is True


class TestConcurrentReadVsCycle:
    """ISSUE 12 satellite: randomized concurrent read-vs-cycle
    interleaving — (i) responses are internally consistent (one
    snapshot, one token per borrowed view), (ii) staleness never
    exceeds one structural generation once steady, (iii) no torn
    position tables."""

    @pytest.mark.parametrize("seed", [0, 7])
    def test_interleaved_readers_stay_consistent(self, seed):
        rng = random.Random(seed)
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock, cqs=3, quota=2, cohort="co")
        qp = mgr.query_plane
        cache = mgr.cache
        stop = threading.Event()
        errors = []
        max_lag = [0]
        reads = [0]

        def reader(idx):
            n = 0
            while not stop.is_set():
                view = qp.acquire()
                if view is None:
                    continue
                try:
                    # (ii) staleness sampled AT ACQUIRE: a borrow held
                    # across driver iterations may observe more drift
                    # (that is what holding means); the bound under
                    # test is how stale a just-acquired view can be.
                    lag = cache.generation_lag(view.generation)
                    cq = f"cq{(n + idx) % 3}"
                    rows = qp.pending_cq(view, cq, 100, 0)
                    again = qp.pending_cq(view, cq, 100, 0)
                    # (iii) immutable within a view: two reads agree
                    if [r.name for r in rows] != [r.name for r in again]:
                        errors.append(f"torn table for {cq}")
                    names = [r.name for r in rows]
                    if len(set(names)) != len(names):
                        errors.append(f"duplicate rows: {names}")
                    if [r.position_in_cluster_queue for r in rows] \
                            != list(range(len(rows))):
                        errors.append(f"non-dense positions: {rows}")
                    # (i) one token per view
                    if tuple(view.stamp()["generation"]) \
                            != view.generation:
                        errors.append("stamp token != view token")
                    if lag > max_lag[0]:
                        max_lag[0] = lag
                    reads[0] += 1
                finally:
                    qp.release(view)
                n += 1

        threads = [threading.Thread(target=reader, args=(i,),
                                    daemon=True) for i in range(3)]
        for t in threads:
            t.start()
        try:
            n = 0
            edited = False
            for step in range(40):
                op = rng.random()
                if op < 0.55:
                    lq = f"lq{rng.randrange(3)}"
                    mgr.store.create(
                        WorkloadWrapper(f"r{seed}-{n}").queue(lq)
                        .creation(100 + n)
                        .request("cpu", str(rng.choice([1, 2]))).obj())
                    n += 1
                    mgr.run_until_idle()
                elif op < 0.7:
                    # at most ONE structural edit between seals: the
                    # staleness bound under test
                    _bump_quota(mgr, cq=f"cq{rng.randrange(3)}",
                                cpu=rng.choice([2, 3, 4]))
                    edited = True
                    # (ii) deterministic: an un-sealed edit leaves the
                    # current view at most ONE generation behind
                    lag = qp.token_lag()
                    assert lag is None or lag <= 1
                pubs0 = qp.cycles_published
                mgr.schedule_once()
                clock.advance(1.0)
                if qp.cycles_published > pubs0:
                    # (ii) deterministic: every cycle seal catches the
                    # view back up to the live token — staleness never
                    # exceeds one cycle generation once steady
                    assert qp.token_lag() == 0
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors[:5]
        assert reads[0] > 0
        assert edited  # the run exercised structural churn
        # the at-acquire lag can race one driver iteration past the
        # deterministic bound (acquire -> edit -> seal -> edit within a
        # GIL slice), never unbounded drift
        assert max_lag[0] <= 2, max_lag[0]
        mgr.shutdown(checkpoint=False)
        assert mgr.cache.live_handouts == 0

    def test_read_storm_releases_handouts_on_error_paths(self):
        """The zero-live_handouts regression extended to read storms:
        readers that die mid-request (exception between acquire and
        release) must still return their borrows via try/finally —
        modeled here by raising out of the served block."""
        mgr = _mk_manager(FakeClock(1000.0))
        qp = mgr.query_plane
        _submit(mgr, 2)
        mgr.schedule_once()
        for _ in range(5):
            view = qp.acquire()
            try:
                raise RuntimeError("reader died mid-serve")
            except RuntimeError:
                pass
            finally:
                qp.release(view)
        mgr.schedule_once()   # rotation still releases cleanly
        mgr.shutdown(checkpoint=False)
        assert mgr.cache.live_handouts == 0


class TestQueryPlaneDisabled:
    def test_config_knob_disables_the_plane(self):
        from kueue_tpu import config as cfgpkg
        cfg = cfgpkg.Configuration()
        cfg.observability.query_plane_enable = False
        mgr = KueueManager(cfg=cfg, clock=FakeClock(1000.0))
        assert mgr.query_plane is None
        assert mgr.scheduler.query_plane is None
        mgr.store.create(make_flavor("default"))
        mgr.store.create(ClusterQueueWrapper("cq")
                         .resource_group(flavor_quotas("default", cpu=1))
                         .obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        _submit(mgr, 2, lq="lq")
        mgr.schedule_once()
        # without the plane the scheduler releases per-cycle as before
        assert mgr.cache.live_handouts == 0

    def test_raw_queryplane_on_bare_components(self):
        # the plane composes with a bare Scheduler env (the bench
        # wiring): attach, cycle, read, close
        from tests.test_scheduler import simple_env
        from tests.wrappers import WorkloadWrapper as WW
        env = simple_env()
        qp = QueryPlane(env.cache, env.queues)
        env.scheduler.query_plane = qp
        env.submit(WW("w1").queue("lq").pod_set(count=1, cpu="1").obj())
        env.submit(WW("w2").queue("lq").pod_set(count=1, cpu="4").obj())
        env.cycle()
        view = qp.acquire()
        try:
            assert view is not None
            assert view.generation == env.cache.generation_token()
        finally:
            qp.release(view)
        qp.close()
        assert env.cache.live_handouts == 0
