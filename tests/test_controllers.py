"""Integration-style tests: sim store + core controllers + scheduler.

Plays the role of the reference's envtest suites
test/integration/controller/core/* and test/integration/scheduler/*
(SURVEY.md §4 tier 2), with the sim runtime substituting for
kube-apiserver + controller-runtime.
"""

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import FakeClock, find_condition, is_condition_true
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.sim import Store

from tests.wrappers import (
    finish_eviction,
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


@pytest.fixture
def mgr(clock):
    return KueueManager(clock=clock)


def setup_basic(mgr, cpu_quota=4):
    """Default flavor + one CQ + one LQ, all through the store."""
    mgr.store.create(make_flavor("default"))
    mgr.store.create(
        ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=cpu_quota)).obj())
    mgr.store.create(make_local_queue("lq", "default", "cq"))
    mgr.run_until_idle()


class TestSimStore:
    def test_finalizer_blocks_deletion(self, clock):
        store = Store(clock)
        wl = WorkloadWrapper("w").queue("lq").obj()
        wl.metadata.finalizers = [api.RESOURCE_IN_USE_FINALIZER]
        store.create(wl)
        store.delete("Workload", "default", "w")
        parked = store.get("Workload", "default", "w")
        assert parked.metadata.deletion_timestamp is not None
        parked.metadata.finalizers = []
        store.update(parked)
        assert store.try_get("Workload", "default", "w") is None

    def test_noop_update_fires_no_event(self, clock):
        store = Store(clock)
        events = []
        store.watch("Workload", lambda e, o, old: events.append(e))
        wl = WorkloadWrapper("w").queue("lq").obj()
        store.create(wl)
        current = store.get("Workload", "default", "w")
        store.update(current)
        assert events == ["ADDED"]


class TestEndToEndAdmission:
    def test_workload_admitted_through_full_stack(self, mgr):
        setup_basic(mgr)
        wl = WorkloadWrapper("job-a").queue("lq").request("cpu", "2").obj()
        mgr.store.create(wl)
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "job-a")
        assert wlpkg.has_quota_reservation(got)
        assert wlpkg.is_admitted(got)  # no admission checks -> immediate
        assert got.status.admission.cluster_queue == "cq"
        # CQ status reflects the admission
        cq = mgr.store.get("ClusterQueue", "", "cq")
        assert cq.status.reserving_workloads == 1
        assert cq.status.admitted_workloads == 1
        assert cq.status.flavors_usage[0].resources[0].total == 2000
        # LQ status too
        lq = mgr.store.get("LocalQueue", "default", "lq")
        assert lq.status.admitted_workloads == 1
        assert mgr.metrics.admitted_workloads_total.value(cluster_queue="cq") == 1

    def test_over_quota_stays_pending_with_reason(self, mgr):
        setup_basic(mgr, cpu_quota=1)
        mgr.store.create(WorkloadWrapper("big").queue("lq").request("cpu", "2").obj())
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "big")
        assert not wlpkg.has_quota_reservation(got)
        cond = find_condition(got.status.conditions, api.WORKLOAD_QUOTA_RESERVED)
        assert cond is not None and cond.status == "False"
        assert "insufficient quota" in cond.message

    def test_fifo_order_and_second_cycle(self, mgr, clock):
        setup_basic(mgr, cpu_quota=2)
        a = WorkloadWrapper("a").queue("lq").request("cpu", "2").creation(10).obj()
        b = WorkloadWrapper("b").queue("lq").request("cpu", "2").creation(20).obj()
        mgr.store.create(a)
        mgr.store.create(b)
        mgr.schedule_once()
        got_a = mgr.store.get("Workload", "default", "a")
        got_b = mgr.store.get("Workload", "default", "b")
        assert wlpkg.is_admitted(got_a)
        assert not wlpkg.has_quota_reservation(got_b)
        # finish a -> b admits next cycle
        got_a.status.conditions.append(
            type(got_a.status.conditions[0])(
                type=api.WORKLOAD_FINISHED, status="True", reason="JobFinished",
                message="done", last_transition_time=clock.now()))
        mgr.store.update(got_a)
        mgr.schedule_until_settled()
        assert wlpkg.is_admitted(mgr.store.get("Workload", "default", "b"))

    def test_missing_local_queue_marks_inadmissible(self, mgr):
        setup_basic(mgr)
        mgr.store.create(WorkloadWrapper("w").queue("nope").request("cpu", "1").obj())
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        cond = find_condition(got.status.conditions, api.WORKLOAD_QUOTA_RESERVED)
        assert cond is not None and cond.status == "False"
        assert cond.reason == api.WORKLOAD_INADMISSIBLE
        assert "doesn't exist" in cond.message

    def test_inactive_cq_missing_flavor(self, mgr):
        mgr.store.create(
            ClusterQueueWrapper("cq").resource_group(
                flavor_quotas("ghost", cpu=1)).obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        cq = mgr.store.get("ClusterQueue", "", "cq")
        cond = find_condition(cq.status.conditions, api.CLUSTER_QUEUE_ACTIVE)
        assert cond.status == "False"
        assert cond.reason == "FlavorNotFound"
        # creating the flavor activates the CQ
        mgr.store.create(make_flavor("ghost"))
        mgr.run_until_idle()
        cq = mgr.store.get("ClusterQueue", "", "cq")
        assert is_condition_true(cq.status.conditions, api.CLUSTER_QUEUE_ACTIVE)

    def test_local_queue_active_condition(self, mgr):
        setup_basic(mgr)
        lq = mgr.store.get("LocalQueue", "default", "lq")
        assert is_condition_true(lq.status.conditions, api.LOCAL_QUEUE_ACTIVE)
        mgr.store.create(make_local_queue("dangling", "default", "no-cq"))
        mgr.run_until_idle()
        lq2 = mgr.store.get("LocalQueue", "default", "dangling")
        cond = find_condition(lq2.status.conditions, api.LOCAL_QUEUE_ACTIVE)
        assert cond.status == "False" and cond.reason == "ClusterQueueDoesNotExist"


class TestAdmissionChecks:
    def make_check(self, mgr, name="check1", controller="test-controller"):
        ac = api.AdmissionCheck()
        ac.metadata.name = name
        ac.spec.controller_name = controller
        return ac

    def test_checks_gate_admitted_condition(self, clock):
        mgr = KueueManager(clock=clock,
                           registered_check_controllers={"test-controller"})
        mgr.store.create(make_flavor("default"))
        mgr.store.create(self.make_check(mgr))
        mgr.store.create(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", cpu=4))
            .admission_checks("check1").obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        ac = mgr.store.get("AdmissionCheck", "", "check1")
        assert is_condition_true(ac.status.conditions, api.ADMISSION_CHECK_ACTIVE)

        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.has_quota_reservation(got)
        assert not wlpkg.is_admitted(got)  # gated on the pending check
        assert [c.name for c in got.status.admission_checks] == ["check1"]

        # flip the check to Ready -> workload admits
        wlpkg.set_admission_check_state(
            got.status.admission_checks,
            api.AdmissionCheckState(name="check1", state=api.CHECK_STATE_READY),
            clock.now())
        mgr.store.update(got)
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(got)

    def test_retry_check_evicts(self, clock):
        mgr = KueueManager(clock=clock,
                           registered_check_controllers={"test-controller"})
        mgr.store.create(make_flavor("default"))
        mgr.store.create(self.make_check(mgr))
        mgr.store.create(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", cpu=4))
            .admission_checks("check1").obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "w")
        wlpkg.set_admission_check_state(
            got.status.admission_checks,
            api.AdmissionCheckState(name="check1", state=api.CHECK_STATE_RETRY),
            clock.now())
        mgr.store.update(got)
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_evicted(got)
        cond = find_condition(got.status.conditions, api.WORKLOAD_EVICTED)
        assert cond.reason == api.EVICTED_BY_ADMISSION_CHECK

    def test_rejected_check_deactivates(self, clock):
        mgr = KueueManager(clock=clock,
                           registered_check_controllers={"test-controller"})
        mgr.store.create(make_flavor("default"))
        mgr.store.create(self.make_check(mgr))
        mgr.store.create(
            ClusterQueueWrapper("cq")
            .resource_group(flavor_quotas("default", cpu=4))
            .admission_checks("check1").obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "w")
        wlpkg.set_admission_check_state(
            got.status.admission_checks,
            api.AdmissionCheckState(name="check1", state=api.CHECK_STATE_REJECTED,
                                    message="no capacity"),
            clock.now())
        mgr.store.update(got)
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        assert not got.spec.active
        assert mgr.recorder.by_reason("AdmissionCheckRejected")

    def test_unregistered_controller_check_inactive(self, mgr):
        mgr.store.create(self.make_check(mgr, controller="ghost"))
        mgr.run_until_idle()
        ac = mgr.store.get("AdmissionCheck", "", "check1")
        cond = find_condition(ac.status.conditions, api.ADMISSION_CHECK_ACTIVE)
        assert cond.status == "False" and cond.reason == "ControllerNotRegistered"


class TestLifecycle:
    def test_deactivation_evicts_and_requeue_on_reactivate(self, mgr, clock):
        setup_basic(mgr)
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(got)
        # deactivate
        got.spec.active = False
        mgr.store.update(got)
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_evicted(got)
        cond = find_condition(got.status.conditions, api.WORKLOAD_EVICTED)
        assert cond.reason == api.EVICTED_BY_DEACTIVATION
        # usage released
        cq = mgr.store.get("ClusterQueue", "", "cq")
        assert cq.status.reserving_workloads == 0

    def test_cq_stop_policy_drains(self, mgr, clock):
        setup_basic(mgr)
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        cq = mgr.store.get("ClusterQueue", "", "cq")
        cq.spec.stop_policy = api.HOLD_AND_DRAIN
        mgr.store.update(cq)
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        cond = find_condition(got.status.conditions, api.WORKLOAD_EVICTED)
        assert cond is not None and cond.reason == api.EVICTED_BY_CLUSTER_QUEUE_STOPPED
        # restart -> Requeued=True again and admitted eventually
        cq = mgr.store.get("ClusterQueue", "", "cq")
        cq.spec.stop_policy = api.STOP_POLICY_NONE
        mgr.store.update(cq)
        mgr.schedule_until_settled()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(got)

    def test_resource_flavor_finalizer(self, mgr):
        setup_basic(mgr)
        rf = mgr.store.get("ResourceFlavor", "", "default")
        assert api.RESOURCE_IN_USE_FINALIZER in rf.metadata.finalizers
        # delete while in use -> parked
        mgr.store.delete("ResourceFlavor", "", "default")
        mgr.run_until_idle()
        assert mgr.store.try_get("ResourceFlavor", "", "default") is not None
        # remove the CQ -> the CQ-deletion fan-out re-reconciles the
        # flavor, which can now finalize
        mgr.store.delete("ClusterQueue", "", "cq")
        mgr.run_until_idle()
        assert mgr.store.try_get("ResourceFlavor", "", "default") is None


class TestPodsReadyTimeout:
    def make_mgr(self, clock, backoff_limit=None):
        cfg = cfgpkg.Configuration(
            wait_for_pods_ready=cfgpkg.WaitForPodsReady(
                enable=True, timeout_seconds=60.0, block_admission=False,
                requeuing_strategy=cfgpkg.RequeuingStrategy(
                    backoff_base_seconds=10, backoff_limit_count=backoff_limit,
                    backoff_jitter=0.0)))
        return KueueManager(cfg=cfg, clock=clock)

    def test_timeout_evicts_with_backoff(self, clock):
        mgr = self.make_mgr(clock)
        setup_basic(mgr)
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        assert wlpkg.is_admitted(mgr.store.get("Workload", "default", "w"))
        # not ready after the timeout -> evicted with requeue state
        mgr.advance(61.0)
        got = mgr.store.get("Workload", "default", "w")
        cond = find_condition(got.status.conditions, api.WORKLOAD_EVICTED)
        assert cond is not None and cond.reason == api.EVICTED_BY_PODS_READY_TIMEOUT
        assert got.status.requeue_state.count == 1
        assert got.status.requeue_state.requeue_at == pytest.approx(clock.now() + 10.0)
        # the job side completes the eviction (suspend + unset reservation)
        finish_eviction(mgr.store, "default", "w", clock.now())
        mgr.run_until_idle()
        # after the backoff the workload requeues and re-admits
        mgr.advance(11.0)
        mgr.schedule_until_settled()
        got = mgr.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(got)
        assert is_condition_true(got.status.conditions, api.WORKLOAD_REQUEUED)

    def test_backoff_limit_deactivates(self, clock):
        mgr = self.make_mgr(clock, backoff_limit=1)
        setup_basic(mgr)
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_once()
        mgr.advance(61.0)   # first eviction, count=1
        finish_eviction(mgr.store, "default", "w", clock.now())
        mgr.advance(11.0)
        mgr.schedule_until_settled()
        assert wlpkg.is_admitted(mgr.store.get("Workload", "default", "w"))
        mgr.advance(61.0)   # second timeout: count would exceed limit -> deactivate
        mgr.run_until_idle()
        got = mgr.store.get("Workload", "default", "w")
        assert not got.spec.active
