"""MultiKueue capacity-column tests (ISSUE 13): the batched placement
scored inside the fused solve must be bit-equivalent to the sequential
oracle (encode.place_remote_dicts), match the sequential multikueue
controller's outcome on single-cluster traffic, mask lost clusters to
zero capacity, and drive single-mirror execution end-to-end through a
real manager (host-oracle CPU route AND device-decode solver route)."""

import numpy as np
import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import autoscaling as asapi
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import FakeClock, ObjectMeta
from kueue_tpu.controller.admissionchecks.multikueue import (
    CONTROLLER_NAME as MK_CONTROLLER,
)
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.solver import encode

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


FLAVORS = ["fa", "fb", "fc"]      # sorted — topology index order
RESOURCES = ["cpu", "mem"]        # sorted


def _random_columns(rng, K):
    """Random (columns tuple, ccap, coffer, cactive) with the dict and
    tensor forms built from the SAME draw."""
    F, R = len(FLAVORS), len(RESOURCES)
    ccap = np.zeros((K, F, R), np.int64)
    coffer = np.zeros((K, F, R), bool)
    cactive = rng.random(K) < 0.8
    cols = []
    for k in range(K):
        caps = {}
        for fi, fname in enumerate(FLAVORS):
            for ri, rname in enumerate(RESOURCES):
                if rng.random() < 0.7:
                    avail = int(rng.integers(0, 50))
                    caps[(fname, rname)] = avail
                    coffer[k, fi, ri] = True
                    ccap[k, fi, ri] = avail
        cols.append((f"w{k}", caps, bool(cactive[k])))
    return tuple(cols), ccap, coffer, cactive


class TestKernelVsOracle:
    def test_batched_placement_matches_sequential_oracle(self):
        import jax.numpy as jnp

        from kueue_tpu.solver.kernel import score_cluster_columns_impl
        F, R = len(FLAVORS), len(RESOURCES)
        for seed in range(12):
            rng = np.random.default_rng(seed)
            K = int(rng.integers(1, 5))
            W, P, Q = 24, 2, 6
            cols, ccap, coffer, cactive = _random_columns(rng, K)
            requests = rng.integers(0, 30, size=(W, P, R)).astype(np.int64)
            podset_active = rng.random((W, P)) < 0.8
            requests[~podset_active] = 0
            wl_cq = rng.integers(0, Q, size=W).astype(np.int32)
            mk_cq = rng.random(Q) < 0.7
            admitted = rng.random(W) < 0.7
            order = rng.permutation(W).astype(np.int64)

            got = np.asarray(score_cluster_columns_impl(
                jnp.asarray(ccap), jnp.asarray(coffer),
                jnp.asarray(cactive), jnp.asarray(mk_cq),
                jnp.asarray(requests), jnp.asarray(podset_active),
                jnp.asarray(wl_cq), jnp.asarray(order),
                jnp.asarray(admitted)))

            # oracle: the mk-admitted rows in admission order
            treq = np.where(podset_active[:, :, None], requests, 0).sum(1)
            seq = [w for w in order.tolist()
                   if mk_cq[wl_cq[w]] and admitted[w]]
            reqs = [{RESOURCES[ri]: int(treq[w, ri]) for ri in range(R)}
                    for w in seq]
            placed = encode.place_remote_dicts(cols, reqs)
            name_to_idx = {c[0]: i for i, c in enumerate(cols)}
            want = np.full(W, -1, np.int32)
            for w, name in zip(seq, placed):
                if name is not None:
                    want[w] = name_to_idx[name]
            assert (got == want).all(), (seed, got.tolist(), want.tolist())
            # non-mk / non-admitted rows never place
            non = ~(mk_cq[wl_cq] & admitted)
            assert (got[non] == -1).all()

    def test_lost_cluster_columns_mask_to_zero(self):
        # an inactive cluster can hold capacity but never receives a
        # placement — its column is masked (the snapshot stamps
        # active=False the moment the activity probe flips)
        cols = ((u"w0", {("fa", "cpu"): 100}, False),
                ("w1", {("fa", "cpu"): 100}, True))
        placed = encode.place_remote_dicts(cols, [{"cpu": 10}, {"cpu": 10}])
        assert placed == ["w1", "w1"]

    def test_intra_cycle_accounting_consumes_capacity(self):
        cols = (("w0", {("fa", "cpu"): 15}, True),
                ("w1", {("fa", "cpu"): 100}, True))
        placed = encode.place_remote_dicts(
            cols, [{"cpu": 10}, {"cpu": 10}, {"cpu": 5}])
        # the second workload no longer fits w0's remaining 5
        assert placed == ["w0", "w1", "w0"]

    def test_mk_cluster_survives_compact_pack(self):
        import jax.numpy as jnp

        from kueue_tpu.solver.kernel import pack_decisions_impl
        W, P, R = 8, 1, 2
        out = {"admitted": jnp.zeros(W, bool), "fit": jnp.zeros(W, bool),
               "borrows": jnp.zeros(W, bool),
               "chosen": jnp.zeros((W, P, R), jnp.int32),
               "chosen_borrow": jnp.zeros((W, P, R), bool),
               "usage": jnp.zeros((2, 2, 2), jnp.int64),
               "cohort_usage": jnp.zeros((1, 2, 2), jnp.int64),
               "mk_cluster": jnp.full(W, -1, jnp.int32)}
        packed = pack_decisions_impl(out)
        assert "mk_cluster" in packed and "admitted" not in packed


def _mk_manager(clock, workers, quota_cpu=8, solver=None, worker_cpu=None):
    worker_mgrs = {}
    for name in workers:
        w = KueueManager(clock=clock)
        w.store.create(make_flavor("default"))
        w.store.create(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=worker_cpu or quota_cpu)).obj())
        w.store.create(make_local_queue("lq", "default", "cq"))
        w.run_until_idle()
        worker_mgrs[name] = w
    cfg = None
    if solver is not None:
        cfg = cfgpkg.Configuration()
        cfg.solver.enable = True
        cfg.solver.min_heads = 0
    mgr = KueueManager(cfg=cfg, clock=clock, solver=solver,
                       remote_clusters=worker_mgrs)
    for name in workers:
        mgr.store.create(asapi.MultiKueueCluster(
            metadata=ObjectMeta(name=name)))
    mgr.store.create(asapi.MultiKueueConfig(
        metadata=ObjectMeta(name="mk-config"),
        spec=asapi.MultiKueueConfigSpec(clusters=list(workers))))
    ac = api.AdmissionCheck(metadata=ObjectMeta(name="mk-check"))
    ac.spec.controller_name = MK_CONTROLLER
    ac.spec.parameters = api.AdmissionCheckParametersReference(
        kind="MultiKueueConfig", name="mk-config")
    mgr.store.create(ac)
    mgr.store.create(make_flavor("default"))
    mgr.store.create(ClusterQueueWrapper("cq").resource_group(
        flavor_quotas("default", cpu=quota_cpu))
        .admission_checks("mk-check").obj())
    mgr.store.create(make_local_queue("lq", "default", "cq"))
    mgr.run_until_idle()
    return mgr, worker_mgrs


class TestControllerExecutesPlacements:
    def test_single_cluster_matches_sequential_controller(self, clock):
        # Acceptance gate: on single-cluster traffic the batched-column
        # choice must equal the sequential controller's outcome (the
        # only cluster that CAN reserve) for every workload — and the
        # planned path must actually have executed (no mirror race).
        mgr, workers = _mk_manager(clock, ["w1"])
        for i in range(3):
            mgr.store.create(WorkloadWrapper(f"wl{i}").queue("lq")
                             .request("cpu", "2").obj())
        mgr.schedule_until_settled()
        workers["w1"].schedule_until_settled()
        mgr.run_until_idle()
        mk = mgr.multikueue
        assert mk.placements_executed >= 3
        for i in range(3):
            key = f"default/wl{i}"
            assert mk.planned.get(key) == "w1"
            # the sequential outcome: the reserving cluster recorded by
            # the first-reserve probe equals the batched choice
            assert mk._reserving.get(key) == "w1"

    def test_capacity_columns_mask_lost_cluster(self, clock):
        mgr, workers = _mk_manager(clock, ["w1", "w2"])
        cols, checks = mgr.multikueue.capacity_columns()
        assert [c[0] for c in cols] == ["w1", "w2"]
        assert checks == {"mk-check"}
        assert all(active for _, _, active in cols)
        assert all(caps for _, caps, _ in cols)
        mgr.multikueue.mark_cluster_lost("w1")
        cols, _ = mgr.multikueue.capacity_columns()
        byname = {c[0]: c for c in cols}
        assert byname["w1"][2] is False and not byname["w1"][1]
        assert byname["w2"][2] is True
        # snapshots pick the masked columns up immediately
        snap = mgr.cache.snapshot()
        assert {c[0]: c[2] for c in snap.remote_clusters} == {
            "w1": False, "w2": True}
        mgr.cache.release_snapshot(snap)
        # placement avoids the lost cluster
        mgr.store.create(WorkloadWrapper("late").queue("lq")
                         .request("cpu", "2").obj())
        mgr.schedule_until_settled()
        assert mgr.multikueue.planned.get("default/late") == "w2"

    def test_capacity_spills_to_second_cluster(self, clock):
        # w1's capacity exhausts mid-cycle; the batched greedy places
        # the overflow on w2 — one mirror each, no race. Local quota
        # admits all four in one cycle; each WORKER only holds two.
        mgr, workers = _mk_manager(clock, ["w1", "w2"], quota_cpu=8,
                                   worker_cpu=4)
        for i in range(4):  # 2 cpu each; w1 fits two, w2 takes the rest
            mgr.store.create(WorkloadWrapper(f"wl{i}").queue("lq")
                             .request("cpu", "2").obj())
        mgr.schedule_until_settled()
        placed = [mgr.multikueue.planned.get(f"default/wl{i}")
                  for i in range(4)]
        assert placed.count("w1") == 2 and placed.count("w2") == 2, placed
        for i in range(4):
            mirrors = [n for n, w in workers.items()
                       if w.store.try_get("Workload", "default", f"wl{i}")
                       is not None]
            assert mirrors == [mgr.multikueue.planned[f"default/wl{i}"]]

    def test_warm_ladder_covers_cluster_variants(self, clock):
        # The warm helpers must register the EXACT keys a
        # cluster-carrying dispatch computes (kdim = bucketed column
        # shape), or every MultiKueue deployment would compile each
        # variant mid-traffic on the admission thread.
        from kueue_tpu.solver import BatchSolver
        from kueue_tpu.solver.service import note_program
        solver = BatchSolver()
        mgr, _workers = _mk_manager(clock, ["w1", "w2"], solver=solver)
        snap = mgr.cache.snapshot()
        try:
            assert snap.remote_clusters and snap.mk_check_names
            ctx = solver.warm_setup(snap)
            assert ctx.cluster is not None
            kdim = ctx.cluster.ccap.shape
            variants = solver._cluster_variants(ctx)
            assert [v[1] for v in variants] == [None, kdim]
            solver.warm_bucket(ctx, 8, max_ranks=(8,))
            dims = solver._topo_dims(ctx.topo)
            compact = solver._compact_flag(ctx.topo)
            # the dispatch-site key for a cluster-carrying fused cycle
            # at this bucket must already be registered (False = no
            # mid-traffic compile would be counted)
            key = ("fused", dims, 8, solver.max_podsets, 8, False,
                   False, (), (), (), compact, kdim)
            assert note_program(key) is False, key
            # and the column-less twin too
            key_none = key[:-1] + (None,)
            assert note_program(key_none) is False
        finally:
            mgr.cache.release_snapshot(snap)
        mgr.scheduler.stop()

    def test_device_route_decodes_placements(self, clock):
        # End-to-end through the SOLVER route: the fused solve's
        # mk_cluster column drives the decode -> on_placement ->
        # controller execution chain.
        from kueue_tpu.solver import BatchSolver
        solver = BatchSolver()
        mgr, workers = _mk_manager(clock, ["w1"], solver=solver)
        for i in range(4):
            mgr.store.create(WorkloadWrapper(f"wl{i}").queue("lq")
                             .request("cpu", "2").obj())
        mgr.run_until_idle()
        for _ in range(10):  # the speculative pipeline collects lazily
            mgr.scheduler.schedule(timeout=0)
            mgr.run_until_idle()
            if len(mgr.multikueue.planned) == 4:
                break
        routes = set(mgr.scheduler.cycle_counts)
        assert any(r.startswith("device") for r in routes), routes
        # every admission carried a device-decoded placement
        for i in range(4):
            assert mgr.multikueue.planned.get(f"default/wl{i}") == "w1"
            mirrors = [n for n, w in workers.items()
                       if w.store.try_get("Workload", "default", f"wl{i}")
                       is not None]
            assert mirrors == ["w1"]
        mgr.scheduler.stop()
