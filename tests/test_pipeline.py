"""Always-on speculative admission pipeline (ISSUE 6).

Covers the explicit nominate/solve/apply stage contract
(scheduler/stages.py), the generation-token speculation protocol —
stamp at dispatch, validate at apply; mis-speculation abandons the
in-flight result and falls back to the synchronous path — the
admitted-set bit-equivalence with the synchronous oracle under
randomized churn (mis-speculation included), the shed-rung bounded
pipelining allowance, and the bench-env honesty refusal
(perf.checker.refuse_cross_backend). See scheduler/PIPELINE.md.
"""

import random

import pytest

from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import RAISE, FaultInjector
from kueue_tpu.scheduler import stages
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas

N_CQS = 4


def _setup(env):
    env.add_flavor("default")
    for i in range(N_CQS):
        env.add_cq(ClusterQueueWrapper(f"cq{i}").cohort("co")
                   .resource_group(flavor_quotas("default", cpu="8")).obj(),
                   f"lq-cq{i}")


def _wl(name, i, priority=0, creation=0.0, cpu="2"):
    return (WorkloadWrapper(name).queue(f"lq-cq{i}").priority(priority)
            .creation(creation).pod_set(count=1, cpu=cpu).obj())


def _submit_waves(env, waves, start_wave=0, cpu="2"):
    n = start_wave * N_CQS
    for wave in range(start_wave, start_wave + waves):
        for i in range(N_CQS):
            env.submit(_wl(f"w{wave}-{i}", i, creation=float(n), cpu=cpu))
            n += 1


def _pipelined_env():
    env = build_env(_setup, solver=True)
    env.scheduler.pipeline_enabled = True
    return env


def _quota_reserved_counts(env):
    counts: dict = {}
    for key, reason in env.client.events:
        if reason == "QuotaReserved":
            counts[key] = counts.get(key, 0) + 1
    return counts


class TestStageContract:
    def test_sync_cycle_through_typed_stages(self):
        """The synchronous cycle is the three-stage machine with typed
        hand-offs: nominate -> (solve) -> apply/requeue."""
        env = build_env(_setup, solver=False)
        _submit_waves(env, 1)
        s = env.scheduler
        heads = env.queues.heads(timeout=0)
        assert len(heads) == N_CQS
        snapshot = env.cache.snapshot()
        nom = s._stage_nominate(heads, snapshot, "cpu-forced", 0)
        assert isinstance(nom, stages.NominatedCycle)
        assert len(nom.entries) == N_CQS and nom.solver_entries == []
        s._stage_apply(nom, 0)
        applied = s._stage_requeue(nom)
        assert isinstance(applied, stages.AppliedCycle)
        assert applied.admitted == N_CQS and applied.success
        assert applied.regime == "fit" and not applied.blocked_preemptor
        assert len(env.client.applied) == N_CQS

    def test_inflight_cycle_is_typed_and_stamped(self):
        env = _pipelined_env()
        _submit_waves(env, 2)
        env.cycle()  # dispatch-only first pipelined cycle
        inflight = env.scheduler._inflight
        assert isinstance(inflight, stages.InFlightCycle)
        token = inflight.token
        assert isinstance(token, stages.SpeculationToken)
        assert token.epochs == env.cache.generation_token()
        assert token.resident is env.scheduler.solver._resident
        # arena-backed dispatch: the slot generations were captured
        assert token.slots is not None and token.slot_gens is not None
        ok, reason = token.validate(env.cache, env.scheduler.solver)
        assert ok and reason == ""
        env.cycle()
        while env.scheduler._inflight is not None:
            env.cycle()
        assert env.scheduler.speculation_hits > 0
        assert env.scheduler.speculation_aborts == 0


class TestSpeculationToken:
    """Each generation-token clause trips independently, and cheaply —
    never a snapshot comparison."""

    def _token_env(self):
        env = _pipelined_env()
        _submit_waves(env, 2)
        env.cycle()
        return env, env.scheduler._inflight.token

    def test_structural_epoch_moves_invalidate(self):
        env, token = self._token_env()
        env.add_cq(ClusterQueueWrapper("late-cq").resource_group(
            flavor_quotas("default", cpu="8")).obj(), "lq-late")
        ok, reason = token.validate(env.cache, env.scheduler.solver)
        assert not ok and reason == "topology-epoch"

    def test_residency_identity_invalidates(self):
        env, token = self._token_env()
        env.scheduler.solver.invalidate_resident()
        ok, reason = token.validate(env.cache, env.scheduler.solver)
        assert not ok and reason == "residency"

    def test_arena_slot_generation_invalidates(self):
        env, token = self._token_env()
        victim = env.scheduler._inflight.inflight.plan.batch.infos[0]
        # The queue-manager upsert delta bumps the slot generation even
        # before the next assemble() drains it.
        env.queues.add_or_update_workload(
            _wl(victim.obj.metadata.name, 0, priority=3, creation=999.0))
        ok, reason = token.validate(env.cache, env.scheduler.solver)
        assert not ok and reason == "arena-slots"

    def test_journal_overflow_invalidates(self):
        env, token = self._token_env()
        env.cache._journal_overflowed.add("solver")
        ok, reason = token.validate(env.cache, env.scheduler.solver)
        assert not ok and reason == "journal-overflow"

    def test_generations_current_is_the_cheap_check(self):
        from kueue_tpu.cache.incremental import generations_current
        env = build_env(_setup, solver=False)
        snap = env.cache.snapshot()
        assert generations_current(snap, env.cache)
        assert env.cache.snapshot_current(snap)
        env.add_flavor("late-flavor")
        assert not generations_current(snap, env.cache)
        assert not env.cache.snapshot_current(snap)


class TestMisSpeculationFallback:
    def test_topology_change_mid_flight_aborts_and_recovers(self):
        env = _pipelined_env()
        s = env.scheduler
        _submit_waves(env, 3)
        env.cycle()  # dispatch-only
        assert s._inflight is not None
        # Structural change while a cycle is in flight: the speculation
        # must abort BEFORE the next dispatch chains on doomed state.
        env.add_cq(ClusterQueueWrapper("late-cq").resource_group(
            flavor_quotas("default", cpu="8")).obj(), "lq-late")
        env.cycle()
        assert s.speculation_aborts == 1
        assert s.speculation_abort_reasons == {"topology-epoch": 1}
        for _ in range(8):
            env.cycle()
        # every workload admitted exactly once, despite the abort
        assert len(admitted_map(env)) == 12
        assert all(c == 1 for c in _quota_reserved_counts(env).values())
        # the abort annotated the cycle trace
        kinds = [a["kind"] for t in s.recorder.traces()
                 for a in t.annotations]
        assert "speculation-abort" in kinds

    def test_inflight_update_aborts_and_readmits_fresh_object(self):
        env = _pipelined_env()
        s = env.scheduler
        _submit_waves(env, 3)
        env.cycle()
        victim = s._inflight.inflight.plan.batch.infos[0]
        vname = victim.obj.metadata.name
        env.queues.add_or_update_workload(
            _wl(vname, 0, priority=5, creation=500.0))
        env.cycle()
        assert s.speculation_aborts == 1
        assert s.speculation_abort_reasons == {"arena-slots": 1}
        for _ in range(8):
            env.cycle()
        assert len(admitted_map(env)) == 12
        assert _quota_reserved_counts(env)[f"default/{vname}"] == 1
        # the admission reflects the FRESH object, not the stale one
        applied = env.client.applied[f"default/{vname}"]
        assert applied.spec.priority == 5

    def test_metrics_and_debug_surface(self):
        from kueue_tpu.metrics import Registry
        from kueue_tpu.obs import DebugEndpoints, pipeline_status
        env = _pipelined_env()
        s = env.scheduler
        s.metrics = Registry()
        _submit_waves(env, 3)
        env.cycle()
        env.add_flavor("late")  # flavor-spec epoch bump -> abort
        env.cycle()
        for _ in range(8):
            env.cycle()
        assert s.speculation_aborts >= 1 and s.speculation_hits >= 1
        assert s.metrics.speculation_aborts_total.value(
            reason="topology-epoch") >= 1
        assert s.metrics.speculation_hits_total.value() \
            == s.speculation_hits
        st = pipeline_status(s)
        assert st["enabled"] and st["speculation_aborts"] >= 1
        assert st["pipelined_hit_rate"] is not None
        ep = DebugEndpoints(s, s.metrics)
        payload = ep.handle("/debug/pipeline", {})
        # the endpoint additionally stamps the generation token it
        # rendered under (ISSUE 12 satellite)
        assert payload.pop("generation") == \
            list(s.cache.generation_token())
        assert payload == pipeline_status(s)
        text = s.metrics.dump()
        assert "kueue_scheduler_speculation_aborts_total" in text


class TestRandomizedChurnEquivalence:
    """ISSUE 6 acceptance: admitted-set bit-equivalence with the
    synchronous oracle under randomized churn, mis-speculation included
    (both organic — mid-flight updates — and injected)."""

    @staticmethod
    def _roomy_setup(env):
        # All-fit sizing (6 waves x 2cpu <= 16): pipelining's documented
        # deviation (heads pop before the previous cycle's requeues)
        # makes the admitted SUBSET under contention depend on in-flight
        # timing, which churn legitimately shifts — the invariant this
        # suite owns is bit-equivalence of the TOTAL admitted set plus
        # exactly-once admission across aborts (the chaos sweep uses the
        # same sizing rule for its pipelined variant).
        env.add_flavor("default")
        for i in range(N_CQS):
            env.add_cq(ClusterQueueWrapper(f"cq{i}").cohort("co")
                       .resource_group(
                           flavor_quotas("default", cpu="16")).obj(),
                       f"lq-cq{i}")

    @pytest.mark.parametrize("seed", [3, 17, 404])
    def test_random_churn_matches_sync_oracle(self, seed):
        rng = random.Random(seed)
        # deterministic schedule, identical for both runs: per cycle, a
        # submit wave, a set of (workload name, new priority) updates,
        # and completion of earlier admissions
        cycles = 14
        schedule = []
        for c in range(6):
            ups = []
            if c >= 1 and rng.random() < 0.7:
                wave = rng.randrange(0, c + 1)
                ups.append((f"w{wave}-{rng.randrange(N_CQS)}",
                            rng.randrange(1, 9)))
            schedule.append((True, ups))
        schedule += [(False, [])] * (cycles - len(schedule))
        inject_hits = sorted(rng.sample(range(8), 2))

        def run(pipeline):
            env = build_env(self._roomy_setup, solver=pipeline)
            env.scheduler.pipeline_enabled = pipeline
            injector = None
            if pipeline:
                injector = FaultInjector(
                    {faultinject.SITE_SPECULATION:
                     {h: RAISE for h in inject_hits}})
                faultinject.install(injector)
            try:
                n = 0
                for c, (submit, ups) in enumerate(schedule):
                    if submit:
                        _submit_waves(env, 1, start_wave=c)
                    for name, prio in ups:
                        i = int(name.split("-")[1])
                        env.queues.add_or_update_workload(
                            _wl(name, i, priority=prio,
                                creation=1000.0 + n))
                        n += 1
                    env.cycle()
                    env.clock.advance(1.0)
                for _ in range(10):
                    env.cycle()
                    env.clock.advance(1.0)
                    if env.scheduler._inflight is None \
                            and not env.queues.pending_total():
                        break
            finally:
                faultinject.uninstall()
            return env

        oracle = run(False)
        pipe = run(True)
        # bit-equivalence of the admitted set (all-fit sizing: the set
        # is total) and of the final per-CQ usage
        assert set(admitted_map(pipe)) == set(admitted_map(oracle))
        for i in range(N_CQS):
            assert pipe.usage(f"cq{i}") == oracle.usage(f"cq{i}")
        # nothing admitted twice, even across aborts
        assert all(c == 1 for c in _quota_reserved_counts(pipe).values())


class TestShedRungPipelining:
    def test_pipeline_survives_shed_rung_with_head_cap(self):
        from kueue_tpu.resilience.degrade import SHED, DegradationLadder
        env = _pipelined_env()
        s = env.scheduler
        s.ladder = DegradationLadder(budget_s=60.0, shed_heads=2,
                                     escalate_after=1, recovery_cycles=99,
                                     ewma_alpha=1.0)
        s.ladder.state = SHED
        _submit_waves(env, 2)
        for _ in range(10):
            env.cycle()
        # pipelining engaged WHILE degraded (the bounded allowance) and
        # the head cap still sheds
        assert s.cycle_counts.get("device-pipelined", 0) > 0
        assert s.shed_heads_requeued > 0
        assert len(admitted_map(env)) == 8  # nothing lost
        assert s.speculation_aborts == 0

    def test_preempt_needing_cycle_bails_to_sync_under_shed(self):
        from kueue_tpu.api import kueue as api
        from kueue_tpu.resilience.degrade import SHED, DegradationLadder

        def setup(env):
            env.add_flavor("default")
            for i in range(2):
                env.add_cq(
                    ClusterQueueWrapper(f"cq{i}")
                    .preemption(
                        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                    .resource_group(flavor_quotas("default", cpu="4"))
                    .obj(), f"lq-cq{i}")

        env = build_env(setup, solver=True)
        s = env.scheduler
        s.pipeline_enabled = True
        s.ladder = DegradationLadder(budget_s=60.0, shed_heads=8,
                                     escalate_after=1, recovery_cycles=99,
                                     ewma_alpha=1.0)
        s.ladder.state = SHED
        for i in range(2):
            env.admit_existing(
                WorkloadWrapper(f"victim{i}").queue(f"lq-cq{i}")
                .priority(0).pod_set(count=1, cpu="4")
                .reserve(f"cq{i}").obj())
            env.submit(WorkloadWrapper(f"preemptor{i}")
                       .queue(f"lq-cq{i}").priority(10)
                       .creation(float(i)).pod_set(count=1, cpu="4").obj())
        for _ in range(4):
            env.cycle()
        # shed defers preempt planning: the pipelined-mixed machinery
        # must not engage, and the deferral counters must
        assert "pipelined-preempt" not in s.cycle_counts
        assert s.preempt_plans_deferred > 0
        assert not env.client.evicted  # deferred, not planned


class TestIdleLadderRecovery:
    def test_idle_ticks_rung_the_scheduler_ladder_down(self):
        from kueue_tpu.resilience.degrade import (
            NORMAL, SURVIVAL, DegradationLadder)
        env = build_env(_setup, solver=False)
        s = env.scheduler
        s.ladder = DegradationLadder(budget_s=0.1, recovery_cycles=2)
        s.ladder.state = SURVIVAL
        # empty queue: each schedule() call is an idle tick
        for _ in range(4):
            env.cycle()
        assert s.ladder.state == NORMAL
        assert s.ladder.recoveries == 2
        assert s.ladder.idle_cycles == 4


class TestBenchEnvHonesty:
    def test_refuse_cross_backend(self):
        from kueue_tpu.perf import RangeSpec, refuse_cross_backend
        spec = RangeSpec(backend="tpu")
        assert refuse_cross_backend(
            spec, {"backend": "tpu", "cpu_fallback": False}) is None
        r = refuse_cross_backend(
            spec, {"backend": "tpu", "cpu_fallback": True})
        assert r is not None and "refused" in r
        r = refuse_cross_backend(
            spec, {"backend": "cpu", "cpu_fallback": False})
        assert r is not None and "refused" in r
        # backend-agnostic specs (the default) always compare
        assert refuse_cross_backend(
            RangeSpec(), {"backend": "cpu", "cpu_fallback": True}) is None


class TestReconcileEventSplit:
    def test_workload_reconcile_feeds_per_event_histogram(self):
        from kueue_tpu.manager import KueueManager
        from tests.wrappers import make_flavor, make_local_queue
        mgr = KueueManager()
        mgr.store.create(make_flavor("default"))
        mgr.store.create(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=8)).obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.store.create(WorkloadWrapper("w").queue("lq")
                         .pod_set(count=1, cpu="2").obj())
        mgr.run_until_idle()
        mgr.schedule_once()
        h = mgr.metrics.reconcile_event_seconds
        # the coarse series still aggregates per controller...
        assert mgr.metrics.reconcile_seconds.count(
            controller="workload") > 0
        # ...and the split now attributes events inside the reconcile
        assert h.count(controller="workload", event="sync-admitted") > 0
        assert "kueue_reconcile_event_seconds" in mgr.metrics.dump()
