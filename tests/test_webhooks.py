"""Webhook defaulting/validation tests (reference: pkg/webhooks/*_test.go
and per-job webhook suites, SURVEY.md §2.5/L6)."""

import pytest

from kueue_tpu.api import batchv1, corev1, kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import FakeClock, ObjectMeta
from kueue_tpu.manager import KueueManager
from kueue_tpu.sim import Invalid
from kueue_tpu import webhooks

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def mgr():
    return KueueManager(clock=FakeClock(1000.0))


def cq_with_quota(name="cq", cohort="", **kwargs):
    cq = ClusterQueueWrapper(name).resource_group(
        flavor_quotas("default", cpu=4)).obj()
    cq.spec.cohort = cohort
    return cq


class TestClusterQueueValidation:
    def test_valid_cq_accepted(self, mgr):
        mgr.store.create(cq_with_quota())

    def test_borrowing_limit_requires_cohort(self, mgr):
        cq = ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=(4, 2))).obj()
        with pytest.raises(Invalid, match="borrowingLimit.*cohort"):
            mgr.store.create(cq)

    def test_lending_limit_above_nominal_rejected(self, mgr):
        cq = ClusterQueueWrapper("cq").cohort("team").resource_group(
            flavor_quotas("default", cpu=(4, None, 8))).obj()
        with pytest.raises(Invalid, match="lendingLimit"):
            mgr.store.create(cq)

    def test_duplicate_flavor_across_groups_rejected(self, mgr):
        cq = (ClusterQueueWrapper("cq")
              .resource_group(flavor_quotas("default", cpu=4))
              .resource_group(flavor_quotas("default", memory="1Gi")).obj())
        with pytest.raises(Invalid, match="already used"):
            mgr.store.create(cq)

    def test_checks_xor_strategy(self, mgr):
        cq = cq_with_quota()
        cq.spec.admission_checks = ["a"]
        cq.spec.admission_checks_strategy = [
            api.AdmissionCheckStrategyRule(name="b")]
        with pytest.raises(Invalid, match="either admissionChecks or"):
            mgr.store.create(cq)

    def test_reclaim_never_with_borrow_within_cohort(self, mgr):
        cq = cq_with_quota(cohort="team")
        cq.spec.preemption = api.ClusterQueuePreemption(
            reclaim_within_cohort=api.PREEMPTION_NEVER,
            borrow_within_cohort=api.BorrowWithinCohort(
                policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY))
        with pytest.raises(Invalid, match="reclaimWithinCohort=Never"):
            mgr.store.create(cq)

    def test_flavor_resources_must_match_covered(self, mgr):
        cq = api.ClusterQueue(metadata=ObjectMeta(name="cq"))
        cq.spec.namespace_selector = api.LabelSelector()
        cq.spec.resource_groups = [api.ResourceGroup(
            covered_resources=["cpu", "memory"],
            flavors=[api.FlavorQuotas(name="f", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=1)])])]
        with pytest.raises(Invalid, match="must match coveredResources"):
            mgr.store.create(cq)


class TestWorkloadValidation:
    def test_single_podset_defaulted_to_main(self, mgr):
        wl = api.Workload(metadata=ObjectMeta(name="w", namespace="default"))
        wl.spec.queue_name = "lq"
        wl.spec.pod_sets = [api.PodSet(name="", count=1)]
        created = mgr.store.create(wl)
        assert created.spec.pod_sets[0].name == "main"

    def test_multiple_min_count_rejected(self, mgr):
        wl = WorkloadWrapper("w").queue("lq") \
            .pod_set(name="a", count=2, min_count=1) \
            .pod_set(name="b", count=2, min_count=1).obj()
        with pytest.raises(Invalid, match="at most one podSet"):
            mgr.store.create(wl)

    def test_pods_resource_reserved(self, mgr):
        wl = WorkloadWrapper("w").queue("lq").request("pods", 1).obj()
        with pytest.raises(Invalid, match="reserved"):
            mgr.store.create(wl)

    def test_podsets_immutable_after_reservation(self, mgr):
        mgr.store.create(make_flavor("default"))
        mgr.store.create(cq_with_quota())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_until_settled()
        got = mgr.store.get("Workload", "default", "w")
        got.spec.pod_sets[0].count = 5
        with pytest.raises(Invalid, match="immutable"):
            mgr.store.update(got)

    def test_admission_fields_immutable(self, mgr):
        mgr.store.create(make_flavor("default"))
        mgr.store.create(cq_with_quota())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.store.create(WorkloadWrapper("w").queue("lq").request("cpu", "1").obj())
        mgr.schedule_until_settled()
        got = mgr.store.get("Workload", "default", "w")
        got.status.admission.cluster_queue = "other"
        with pytest.raises(Invalid, match="admission"):
            mgr.store.update(got)

    def test_reclaimable_cannot_decrease(self, mgr):
        mgr.store.create(make_flavor("default"))
        mgr.store.create(cq_with_quota())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.store.create(
            WorkloadWrapper("w").queue("lq").pod_set(count=3)
            .request("cpu", "1").obj())
        mgr.schedule_until_settled()
        got = mgr.store.get("Workload", "default", "w")
        got.status.reclaimable_pods = [api.ReclaimablePod(name="main", count=2)]
        mgr.store.update(got)
        got = mgr.store.get("Workload", "default", "w")
        got.status.reclaimable_pods = [api.ReclaimablePod(name="main", count=1)]
        with pytest.raises(Invalid, match="cannot be less"):
            mgr.store.update(got)


class TestJobAndPodWebhooks:
    def test_queued_job_created_suspended(self, mgr):
        job = batchv1.Job(metadata=ObjectMeta(
            name="j", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        job.spec.suspend = False  # user forgot; webhook enforces
        job.spec.template = PodTemplateSpec(spec=PodSpec(
            containers=[Container(requests={"cpu": 1000})]))
        created = mgr.store.create(job)
        assert created.spec.suspend

    def test_queue_change_rejected_while_running(self, mgr):
        job = batchv1.Job(metadata=ObjectMeta(name="j", namespace="default"))
        job.spec.suspend = False
        mgr.store.create(job)
        got = mgr.store.get("Job", "default", "j")
        got.metadata.labels[api.QUEUE_LABEL] = "lq2"
        with pytest.raises(Invalid, match="must not be changed"):
            mgr.store.update(got)

    def test_pod_gets_gated_on_create(self, mgr):
        pod = corev1.Pod(metadata=ObjectMeta(
            name="p", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        created = mgr.store.create(pod)
        assert api.ADMISSION_GATE in created.spec.scheduling_gates
        assert created.metadata.labels[api.MANAGED_LABEL] == "true"

    def test_pod_in_excluded_namespace_not_gated(self, mgr):
        pod = corev1.Pod(metadata=ObjectMeta(
            name="p", namespace="kube-system", labels={api.QUEUE_LABEL: "lq"}))
        created = mgr.store.create(pod)
        assert created.spec.scheduling_gates == []

    def test_local_queue_cq_immutable(self, mgr):
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        got = mgr.store.get("LocalQueue", "default", "lq")
        got.spec.cluster_queue = "other"
        with pytest.raises(Invalid, match="immutable"):
            mgr.store.update(got)

    def test_resource_flavor_bad_taint_rejected(self, mgr):
        from kueue_tpu.api.corev1 import Taint
        rf = make_flavor("f", taints=[Taint(key="", effect="Bogus")])
        with pytest.raises(Invalid):
            mgr.store.create(rf)
