"""Hierarchical (nested) cohort trees: cache math, scheduler borrowing,
preemption reclaim, and solver differential conformance.

The v1alpha1 Cohort CRD forms arbitrary-depth trees
(reference: apis/kueue/v1alpha1/cohort_types.go:26-100); quota math walks
the chain to the root (reference: pkg/cache/resource_node.go:89-146).
"""

from kueue_tpu.api import kueue as api
from kueue_tpu.core.resources import FlavorResource
from tests.test_scheduler import Env
from tests.test_solver import admitted_map, assert_differential, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas

CPU = "cpu"
FR = FlavorResource("default", CPU)


def three_level_env(env):
    """root <- {left, right}; a under left, b under right (quota only on
    the CQs: each subtree lends everything)."""
    env.add_flavor("default")
    env.add_cohort("root")
    env.add_cohort("left", "root")
    env.add_cohort("right", "root")
    env.add_cq(ClusterQueueWrapper("a").cohort("left")
               .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-a")
    env.add_cq(ClusterQueueWrapper("b").cohort("right")
               .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")


class TestNestedCohortCache:
    def test_subtree_quota_aggregation(self):
        env = Env()
        three_level_env(env)
        hm = env.cache.hm
        root = hm.cohorts["root"].payload
        left = hm.cohorts["left"].payload
        assert left.resource_node.subtree_quota[FR] == 10000
        assert root.resource_node.subtree_quota[FR] == 20000

    def test_usage_bubbles_to_root(self):
        env = Env()
        three_level_env(env)
        wl = (WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="14")
              .reserve("a").obj())
        env.cache.add_or_update_workload(wl)
        hm = env.cache.hm
        # a has guaranteed 0 => all 14 bubble into left, then root
        assert hm.cohorts["left"].payload.resource_node.usage[FR] == 14000
        assert hm.cohorts["root"].payload.resource_node.usage[FR] == 14000

    def test_mid_cohort_lending_limit(self):
        """left holds its own quota (5) with lendingLimit 2: the root only
        sees 2 of left's 15-unit subtree."""
        env = Env()
        env.add_flavor("default")
        env.add_cohort("root")
        env.add_cohort("left", "root", flavor_quotas("default", cpu=("5", None, "2")))
        env.add_cohort("right", "root")
        env.add_cq(ClusterQueueWrapper("a").cohort("left")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("right")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")
        hm = env.cache.hm
        left = hm.cohorts["left"].payload
        root = hm.cohorts["root"].payload
        assert left.resource_node.subtree_quota[FR] == 15000
        assert left.resource_node.guaranteed_quota(FR) == 13000
        # root subtree = (left 15 - guaranteed 13) + right 10 = 12
        assert root.resource_node.subtree_quota[FR] == 12000

    def test_reparent_refreshes_old_tree(self):
        env = Env()
        three_level_env(env)
        hm = env.cache.hm
        # move right out from under root
        env.add_cohort("right", "")
        assert hm.cohorts["root"].payload.resource_node.subtree_quota[FR] == 10000
        assert hm.cohorts["right"].payload.resource_node.subtree_quota[FR] == 10000


class TestNestedCohortInvalidation:
    def test_tree_wide_generation_aggregate(self):
        """A capacity change anywhere in a tree must be visible from every
        cohort in it (flavor-resume invalidation across subtrees), and
        the generation must grow monotonically."""
        env = Env()
        three_level_env(env)
        snap1 = env.cache.snapshot()
        gens1 = {c.name: c.allocatable_resource_generation
                 for c in (snap1.cluster_queues["a"].cohort,
                           snap1.cluster_queues["b"].cohort)}
        assert gens1["left"] == gens1["right"]  # shared capacity version
        # finishing a workload in b bumps b's generation only
        wl = (WorkloadWrapper("w").queue("lq-b").pod_set(count=1, cpu="4")
              .reserve("b").obj())
        env.cache.add_or_update_workload(wl)
        env.cache.delete_workload(wl)
        snap2 = env.cache.snapshot()
        assert (snap2.cluster_queues["a"].cohort.allocatable_resource_generation
                > gens1["left"])

    def test_generation_monotonic_across_tree_shrink(self):
        """Detaching a subtree must not make generations go backwards —
        stored resume state compares with `>` and would never invalidate
        again (the shrink-then-edit trap)."""
        env = Env()
        three_level_env(env)
        g1 = env.cache.snapshot().cluster_queues["a"].cohort \
            .allocatable_resource_generation
        env.add_cohort("right", "")  # tree shrinks
        g2 = env.cache.snapshot().cluster_queues["a"].cohort \
            .allocatable_resource_generation
        assert g2 > g1
        env.add_cohort("root", "", flavor_quotas("default", cpu="50"))
        g3 = env.cache.snapshot().cluster_queues["a"].cohort \
            .allocatable_resource_generation
        assert g3 > g2

    def test_solver_topology_invalidated_by_reparent(self):
        """Cohort re-parents don't bump CQ generations; the solver's
        topology cache must still refresh (cohort_epoch)."""
        from kueue_tpu.solver import BatchSolver
        env = Env()
        three_level_env(env)
        solver = BatchSolver()
        topo1, _ = solver._topology(env.cache.snapshot())
        assert topo1.cq_chain.shape[1] == 2
        env.add_cohort("right", "")  # detach right from root
        topo2, _ = solver._topology(env.cache.snapshot())
        assert topo2 is not topo1
        # b's chain no longer reaches root
        qi = topo2.cq_index["b"]
        assert topo2.cohort_names[topo2.cq_chain[qi, 0]] == "right"
        assert (topo2.cq_chain.shape[1] == 1
                or topo2.cq_chain[qi, 1] == -1)


class TestCohortLifecycleEdgeCases:
    def test_cycle_reparent_leaves_tree_intact(self):
        """a <- b <- c, then updating b to parent=c must raise and leave
        the old tree's aggregation consistent."""
        import pytest
        from tests.wrappers import make_cohort
        env = Env()
        env.add_flavor("default")
        env.add_cohort("a")
        env.add_cohort("b", "a")
        env.add_cohort("c", "b")
        env.add_cq(ClusterQueueWrapper("q1").cohort("c")
                   .resource_group(flavor_quotas("default", cpu="4")).obj(), "lq1")
        with pytest.raises(ValueError, match="cycle"):
            env.cache.add_or_update_cohort(make_cohort("b", "c"))
        hm = env.cache.hm
        assert hm.cohorts["b"].parent.name == "a"
        assert hm.cohorts["a"].payload.resource_node.subtree_quota[FR] == 4000

    def test_cohort_quota_edit_invalidates_flavor_resume(self):
        """Raising a Cohort's own quota bumps no CQ generation but must
        still invalidate cached last-assignment state (cohort_epoch is
        folded into the snapshot cohort generation)."""
        env = Env()
        three_level_env(env)
        gen1 = env.cache.snapshot().cluster_queues["a"].cohort \
            .allocatable_resource_generation
        env.add_cohort("root", "", flavor_quotas("default", cpu="50"))
        gen2 = env.cache.snapshot().cluster_queues["a"].cohort \
            .allocatable_resource_generation
        assert gen2 != gen1


class TestNestedCohortScheduling:
    def test_borrow_across_subtrees(self):
        """a (nominal 10) admits a 16-cpu workload by borrowing b's
        capacity through the root — invisible to a flat two-level tree."""
        env = Env()
        three_level_env(env)
        env.submit(WorkloadWrapper("w").queue("lq-a")
                   .pod_set(count=1, cpu="16").obj())
        env.cycle()
        assert "default/w" in env.client.applied

    def test_reclaim_across_subtrees(self):
        """b borrows via the root; a reclaims its nominal quota by
        preempting the borrower in the sibling subtree."""
        env = Env()
        env.add_flavor("default")
        env.add_cohort("root")
        env.add_cohort("left", "root")
        env.add_cohort("right", "root")
        env.add_cq(ClusterQueueWrapper("a").cohort("left")
                   .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("right")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")
        borrower = (WorkloadWrapper("borrower").queue("lq-b")
                    .pod_set(count=1, cpu="14").reserve("b").obj())
        env.admit_existing(borrower)
        env.submit(WorkloadWrapper("claimant").queue("lq-a").priority(10)
                   .pod_set(count=1, cpu="10").obj())
        env.cycle()
        evicted = env.client.evicted.get("default/borrower")
        assert evicted is not None
        assert any(c.type == api.WORKLOAD_EVICTED and c.status == "True"
                   for c in evicted.status.conditions)


class TestNestedCohortSolverDifferential:
    def test_three_level_borrow(self):
        def workloads():
            return [WorkloadWrapper("w").queue("lq-a")
                    .pod_set(count=1, cpu="16").obj()]

        result = assert_differential(three_level_env, workloads)
        assert set(result) == {"default/w"}

    def test_three_level_contention(self):
        """Both subtrees race for the root's shared capacity; intra-cycle
        accounting must bubble through the tree identically."""
        def workloads():
            return [
                WorkloadWrapper("w1").queue("lq-a").priority(5).creation(1)
                .pod_set(count=1, cpu="16").obj(),
                WorkloadWrapper("w2").queue("lq-b").priority(1).creation(2)
                .pod_set(count=1, cpu="16").obj(),
            ]

        result = assert_differential(three_level_env, workloads)
        assert set(result) == {"default/w1"}

    def test_mid_cohort_lending_limit_capped_borrow(self):
        """b can take at most 2 units of left's subtree (lendingLimit):
        12 fits, 13 does not."""
        def setup(env):
            env.add_flavor("default")
            env.add_cohort("root")
            env.add_cohort("left", "root",
                           flavor_quotas("default", cpu=("5", None, "2")))
            env.add_cohort("right", "root")
            env.add_cq(ClusterQueueWrapper("a").cohort("left")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("right")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq-b")

        def workloads():
            return [
                WorkloadWrapper("too-big").queue("lq-b").creation(1)
                .pod_set(count=1, cpu="13").obj(),
                WorkloadWrapper("fits").queue("lq-b").creation(2)
                .pod_set(count=1, cpu="12").obj(),
            ]

        result = assert_differential(setup, workloads, cycles=2)
        assert set(result) == {"default/fits"}

    def test_four_level_chain(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cohort("t0")
            env.add_cohort("t1", "t0")
            env.add_cohort("t2", "t1")
            env.add_cq(ClusterQueueWrapper("deep").cohort("t2")
                       .resource_group(flavor_quotas("default", cpu="2")).obj(),
                       "lq-deep")
            env.add_cq(ClusterQueueWrapper("top").cohort("t0")
                       .resource_group(flavor_quotas("default", cpu="8")).obj(),
                       "lq-top")

        def workloads():
            return [WorkloadWrapper("w").queue("lq-deep")
                    .pod_set(count=1, cpu="9").obj()]

        result = assert_differential(setup, workloads)
        assert set(result) == {"default/w"}

    def test_mixed_depths_random(self):
        """Random forest: flat cohorts, nested trees and cohortless CQs in
        one cycle."""
        import random
        for seed in range(8):
            rng = random.Random(1000 + seed)
            quotas = [rng.choice([2, 5, 10]) for _ in range(5)]

            def setup(env, quotas=quotas):
                env.add_flavor("default")
                env.add_cohort("root")
                env.add_cohort("mid", "root")
                env.add_cohort("flat")  # single-level cohort
                homes = ["root", "mid", "flat", ""]
                for i in range(5):
                    home = homes[i % len(homes)]
                    w = ClusterQueueWrapper(f"cq{i}")
                    if home:
                        w = w.cohort(home)
                    env.add_cq(w.resource_group(
                        flavor_quotas("default", cpu=str(quotas[i]))).obj(),
                        f"lq-cq{i}")

            specs = [(f"w{i}", f"lq-cq{rng.randrange(5)}", rng.randint(0, 3),
                      float(i), str(rng.choice([1, 2, 4, 7, 12])))
                     for i in range(rng.randint(4, 10))]

            def workloads(specs=specs):
                return [WorkloadWrapper(n).queue(q).priority(p).creation(ts)
                        .pod_set(count=1, cpu=c).obj()
                        for n, q, p, ts, c in specs]

            assert_differential(setup, workloads)


class TestNestedCohortShardedSolve:
    def test_sharded_nested_matches(self):
        """Conflict domains are root cohorts: two trees + lone CQs shard
        cleanly across the 8-device mesh."""
        from kueue_tpu.parallel.mesh import make_mesh

        def setup(env):
            env.add_flavor("default")
            for t in ("t0", "t1"):
                env.add_cohort(f"{t}-root")
                env.add_cohort(f"{t}-mid", f"{t}-root")
                env.add_cq(ClusterQueueWrapper(f"{t}-deep").cohort(f"{t}-mid")
                           .resource_group(flavor_quotas("default", cpu="4")).obj(),
                           f"lq-{t}-deep")
                env.add_cq(ClusterQueueWrapper(f"{t}-top").cohort(f"{t}-root")
                           .resource_group(flavor_quotas("default", cpu="4")).obj(),
                           f"lq-{t}-top")

        def workloads():
            out = []
            for i, t in enumerate(("t0", "t1")):
                out.append(WorkloadWrapper(f"w-{t}-deep").queue(f"lq-{t}-deep")
                           .priority(2).creation(i)
                           .pod_set(count=1, cpu="6").obj())
                out.append(WorkloadWrapper(f"w-{t}-top").queue(f"lq-{t}-top")
                           .priority(1).creation(10 + i)
                           .pod_set(count=1, cpu="4").obj())
            return out

        env_single = build_env(setup, solver=True)
        env_sharded = build_env(setup, solver=True)
        env_sharded.scheduler.solver.mesh = make_mesh()
        env_cpu = build_env(setup, solver=False)
        for env in (env_single, env_sharded, env_cpu):
            for w in workloads():
                env.submit(w)
            env.cycle()
        assert (admitted_map(env_single) == admitted_map(env_sharded)
                == admitted_map(env_cpu))
