"""AdmissionCheck controller tests: provisioning + MultiKueue.

Plays the role of the reference's
test/integration/controller/admissionchecks and
test/integration/multikueue suites (two envtest instances in one
process -> two KueueManagers in one process, SURVEY.md §4).
"""

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import autoscaling as asapi
from kueue_tpu.api import batchv1, kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import Condition, FakeClock, ObjectMeta, find_condition, set_condition
from kueue_tpu.controller.admissionchecks.multikueue import (
    CONTROLLER_NAME as MK_CONTROLLER,
    ORIGIN_LABEL,
)
from kueue_tpu.controller.admissionchecks.provisioning import (
    CONTROLLER_NAME as PROV_CONTROLLER,
    CONSUME_ANNOTATION,
)
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


def setup_cluster(mgr, check_names=()):
    mgr.store.create(make_flavor("default"))
    cq = ClusterQueueWrapper("cq").resource_group(flavor_quotas("default", cpu=8))
    if check_names:
        cq = cq.admission_checks(*check_names)
    mgr.store.create(cq.obj())
    mgr.store.create(make_local_queue("lq", "default", "cq"))
    mgr.run_until_idle()


class TestProvisioning:
    def make_mgr(self, clock):
        mgr = KueueManager(clock=clock)
        mgr.store.create(asapi.ProvisioningRequestConfig(
            metadata=ObjectMeta(name="prov-config"),
            spec=asapi.ProvisioningRequestConfigSpec(
                provisioning_class_name="queued-provisioning.gke.io")))
        ac = api.AdmissionCheck(metadata=ObjectMeta(name="prov-check"))
        ac.spec.controller_name = PROV_CONTROLLER
        ac.spec.parameters = api.AdmissionCheckParametersReference(
            kind="ProvisioningRequestConfig", name="prov-config")
        mgr.store.create(ac)
        setup_cluster(mgr, ["prov-check"])
        return mgr

    def submit(self, mgr):
        mgr.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        mgr.schedule_until_settled()
        return mgr.store.get("Workload", "default", "w")

    def test_request_created_after_quota_reservation(self, clock):
        mgr = self.make_mgr(clock)
        wl = self.submit(mgr)
        assert wlpkg.has_quota_reservation(wl)
        assert not wlpkg.is_admitted(wl)  # gated on the check
        pr = mgr.store.get("ProvisioningRequest", "default", "w-prov-check")
        assert pr.spec.provisioning_class_name == "queued-provisioning.gke.io"
        assert pr.spec.pod_sets[0].count == 1
        # pod template object exists
        assert mgr.store.get("PodTemplate", "default",
                             "ppt-w-prov-check-main") is not None

    def test_provisioned_flips_check_ready_with_podset_updates(self, clock):
        mgr = self.make_mgr(clock)
        self.submit(mgr)
        pr = mgr.store.get("ProvisioningRequest", "default", "w-prov-check")
        set_condition(pr.status.conditions, Condition(
            type=asapi.PROVISIONED, status="True", reason="Provisioned"),
            clock.now())
        mgr.store.update(pr)
        mgr.schedule_until_settled()
        wl = mgr.store.get("Workload", "default", "w")
        state = wlpkg.find_admission_check(wl, "prov-check")
        assert state.state == api.CHECK_STATE_READY
        assert state.pod_set_updates[0].annotations[CONSUME_ANNOTATION] == \
            "w-prov-check"
        assert wlpkg.is_admitted(wl)

    def test_failed_retries_with_backoff_then_rejects(self, clock):
        mgr = self.make_mgr(clock)
        self.submit(mgr)

        def fail_current(name):
            pr = mgr.store.get("ProvisioningRequest", "default", name)
            set_condition(pr.status.conditions, Condition(
                type=asapi.FAILED, status="True", reason="NotEnoughCapacity",
                message="no capacity"), clock.now())
            mgr.store.update(pr)
            mgr.run_until_idle()

        fail_current("w-prov-check")
        # in backoff: no second attempt yet
        assert mgr.store.try_get("ProvisioningRequest", "default",
                                 "w-prov-check-attempt2") is None
        mgr.advance(60.0)  # base backoff elapsed, jitter remains
        assert mgr.store.try_get("ProvisioningRequest", "default",
                                 "w-prov-check-attempt2") is None
        mgr.advance(13.0)  # past base 60s x max jitter 1.2 for attempt 1
        assert mgr.store.try_get("ProvisioningRequest", "default",
                                 "w-prov-check-attempt2") is not None
        fail_current("w-prov-check-attempt2")
        mgr.advance(145.0)  # past 120s x 1.2
        fail_current("w-prov-check-attempt3")
        mgr.advance(289.0)  # past 240s x 1.2
        # 3 retries exhausted after the 4th attempt fails -> Rejected ->
        # workload deactivated by the check-based eviction
        fail_current("w-prov-check-attempt4")
        mgr.run_until_idle()
        wl = mgr.store.get("Workload", "default", "w")
        assert not wl.spec.active

    def test_retry_backoff_jitter_desynchronizes_workloads(self, clock):
        # ISSUE 5 satellite: pure base * 2^(attempt-1) synchronized the
        # retry storm across every workload that failed together (one
        # capacity outage fails a whole wave at the same transition
        # time). The seeded per-(workload, check, attempt) jitter
        # spreads them — deterministically, so fake-clock tests stay
        # reproducible.
        from kueue_tpu.controller.admissionchecks.provisioning import (
            ProvisioningController, _jitter_fraction)
        ctrl = ProvisioningController(store=None, recorder=None,
                                      clock=clock)
        b1 = ctrl._backoff_seconds("wl-a", "chk", 1)
        b2 = ctrl._backoff_seconds("wl-b", "chk", 1)
        # stable per key, different across workloads, bounded
        assert b1 == ctrl._backoff_seconds("wl-a", "chk", 1)
        assert b1 != b2
        for b in (b1, b2):
            assert 60.0 <= b < 60.0 * 1.2
        # attempt 2 doubles the base, keeps its own jitter draw
        b1a2 = ctrl._backoff_seconds("wl-a", "chk", 2)
        assert 120.0 <= b1a2 < 144.0
        # jitter=0 restores the pure exponential schedule
        plain = ProvisioningController(store=None, recorder=None,
                                       clock=clock, backoff_jitter=0.0)
        assert plain._backoff_seconds("wl-a", "chk", 1) == 60.0
        assert plain._backoff_seconds("wl-b", "chk", 3) == 240.0
        # the fraction itself is uniform-ish and seed-keyed
        assert _jitter_fraction(0, "k") != _jitter_fraction(1, "k")
        assert 0.0 <= _jitter_fraction(0, "k") < 1.0


class TestMultiKueue:
    def make_clusters(self, clock):
        worker1 = KueueManager(clock=clock)
        worker2 = KueueManager(clock=clock)
        setup_cluster(worker1)
        setup_cluster(worker2)
        manager = KueueManager(clock=clock, remote_clusters={
            "worker1": worker1, "worker2": worker2})
        for name in ("worker1", "worker2"):
            manager.store.create(asapi.MultiKueueCluster(
                metadata=ObjectMeta(name=name)))
        manager.store.create(asapi.MultiKueueConfig(
            metadata=ObjectMeta(name="mk-config"),
            spec=asapi.MultiKueueConfigSpec(clusters=["worker1", "worker2"])))
        ac = api.AdmissionCheck(metadata=ObjectMeta(name="mk-check"))
        ac.spec.controller_name = MK_CONTROLLER
        ac.spec.parameters = api.AdmissionCheckParametersReference(
            kind="MultiKueueConfig", name="mk-config")
        manager.store.create(ac)
        setup_cluster(manager, ["mk-check"])
        return manager, worker1, worker2

    def run_all(self, manager, worker1, worker2, cycles=3):
        for _ in range(cycles):
            manager.schedule_until_settled()
            worker1.schedule_until_settled()
            worker2.schedule_until_settled()
            manager.run_until_idle()

    def test_first_reserving_cluster_wins(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        manager.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        manager.schedule_until_settled()
        # Batched-column placement (ISSUE 13): admission scored the
        # remote capacity columns and the controller executed the
        # decision — exactly ONE mirror (the planned cluster), not the
        # reference's mirror-everywhere race.
        mirrors = [w for w in (worker1, worker2)
                   if w.store.try_get("Workload", "default", "w") is not None]
        assert len(mirrors) == 1
        assert manager.multikueue.planned.get("default/w") in (
            "worker1", "worker2")
        assert manager.multikueue.placements_executed >= 1
        mirrored = mirrors[0].store.get("Workload", "default", "w")
        assert mirrored.metadata.labels[ORIGIN_LABEL] == "multikueue"
        # workers schedule; one reserves; the other mirror is deleted
        self.run_all(manager, worker1, worker2)
        wl = manager.store.get("Workload", "default", "w")
        state = wlpkg.find_admission_check(wl, "mk-check")
        assert state.state == api.CHECK_STATE_READY
        assert "got reservation on" in state.message
        assert wlpkg.is_admitted(wl)
        remaining = [w for w in (worker1, worker2)
                     if w.store.try_get("Workload", "default", "w") is not None]
        assert len(remaining) == 1

    def test_remote_finish_copied_back(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        manager.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        manager.schedule_until_settled()
        self.run_all(manager, worker1, worker2)
        winner = next(w for w in (worker1, worker2)
                      if w.store.try_get("Workload", "default", "w") is not None)
        remote_wl = winner.store.get("Workload", "default", "w")
        set_condition(remote_wl.status.conditions, Condition(
            type=api.WORKLOAD_FINISHED, status="True", reason="Succeeded",
            message="remote done"), clock.now())
        winner.store.update(remote_wl)
        manager.run_until_idle()
        wl = manager.store.get("Workload", "default", "w")
        assert wlpkg.is_finished(wl)
        fin = find_condition(wl.status.conditions, api.WORKLOAD_FINISHED)
        assert fin.message == "remote done"

    def test_worker_lost_triggers_retry_after_timeout(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        manager.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        manager.schedule_until_settled()
        self.run_all(manager, worker1, worker2)
        winner = next(w for w in (worker1, worker2)
                      if w.store.try_get("Workload", "default", "w") is not None)
        # the worker loses the workload entirely
        wl = winner.store.get("Workload", "default", "w")
        wl.metadata.finalizers = []
        winner.store.update(wl)
        winner.store.delete("Workload", "default", "w")
        manager.run_until_idle()
        # before the timeout the check stays Ready
        state = wlpkg.find_admission_check(
            manager.store.get("Workload", "default", "w"), "mk-check")
        assert state.state == api.CHECK_STATE_READY
        manager.advance(15 * 60.0 + 1)
        state = wlpkg.find_admission_check(
            manager.store.get("Workload", "default", "w"), "mk-check")
        assert state.state == api.CHECK_STATE_RETRY

    def test_batch_job_synced_to_remote(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        job = batchv1.Job(metadata=ObjectMeta(
            name="train", namespace="default",
            labels={api.QUEUE_LABEL: "lq"}))
        job.spec.parallelism = 1
        job.spec.template = PodTemplateSpec(spec=PodSpec(
            containers=[Container(requests={"cpu": 1000})]))
        manager.store.create(job)
        manager.schedule_until_settled()
        self.run_all(manager, worker1, worker2)
        winner = next(w for w in (worker1, worker2)
                      if w.store.try_get("Workload", "default",
                                         manager.store.list("Workload")[0].metadata.name))
        remote_job = winner.store.try_get("Job", "default", "train")
        assert remote_job is not None
        assert remote_job.metadata.labels[ORIGIN_LABEL] == "multikueue"

    def test_gc_orphans(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        manager.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        manager.schedule_until_settled()
        # delete the local workload; remote mirrors are orphaned
        manager.store.delete("Workload", "default", "w")
        manager.run_until_idle()
        removed = manager.multikueue.gc_orphans()
        assert removed >= 0
        assert worker1.store.try_get("Workload", "default", "w") is None
        assert worker2.store.try_get("Workload", "default", "w") is None

    def test_periodic_gc_timer_collects_stale_mirror(self, clock):
        # A mirror stamped with our origin whose local original vanished
        # DURING a worker outage: event-driven reconcile can't touch the
        # lost cluster (and nothing re-enqueues the key on rejoin, the
        # local object is gone), so only the periodic runtime timer
        # (manager.py wires gc_orphans at gcInterval) can collect it.
        manager, worker1, worker2 = self.make_clusters(clock)
        mk = manager.multikueue
        mk.mark_cluster_lost("worker1")
        manager.run_until_idle()
        stale = WorkloadWrapper("stale").queue("lq").request("cpu", "2").obj()
        stale.metadata.labels[ORIGIN_LABEL] = "multikueue"
        worker1.store.create(stale)
        manager.run_until_idle()
        assert worker1.store.try_get("Workload", "default", "stale") is not None
        mk.mark_cluster_rejoined("worker1")
        manager.run_until_idle()
        # rejoin re-enqueues local workloads only; the orphan has none
        assert worker1.store.try_get("Workload", "default", "stale") is not None
        manager.advance(cfgpkg.DEFAULT_MULTIKUEUE_GC_INTERVAL_SECONDS + 1)
        assert worker1.store.try_get("Workload", "default", "stale") is None

    def test_cluster_loss_replaces_then_rejoin_no_double_dispatch(self, clock):
        manager, worker1, worker2 = self.make_clusters(clock)
        mk = manager.multikueue
        manager.store.create(
            WorkloadWrapper("w").queue("lq").request("cpu", "2").obj())
        manager.schedule_until_settled()
        self.run_all(manager, worker1, worker2)
        winner_name, winner = next(
            (n, w) for n, w in (("worker1", worker1), ("worker2", worker2))
            if w.store.try_get("Workload", "default", "w") is not None)
        other_name, other = next(
            (n, w) for n, w in (("worker1", worker1), ("worker2", worker2))
            if n != winner_name)

        mk.mark_cluster_lost(winner_name)
        manager.run_until_idle()
        # before the worker-lost timeout: still Ready, no churn
        wl = manager.store.get("Workload", "default", "w")
        assert wlpkg.find_admission_check(wl, "mk-check").state \
            == api.CHECK_STATE_READY
        # past the timeout: Retry -> eviction -> checks reset -> the
        # workload re-places on the surviving cluster
        manager.advance(15 * 60.0 + 1)
        for _ in range(4):
            manager.schedule_until_settled()
            other.schedule_until_settled()
            manager.run_until_idle()
        wl = manager.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(wl), wl.status.admission_checks
        remote = other.store.get("Workload", "default", "w")
        assert wlpkg.has_quota_reservation(remote)

        # the lost cluster rejoins holding its stale reserved mirror:
        # sticky placement keeps the workload on the survivor and the
        # stale mirror is deleted — never a second dispatch
        mk.mark_cluster_rejoined(winner_name)
        self.run_all(manager, worker1, worker2)
        holders = [n for n, w in (("worker1", worker1), ("worker2", worker2))
                   if (rw := w.store.try_get("Workload", "default", "w"))
                   is not None and wlpkg.has_quota_reservation(rw)]
        assert holders == [other_name], holders
        wl = manager.store.get("Workload", "default", "w")
        assert wlpkg.is_admitted(wl)
