"""Builder wrappers for tests.

Equivalent of the reference's pkg/util/testing/wrappers.go
(MakeWorkload:67, MakeClusterQueue:612, ...): fluent builders that keep
test tables readable.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import (
    Affinity, Container, NodeAffinity, NodeSelector, NodeSelectorRequirement,
    NodeSelectorTerm, PodSpec, PodTemplateSpec, Taint, Toleration, parse_quantity,
)
from kueue_tpu.api.meta import LabelSelector, ObjectMeta, new_uid


class WorkloadWrapper:
    def __init__(self, name: str, namespace: str = "default"):
        # Deterministic uid (NOT the global counter): candidatesOrdering
        # tie-breaks on uid, so differential tests comparing two
        # separately built envs need name-derived uids — counter-based
        # ones sort differently across digit-count boundaries
        # ("wl-100" < "wl-96" lexicographically).
        self.wl = api.Workload(metadata=ObjectMeta(
            name=name, namespace=namespace, uid=f"wl-{namespace}-{name}",
            creation_timestamp=0.0))

    def queue(self, q: str) -> "WorkloadWrapper":
        self.wl.spec.queue_name = q
        return self

    def priority(self, p: int) -> "WorkloadWrapper":
        self.wl.spec.priority = p
        return self

    def creation(self, ts: float) -> "WorkloadWrapper":
        self.wl.metadata.creation_timestamp = ts
        return self

    def active(self, a: bool) -> "WorkloadWrapper":
        self.wl.spec.active = a
        return self

    def pod_set(self, name: str = api.DEFAULT_PODSET_NAME, count: int = 1,
                min_count: Optional[int] = None, **requests) -> "WorkloadWrapper":
        reqs = {k.replace("_", "."): parse_quantity(v, k) for k, v in requests.items()}
        ps = api.PodSet(
            name=name, count=count, min_count=min_count,
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(name="c", requests=reqs)])))
        self.wl.spec.pod_sets.append(ps)
        return self

    def request(self, resource: str, qty) -> "WorkloadWrapper":
        """Add/extend a single default podset with one resource request."""
        if not self.wl.spec.pod_sets:
            self.pod_set()
        ps = self.wl.spec.pod_sets[-1]
        ps.template.spec.containers[0].requests[resource] = parse_quantity(qty, resource)
        return self

    def toleration(self, key: str, value: str = "", effect: str = "NoSchedule",
                   operator: str = "Equal") -> "WorkloadWrapper":
        if not self.wl.spec.pod_sets:
            self.pod_set()
        self.wl.spec.pod_sets[-1].template.spec.tolerations.append(
            Toleration(key=key, value=value, effect=effect, operator=operator))
        return self

    def node_selector(self, key: str, value: str) -> "WorkloadWrapper":
        if not self.wl.spec.pod_sets:
            self.pod_set()
        self.wl.spec.pod_sets[-1].template.spec.node_selector[key] = value
        return self

    def affinity_in(self, key: str, *values: str) -> "WorkloadWrapper":
        if not self.wl.spec.pod_sets:
            self.pod_set()
        spec = self.wl.spec.pod_sets[-1].template.spec
        spec.affinity = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
            node_selector_terms=[NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key=key, operator="In", values=list(values))])])))
        return self

    def reserve(self, cq: str, flavor: str = "default", now: float = 0.0) -> "WorkloadWrapper":
        """Mark the workload as having quota reserved with a simple admission."""
        from kueue_tpu.core import workload as wlpkg
        psas = []
        for i, ps in enumerate(self.wl.spec.pod_sets):
            info = wlpkg.Info(self.wl)
            psas.append(api.PodSetAssignment(
                name=ps.name,
                flavors={r: flavor for r in info.total_requests[i].requests},
                resource_usage=dict(info.total_requests[i].requests),
                count=ps.count))
        wlpkg.set_quota_reservation(self.wl, api.Admission(cluster_queue=cq, pod_set_assignments=psas), now)
        return self

    def obj(self) -> api.Workload:
        return self.wl


class ClusterQueueWrapper:
    def __init__(self, name: str):
        self.cq = api.ClusterQueue(metadata=ObjectMeta(name=name, uid=new_uid("cq")))
        self.cq.spec.namespace_selector = LabelSelector()  # match-all

    def cohort(self, c: str) -> "ClusterQueueWrapper":
        self.cq.spec.cohort = c
        return self

    def queueing_strategy(self, s: str) -> "ClusterQueueWrapper":
        self.cq.spec.queueing_strategy = s
        return self

    def resource_group(self, *flavor_quotas: api.FlavorQuotas) -> "ClusterQueueWrapper":
        covered = []
        for fq in flavor_quotas:
            for rq in fq.resources:
                if rq.name not in covered:
                    covered.append(rq.name)
        self.cq.spec.resource_groups.append(
            api.ResourceGroup(covered_resources=covered, flavors=list(flavor_quotas)))
        return self

    def preemption(self, within_cluster_queue: str = api.PREEMPTION_NEVER,
                   reclaim_within_cohort: str = api.PREEMPTION_NEVER,
                   borrow_within_cohort: Optional[api.BorrowWithinCohort] = None) -> "ClusterQueueWrapper":
        self.cq.spec.preemption = api.ClusterQueuePreemption(
            within_cluster_queue=within_cluster_queue,
            reclaim_within_cohort=reclaim_within_cohort,
            borrow_within_cohort=borrow_within_cohort)
        return self

    def flavor_fungibility(self, when_can_borrow: str = api.BORROW,
                           when_can_preempt: str = api.TRY_NEXT_FLAVOR) -> "ClusterQueueWrapper":
        self.cq.spec.flavor_fungibility = api.FlavorFungibility(
            when_can_borrow=when_can_borrow, when_can_preempt=when_can_preempt)
        return self

    def fair_weight(self, milli: int) -> "ClusterQueueWrapper":
        self.cq.spec.fair_sharing = api.FairSharing(weight=milli)
        return self

    def admission_checks(self, *names: str) -> "ClusterQueueWrapper":
        self.cq.spec.admission_checks = list(names)
        return self

    def obj(self) -> api.ClusterQueue:
        return self.cq


def flavor_quotas(flavor: str, **resources) -> api.FlavorQuotas:
    """flavor_quotas("on-demand", cpu=(nominal, borrowing, lending)) or cpu=nominal."""
    rqs = []
    for res, spec in resources.items():
        res = res.replace("_", ".")
        if isinstance(spec, tuple):
            nominal = parse_quantity(spec[0], res)
            borrowing = parse_quantity(spec[1], res) if len(spec) > 1 and spec[1] is not None else None
            lending = parse_quantity(spec[2], res) if len(spec) > 2 and spec[2] is not None else None
            rqs.append(api.ResourceQuota(name=res, nominal_quota=nominal,
                                         borrowing_limit=borrowing, lending_limit=lending))
        else:
            rqs.append(api.ResourceQuota(name=res, nominal_quota=parse_quantity(spec, res)))
    return api.FlavorQuotas(name=flavor, resources=rqs)


def make_cohort(name: str, parent: str = "",
                *fqs: api.FlavorQuotas) -> api.Cohort:
    """v1alpha1 Cohort: optional parent edge + own quotas
    (reference: cohort_types.go:26-100)."""
    cohort = api.Cohort(metadata=ObjectMeta(name=name, uid=new_uid("cohort")))
    cohort.spec.parent = parent
    if fqs:
        covered = []
        for fq in fqs:
            for rq in fq.resources:
                if rq.name not in covered:
                    covered.append(rq.name)
        cohort.spec.resource_groups.append(
            api.ResourceGroup(covered_resources=covered, flavors=list(fqs)))
    return cohort


def make_flavor(name: str, node_labels: Optional[dict] = None,
                taints: Optional[list] = None) -> api.ResourceFlavor:
    rf = api.ResourceFlavor(metadata=ObjectMeta(name=name, uid=new_uid("rf")))
    if node_labels:
        rf.spec.node_labels = dict(node_labels)
    if taints:
        rf.spec.node_taints = list(taints)
    return rf


def make_local_queue(name: str, namespace: str, cq: str) -> api.LocalQueue:
    lq = api.LocalQueue(metadata=ObjectMeta(name=name, namespace=namespace, uid=new_uid("lq")))
    lq.spec.cluster_queue = cq
    return lq


def finish_eviction(store, namespace: str, name: str, now: float):
    """Complete an eviction the way the job framework's stopJob does
    (reference: jobframework/reconciler.go:823-866, test helper
    util.FinishEvictionForWorkloads): unset quota reservation and set
    Requeued=False with the eviction reason."""
    from kueue_tpu.api.meta import find_condition
    from kueue_tpu.core import workload as wlpkg
    wl = store.get("Workload", namespace, name)
    evicted = find_condition(wl.status.conditions, api.WORKLOAD_EVICTED)
    reason = evicted.reason if evicted else "Evicted"
    wlpkg.unset_quota_reservation_with_condition(wl, "Pending", "The workload was evicted", now)
    wlpkg.set_requeued_condition(wl, reason, evicted.message if evicted else "", False, now)
    store.update(wl)
    return wl
