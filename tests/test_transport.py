"""Transport tests for the fully device-resident admission cycle
(ISSUE 11): decision-only fetch bit-identity against the staged dense
path, one-dispatch/one-collect round-trip accounting (preempt-needing
cycles included), the >5x packed-vs-dense fetch ratio, donated arena
uploads, dispatch depth 2, and the per-trace transport fields."""

import random

import numpy as np
import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.solver import BatchSolver
from tests.test_solver import admitted_map, build_env
from tests.wrappers import (ClusterQueueWrapper, WorkloadWrapper,
                            flavor_quotas)


# --- kernel-level pack/unpack bit-identity ---------------------------------

class TestDecisionPacking:
    def _solve(self, seed, compact):
        import jax.numpy as jnp
        from kueue_tpu.solver.kernel import solve_cycle_fused
        from kueue_tpu.solver.synth import synth_solver_inputs
        topo, usage, cu, wl = synth_solver_inputs(
            num_cqs=16, num_cohorts=4, num_flavors=5, num_resources=2,
            num_workloads=32, num_podsets=2, seed=seed)
        td = {k: jnp.asarray(v) for k, v in topo.items()}
        return solve_cycle_fused(
            td, usage, cu, wl["requests"], wl["podset_active"],
            wl["wl_cq"], wl["priority"], wl["timestamp"], wl["eligible"],
            wl["solvable"], num_podsets=2, max_rank=32, compact=compact)

    @pytest.mark.parametrize("seed", [0, 7, 23])
    def test_roundtrip_bit_identity(self, seed):
        from kueue_tpu.solver.service import unpack_decisions
        dense = self._solve(seed, compact=False)
        packed = self._solve(seed, compact=True)
        assert "admitted" not in packed and "dec_pr" in packed
        got = unpack_decisions(
            {k: np.asarray(v) for k, v in packed.items()
             if k in ("dec_pr", "dec_bits")}, 2, 2)
        for key in ("fit", "admitted", "borrows", "chosen",
                    "chosen_borrow"):
            assert np.array_equal(got[key], np.asarray(dense[key])), key
        # residency chain untouched by packing
        assert np.array_equal(np.asarray(packed["usage"]),
                              np.asarray(dense["usage"]))

    def test_wire_format_beats_dense_by_5x(self, seed=3):
        dense = self._solve(seed, compact=False)
        packed = self._solve(seed, compact=True)
        dense_bytes = sum(
            np.asarray(dense[k]).nbytes
            for k in ("fit", "admitted", "borrows", "chosen",
                      "chosen_borrow"))
        packed_bytes = (np.asarray(packed["dec_pr"]).nbytes
                        + np.asarray(packed["dec_bits"]).nbytes)
        assert dense_bytes > 5 * packed_bytes, (dense_bytes, packed_bytes)


# --- end-to-end: compact path vs the staged dense oracle -------------------

def _mixed_setup(preemption=True):
    def setup(env):
        env.add_flavor("default")
        kwargs = dict(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                      reclaim_within_cohort=api.PREEMPTION_ANY)
        for i in range(4):
            cq = ClusterQueueWrapper(f"cq{i}").cohort("co")
            if preemption:
                cq = cq.preemption(**kwargs)
            env.add_cq(cq.resource_group(
                flavor_quotas("default", cpu="8")).obj(), f"lq-cq{i}")
    return setup


def _run_stream(compact, seed, fair_sharing=False, cycles=10):
    """Randomized multi-wave stream with victims occupying quota so
    preempt-needing cycles occur; compact=False forces the staged dense
    fetch (the oracle)."""
    env = build_env(_mixed_setup(), solver=True, fair_sharing=fair_sharing)
    if not compact:
        env.scheduler.solver.compact_fetch = False
    rng = random.Random(seed)
    n = 0
    for i in range(4):
        env.admit_existing(
            WorkloadWrapper(f"victim{i}").queue(f"lq-cq{i}")
            .priority(0).pod_set(count=1, cpu="6")
            .reserve(f"cq{i}").obj())
    for wave in range(4):
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{wave}-{i}")
                       .queue(f"lq-cq{i}")
                       .priority(rng.randrange(0, 10))
                       .creation(float(n))
                       .pod_set(count=1, cpu=str(rng.choice([2, 4, 6])))
                       .obj())
            n += 1
    for _ in range(cycles):
        env.cycle()
        env.clock.advance(1.0)
    return env


class TestCompactVsStagedDifferential:
    """The fused compact path must be bit-identical to the staged dense
    path: same admitted set, same flavor assignments, same preempt
    targets (evictions), including preempt-needing and fair-sharing
    cycles."""

    @pytest.mark.parametrize("seed", [1, 5, 11])
    def test_preempt_stream_matches_dense_oracle(self, seed):
        dense = _run_stream(compact=False, seed=seed)
        packed = _run_stream(compact=True, seed=seed)
        assert dense.scheduler.solver.counters["collects"] > 0
        assert admitted_map(dense) == admitted_map(packed)
        assert set(dense.client.evicted) == set(packed.client.evicted)
        for i in range(4):
            assert dense.usage(f"cq{i}") == packed.usage(f"cq{i}")

    def test_fair_sharing_stream_matches_dense_oracle(self):
        dense = _run_stream(compact=False, seed=2, fair_sharing=True)
        packed = _run_stream(compact=True, seed=2, fair_sharing=True)
        assert admitted_map(dense) == admitted_map(packed)
        assert set(dense.client.evicted) == set(packed.client.evicted)


# --- round-trip accounting -------------------------------------------------

class TestSingleRoundTripPerCycle:
    def test_preempt_needing_sync_cycle_is_one_dispatch_one_collect(self):
        """The acceptance contract: a steady-state single-chip cycle —
        including one that needs preemption planning — issues exactly
        ONE dispatch and ONE collect (the fused program ships fit +
        preempt target selection in one execute)."""
        env = build_env(_mixed_setup(), solver=True)
        for i in range(4):
            env.admit_existing(
                WorkloadWrapper(f"victim{i}").queue(f"lq-cq{i}")
                .priority(0).pod_set(count=1, cpu="8")
                .reserve(f"cq{i}").obj())
            env.submit(WorkloadWrapper(f"preemptor{i}")
                       .queue(f"lq-cq{i}").priority(10)
                       .creation(float(i)).pod_set(count=1, cpu="8")
                       .obj())
        c = env.scheduler.solver.counters
        d0, c0 = c["dispatches"], c["collects"]
        env.cycle()  # preempt-needing cycle: fit + targets, one execute
        assert c["dispatches"] == d0 + 1
        assert c["collects"] == c0 + 1
        assert len(env.client.evicted) == 4  # targets decoded + issued

    def test_fit_cycle_is_one_dispatch_one_collect(self):
        env = build_env(_mixed_setup(preemption=False), solver=True)
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-cq{i}")
                       .pod_set(count=1, cpu="2").obj())
        c = env.scheduler.solver.counters
        d0, c0 = c["dispatches"], c["collects"]
        env.cycle()
        assert c["dispatches"] == d0 + 1
        assert c["collects"] == c0 + 1


class TestTraceTransportFields:
    def test_traces_carry_bytes_and_round_trips(self):
        env = build_env(_mixed_setup(preemption=False), solver=True)
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-cq{i}")
                       .pod_set(count=1, cpu="2").obj())
        env.cycle()
        tr = env.scheduler.recorder.last()
        assert tr is not None
        assert tr.dispatches == 1 and tr.collects == 1
        assert tr.upload_bytes > 0 and tr.fetch_bytes > 0
        d = tr.to_dict()
        for key in ("upload_bytes", "fetch_bytes", "dispatches",
                    "collects"):
            assert key in d
        # the solver's per-cycle numbers reconcile with the trace
        s = env.scheduler.solver
        assert tr.fetch_bytes == s.last_fetch_bytes
        assert tr.upload_bytes == s.last_upload_bytes

    def test_fetch_is_5x_under_dense_equivalent(self):
        env = build_env(_mixed_setup(preemption=False), solver=True)
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-cq{i}")
                       .pod_set(count=1, cpu="2").obj())
        env.cycle()
        s = env.scheduler.solver
        topo = s._topo_cache[0]
        from kueue_tpu.solver import encode
        from kueue_tpu.solver.kernel import dense_decision_nbytes
        W = encode._bucket(4)
        P, R = s.max_podsets, topo.nominal.shape[2]
        dense = dense_decision_nbytes(W, P, R)
        assert dense > 5 * s.last_fetch_bytes, (dense, s.last_fetch_bytes)


# --- donated arena uploads -------------------------------------------------

class TestDonatedArenaUpload:
    def test_donated_scatter_keeps_twin_bit_identical(self):
        """prepare_device's donated scatter must leave the device twin
        bit-identical to the host arrays across repeated dirty-row
        uploads (the double-buffer aliases in place; a stale or
        corrupted generation would diverge here)."""
        from kueue_tpu.solver.arena import ARENA_FIELDS
        env = build_env(_mixed_setup(preemption=False), solver=True)
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-cq{i}")
                       .pod_set(count=1, cpu="2").obj())
        env.cycle()  # establishes the twin (full upload)
        arena = env.scheduler.solver._arena
        assert arena.dev is not None
        # churn: fresh workloads dirty new rows -> donated scatter
        for wave in range(1, 3):
            for i in range(4):
                env.submit(WorkloadWrapper(f"c{wave}-{i}")
                           .queue(f"lq-cq{i}")
                           .pod_set(count=1, cpu="2").obj())
            env.cycle()
        assert arena.row_uploads > 0  # the scatter path actually ran
        for name in ARENA_FIELDS:
            assert np.array_equal(np.asarray(arena.dev[name]),
                                  getattr(arena, name)), name
        # satellite: the perf artifact's phase breakdown carries the
        # scatter sub-span in lockstep with the flight recorder's
        # span tree (dotted key nested under dispatch)
        s = env.scheduler.solver
        span_total = sum(
            d for t in env.scheduler.recorder.traces()
            for n, _s, d in t.spans if n == "dispatch.scatter")
        assert span_total > 0
        assert span_total == pytest.approx(s.phase_s["dispatch.scatter"],
                                           rel=1e-9)
        assert s.phase_s["dispatch.scatter"] <= s.phase_s["dispatch"]


# --- dispatch depth 2 ------------------------------------------------------

class TestDispatchDepthTwo:
    def _run(self, waves, depth, cpu="2"):
        def setup(env):
            env.add_flavor("default")
            for i in range(4):
                env.add_cq(
                    ClusterQueueWrapper(f"cq{i}").cohort("co")
                    .resource_group(flavor_quotas("default", cpu="8"))
                    .obj(), f"lq-cq{i}")
        env = build_env(setup, solver=depth > 0)
        if depth:
            env.scheduler.pipeline_enabled = True
            env.scheduler.pipeline_depth = depth
        n = 0
        for wave in range(waves):
            for i in range(4):
                env.submit(WorkloadWrapper(f"w{wave}-{i}")
                           .queue(f"lq-cq{i}").priority(n % 3)
                           .creation(float(n)).pod_set(count=1, cpu=cpu)
                           .obj())
                n += 1
        for _ in range(waves + 6):
            env.cycle()
        return env

    def test_depth2_matches_cpu(self):
        cpu = self._run(waves=4, depth=0)
        deep = self._run(waves=4, depth=2)
        assert admitted_map(cpu) == admitted_map(deep)
        for i in range(4):
            assert cpu.usage(f"cq{i}") == deep.usage(f"cq{i}")
        assert not deep.scheduler._inflight_q  # fully drained

    def test_depth2_contention_set_matches_cpu(self):
        cpu = self._run(waves=5, depth=0, cpu="3")
        deep = self._run(waves=5, depth=2, cpu="3")
        assert set(admitted_map(cpu)) == set(admitted_map(deep))
        for i in range(4):
            assert cpu.usage(f"cq{i}") == deep.usage(f"cq{i}")

    def test_depth2_keeps_two_cycles_in_flight(self):
        env = self._run(waves=8, depth=2)
        # the pipeline deepened to two outstanding dispatches at least
        # once: two dispatch-only fills before the first collect
        assert env.scheduler.cycle_counts.get(
            "device-dispatch-only", 0) >= 2
        assert env.scheduler.cycle_counts.get("device-pipelined", 0) >= 1

    def test_tokenless_dispatch_collapses_depth(self):
        env = self._run(waves=4, depth=2)
        s = env.scheduler
        # a token-less in-flight entry forces effective depth 1: after
        # one more schedule() the queue must not exceed one entry
        from kueue_tpu.scheduler import stages
        for ic in s._inflight_q:
            ic.token = None
        for i in range(4):
            env.submit(WorkloadWrapper(f"late{i}").queue(f"lq-cq{i}")
                       .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert len(s._inflight_q) <= 1


# --- warm ladder key agreement (mirrors the PR-9 pin) ----------------------

class TestWarmFusedKeyAgreement:
    def test_warmed_pipelined_dispatch_counts_no_mid_traffic_compiles(self):
        """Warm->fused-dispatch pin for the compact decision-output
        programs: a real governor warm followed by real PIPELINED
        (depth-2) device dispatches must find every variant key already
        registered — mid_traffic_compiles stays 0 (the ladder warms the
        packed-output programs, not their dense twins)."""
        from kueue_tpu.solver.warmgov import GOV_WARM, CompileGovernor
        from tests.test_warmgov import simple_env
        env = simple_env()
        solver = BatchSolver()
        env.scheduler.solver = solver
        env.scheduler.solver_min_heads = 0
        env.scheduler.pipeline_enabled = True
        env.scheduler.pipeline_depth = 2
        solver.bind_cache(env.cache)
        solver.bind_queues(env.scheduler.queues)
        gov = CompileGovernor(solver, env.cache, warm_preempt=False)
        assert gov.run_sync() > 0
        assert gov.state == GOV_WARM
        env.scheduler.warm_gov = gov
        for i in range(4):
            env.submit(WorkloadWrapper(f"w{i}").queue("lq0")
                       .creation(float(i)).pod_set(count=1, cpu="1")
                       .obj())
        for _ in range(8):
            env.cycle()
        assert "default/w0" in env.client.applied
        assert env.scheduler.cycle_counts.get("device-pipelined", 0) >= 1
        assert solver.counters["mid_traffic_compiles"] == 0
