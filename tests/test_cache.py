"""Cache/snapshot invariants: hierarchical quota math, borrowing/lending,
usage bubbling, assume/forget, DRF shares.

Mirrors the reference's pkg/cache/{snapshot_test.go,cache_test.go}
core cases.
"""

import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.cache import Cache
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core import workload as wlpkg
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas, make_flavor

CPU = "cpu"
FR = FlavorResource("default", CPU)


def make_cache_with_cohort():
    cache = Cache()
    cache.add_or_update_resource_flavor(make_flavor("default"))
    cq_a = (ClusterQueueWrapper("a").cohort("team")
            .resource_group(flavor_quotas("default", cpu=("10", "20", None))).obj())
    cq_b = (ClusterQueueWrapper("b").cohort("team")
            .resource_group(flavor_quotas("default", cpu=("20", None, None))).obj())
    cache.add_cluster_queue(cq_a)
    cache.add_cluster_queue(cq_b)
    return cache


class TestQuotaMath:
    def test_available_with_cohort(self):
        cache = make_cache_with_cohort()
        snap = cache.snapshot()
        a = snap.cluster_queues["a"]
        # Full cohort capacity: 10 (own) + 20 (b lends) = 30, capped by
        # borrowing limit 20 above nominal => min(10+20, 30) = 30
        assert a.available(FR) == 30000
        assert a.potential_available(FR) == 30000

    def test_borrowing_limit_caps_available(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cq_a = (ClusterQueueWrapper("a").cohort("team")
                .resource_group(flavor_quotas("default", cpu=("10", "5", None))).obj())
        cq_b = (ClusterQueueWrapper("b").cohort("team")
                .resource_group(flavor_quotas("default", cpu="20")).obj())
        cache.add_cluster_queue(cq_a)
        cache.add_cluster_queue(cq_b)
        snap = cache.snapshot()
        assert snap.cluster_queues["a"].available(FR) == 15000  # 10 + borrow 5

    def test_lending_limit_reserves_guaranteed(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cq_a = (ClusterQueueWrapper("a").cohort("team")
                .resource_group(flavor_quotas("default", cpu=("10", None, "4"))).obj())
        cq_b = (ClusterQueueWrapper("b").cohort("team")
                .resource_group(flavor_quotas("default", cpu="0")).obj())
        cache.add_cluster_queue(cq_a)
        cache.add_cluster_queue(cq_b)
        snap = cache.snapshot()
        # b can only borrow what a lends: 4
        assert snap.cluster_queues["b"].available(FR) == 4000
        # a keeps guaranteed 6 locally + its 4 in the cohort
        assert snap.cluster_queues["a"].available(FR) == 10000

    def test_usage_bubbles_past_guaranteed(self):
        cache = make_cache_with_cohort()
        w = (WorkloadWrapper("w1").pod_set(count=1, cpu="15")
             .reserve("a", flavor="default").obj())
        cache.add_or_update_workload(w)
        snap = cache.snapshot()
        a = snap.cluster_queues["a"]
        b = snap.cluster_queues["b"]
        assert a.usage_for(FR) == 15000
        assert a.borrowing(FR)  # 15 > nominal 10
        # cohort usage = 15 - 0 guaranteed... a has no lending limit so
        # guaranteed=0 and all 15 bubbles up; b sees 30 total - 15 used - its 0
        assert b.available(FR) == 30000 - 15000

    def test_remove_usage_restores(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w1").pod_set(count=1, cpu="15").reserve("a").obj()
        cache.add_or_update_workload(w)
        cache.delete_workload(w)
        snap = cache.snapshot()
        assert snap.cluster_queues["a"].usage_for(FR) == 0
        # b's own 20 plus everything a lends (no lending limit -> all 10)
        assert snap.cluster_queues["b"].available(FR) == 30000


class TestAssume:
    def test_assume_then_forget(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w1").pod_set(count=1, cpu="5").reserve("a").obj()
        cache.assume_workload(w)
        assert cache.is_assumed_or_admitted(wlpkg.Info(w))
        assert cache.snapshot().cluster_queues["a"].usage_for(FR) == 5000
        cache.forget_workload(w)
        assert not cache.is_assumed_or_admitted(wlpkg.Info(w))
        assert cache.snapshot().cluster_queues["a"].usage_for(FR) == 0

    def test_double_assume_raises(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w1").pod_set(count=1, cpu="5").reserve("a").obj()
        cache.assume_workload(w)
        with pytest.raises(KeyError):
            cache.assume_workload(w)


class TestSnapshotSimulation:
    def test_remove_add_workload_roundtrip(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w1").pod_set(count=1, cpu="8").reserve("a").obj()
        cache.add_or_update_workload(w)
        snap = cache.snapshot()
        info = snap.cluster_queues["a"].workloads[wlpkg.key(w)]
        before = snap.cluster_queues["a"].usage_for(FR)
        snap.remove_workload(info)
        assert snap.cluster_queues["a"].usage_for(FR) == before - 8000
        snap.add_workload(info)
        assert snap.cluster_queues["a"].usage_for(FR) == before
        # cache unchanged by snapshot mutation
        assert cache.snapshot().cluster_queues["a"].usage_for(FR) == 8000


class TestInactive:
    def test_missing_flavor_inactivates(self):
        cache = Cache()
        cq = (ClusterQueueWrapper("a")
              .resource_group(flavor_quotas("missing", cpu="10")).obj())
        cache.add_cluster_queue(cq)
        assert not cache.cluster_queue_active("a")
        snap = cache.snapshot()
        assert "a" in snap.inactive_cluster_queue_sets
        cache.add_or_update_resource_flavor(make_flavor("missing"))
        assert cache.cluster_queue_active("a")

    def test_stopped_cq_inactive(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cq = (ClusterQueueWrapper("a")
              .resource_group(flavor_quotas("default", cpu="10")).obj())
        cq.spec.stop_policy = api.HOLD
        cache.add_cluster_queue(cq)
        assert not cache.cluster_queue_active("a")

    def test_missing_check_inactivates(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cq = (ClusterQueueWrapper("a")
              .resource_group(flavor_quotas("default", cpu="10"))
              .admission_checks("prov").obj())
        cache.add_cluster_queue(cq)
        assert not cache.cluster_queue_active("a")
        ac = api.AdmissionCheck()
        ac.metadata.name = "prov"
        from kueue_tpu.api.meta import Condition, set_condition
        set_condition(ac.status.conditions, Condition(
            type=api.ADMISSION_CHECK_ACTIVE, status="True"), 1.0)
        cache.add_or_update_admission_check(ac)
        assert cache.cluster_queue_active("a")


class TestDRF:
    def test_share_zero_below_nominal(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w").pod_set(count=1, cpu="5").reserve("a").obj()
        cache.add_or_update_workload(w)
        snap = cache.snapshot()
        share, _ = snap.cluster_queues["a"].dominant_resource_share()
        assert share == 0

    def test_share_counts_borrowed(self):
        cache = make_cache_with_cohort()
        w = WorkloadWrapper("w").pod_set(count=1, cpu="16").reserve("a").obj()
        cache.add_or_update_workload(w)
        snap = cache.snapshot()
        share, res = snap.cluster_queues["a"].dominant_resource_share()
        # borrowed 6 over nominal 10; lendable = 30 -> 6*1000/30 = 200
        assert share == 200
        assert res == CPU

    def test_share_with_hypothetical_request(self):
        cache = make_cache_with_cohort()
        snap = cache.snapshot()
        share, _ = snap.cluster_queues["a"].dominant_resource_share_with({FR: 13000})
        # would borrow 3 of 30 lendable -> 100
        assert share == 100

    def test_weight_scales_share(self):
        cache = Cache()
        cache.add_or_update_resource_flavor(make_flavor("default"))
        cq = (ClusterQueueWrapper("a").cohort("team").fair_weight(2000)
              .resource_group(flavor_quotas("default", cpu="10")).obj())
        cq_b = (ClusterQueueWrapper("b").cohort("team")
                .resource_group(flavor_quotas("default", cpu="20")).obj())
        cache.add_cluster_queue(cq)
        cache.add_cluster_queue(cq_b)
        w = WorkloadWrapper("w").pod_set(count=1, cpu="16").reserve("a").obj()
        cache.add_or_update_workload(w)
        snap = cache.snapshot()
        share, _ = snap.cluster_queues["a"].dominant_resource_share()
        assert share == 100  # 200 / weight 2
