"""Incremental journal-replay snapshots (cache/incremental.py,
cache/SNAPSHOTS.md): the maintained snapshot must be deep-equal to a
from-scratch deep clone after arbitrary interleavings of workload and
topology mutations — including the journal-overflow and epoch-bump
fallback paths — and handouts must honor the copy-on-write contract
(cycle mutations never poison the persistent copy; handed-out snapshots
stay frozen at their journal_seq).
"""

import random

from kueue_tpu.api import kueue as api
from kueue_tpu.cache import Cache
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from tests.wrappers import (
    ClusterQueueWrapper, WorkloadWrapper, flavor_quotas, make_cohort,
    make_flavor,
)

FR = FlavorResource("f0", "cpu")


def assert_snapshots_equal(a, b, ctx=""):
    """Deep equality between a maintained snapshot and a from-scratch
    clone: usage trees, workload maps (same Info identities), epochs,
    generations, scalar config and DRF shares."""
    assert set(a.cluster_queues) == set(b.cluster_queues), ctx
    assert a.inactive_cluster_queue_sets == b.inactive_cluster_queue_sets, ctx
    assert (a.cohort_epoch, a.flavor_spec_epoch, a.topology_epoch,
            a.journal_seq) == (b.cohort_epoch, b.flavor_spec_epoch,
                               b.topology_epoch, b.journal_seq), ctx
    assert set(a.resource_flavors) == set(b.resource_flavors), ctx
    for k in a.resource_flavors:
        assert a.resource_flavors[k] is b.resource_flavors[k], (ctx, k)
    for name, ca in a.cluster_queues.items():
        cb = b.cluster_queues[name]
        assert ca.workloads == cb.workloads, (ctx, name)
        assert ca.workloads_not_ready == cb.workloads_not_ready, (ctx, name)
        assert ca.resource_node.usage == cb.resource_node.usage, (ctx, name)
        assert ca.resource_node.quotas == cb.resource_node.quotas, (ctx, name)
        assert ca.resource_node.subtree_quota \
            == cb.resource_node.subtree_quota, (ctx, name)
        assert ca.admission_checks == cb.admission_checks, (ctx, name)
        assert ca.fair_weight == cb.fair_weight, (ctx, name)
        assert ca.preemption is cb.preemption, (ctx, name)
        assert ca.namespace_selector is cb.namespace_selector, (ctx, name)
        assert ca.flavor_fungibility is cb.flavor_fungibility, (ctx, name)
        assert ca.allocatable_resource_generation \
            == cb.allocatable_resource_generation, (ctx, name)
        assert [(rg.covered_resources, rg.flavors, rg.label_keys)
                for rg in ca.resource_groups] \
            == [(rg.covered_resources, rg.flavors, rg.label_keys)
                for rg in cb.resource_groups], (ctx, name)
        assert (ca.cohort is None) == (cb.cohort is None), (ctx, name)
        if ca.cohort is not None:
            assert ca.cohort.name == cb.cohort.name, (ctx, name)
        assert ca.dominant_resource_share() \
            == cb.dominant_resource_share(), (ctx, name)

    def cohort_closure(snap):
        out = {}
        stack = []
        for cq in snap.cluster_queues.values():
            cohort = cq.cohort
            while cohort is not None and cohort.name not in out:
                out[cohort.name] = cohort
                stack.append(cohort)
                cohort = cohort.parent
        while stack:  # downward: sibling subtrees without active members
            for child in stack.pop().child_cohorts:
                if child.name not in out:
                    out[child.name] = child
                    stack.append(child)
        return out

    cohorts_a, cohorts_b = cohort_closure(a), cohort_closure(b)
    assert set(cohorts_a) == set(cohorts_b), ctx
    for name in cohorts_a:
        x, y = cohorts_a[name], cohorts_b[name]
        assert x.resource_node.usage == y.resource_node.usage, (ctx, name)
        assert x.resource_node.subtree_quota \
            == y.resource_node.subtree_quota, (ctx, name)
        assert x.allocatable_resource_generation \
            == y.allocatable_resource_generation, (ctx, name)
        assert {m.name for m in x.members} \
            == {m.name for m in y.members}, (ctx, name)
        assert (x.parent.name if x.parent else None) \
            == (y.parent.name if y.parent else None), (ctx, name)


def check(cache, ctx=""):
    snap = cache.snapshot()
    assert_snapshots_equal(snap, cache._build_snapshot(), ctx)
    return snap


def make_cq(name, cohort="", nominal=10, lending=None, preemption=None):
    w = ClusterQueueWrapper(name)
    if cohort:
        w.cohort(cohort)
    if preemption is not None:
        w.preemption(within_cluster_queue=preemption)
    w.resource_group(flavor_quotas("f0", cpu=(nominal, None, lending)),
                     flavor_quotas("f1", cpu=nominal))
    return w.obj()


def build_cache(**kwargs):
    cache = Cache(**kwargs)
    cache.add_or_update_resource_flavor(make_flavor("f0"))
    cache.add_or_update_resource_flavor(make_flavor("f1"))
    cache.add_or_update_cohort(make_cohort("root"))
    cache.add_or_update_cohort(
        make_cohort("left", "root", flavor_quotas("f0", cpu="8")))
    cache.add_or_update_cohort(make_cohort("right", "root"))
    for i, (cohort, lending) in enumerate(
            [("left", None), ("left", 4), ("left", None),
             ("right", 2), ("right", None), ("", None)]):
        cache.add_cluster_queue(make_cq(f"cq{i}", cohort, lending=lending))
    return cache


def admitted_workload(name, cq, cpu, flavor="f0"):
    return (WorkloadWrapper(name).pod_set(count=1, cpu=cpu)
            .reserve(cq, flavor=flavor).obj())


class TestRandomizedEquivalence:
    def test_interleaved_ops_replay_equals_rebuild(self):
        rng = random.Random(4242)
        cache = build_cache()
        admitted: dict = {}
        assumed: dict = {}
        extra_cqs: list = []
        counter = [0]

        def fresh_name():
            counter[0] += 1
            return f"w{counter[0]}"

        def cq_pool():
            return [f"cq{i}" for i in range(6)] + extra_cqs

        def op_admit():
            wl = admitted_workload(fresh_name(), rng.choice(cq_pool()),
                                   rng.randint(1, 5),
                                   flavor=rng.choice(["f0", "f1"]))
            cache.add_or_update_workload(wl)
            admitted[wlpkg.key(wl)] = wl

        def op_assume():
            wl = admitted_workload(fresh_name(), rng.choice(cq_pool()),
                                   rng.randint(1, 5))
            cache.assume_workload(wl)
            assumed[wlpkg.key(wl)] = wl

        def op_forget():
            if assumed:
                key = rng.choice(sorted(assumed))
                cache.forget_workload(assumed.pop(key))

        def op_delete():
            if admitted:
                key = rng.choice(sorted(admitted))
                cache.delete_workload(admitted.pop(key))

        def op_cq_nonstructural():
            # preemption policy / namespace selector are invisible to
            # every epoch: exercises the journal 'cq' replay records
            i = rng.randrange(6)
            lending = {1: 4, 3: 2}.get(i)
            cohort = {0: "left", 1: "left", 2: "left",
                      3: "right", 4: "right", 5: ""}[i]
            cache.update_cluster_queue(make_cq(
                f"cq{i}", cohort, lending=lending,
                preemption=rng.choice([api.PREEMPTION_NEVER,
                                       api.PREEMPTION_LOWER_PRIORITY])))

        def op_cq_structural():
            # quota change moves the topology signature: full rebuild
            cache.update_cluster_queue(make_cq(
                "cq0", "left", nominal=rng.randint(8, 12)))

        def op_flavor():
            # spec change bumps flavor_spec_epoch: full rebuild
            cache.add_or_update_resource_flavor(
                make_flavor("f1", node_labels={"zone": str(rng.random())}))

        def op_cohort():
            # quota change bumps cohort_epoch: full rebuild
            cache.add_or_update_cohort(make_cohort(
                "left", "root", flavor_quotas("f0", cpu=rng.randint(6, 10))))

        def op_add_cq():
            name = f"xcq{len(extra_cqs)}"
            cache.add_cluster_queue(make_cq(name, "right"))
            extra_cqs.append(name)

        def op_del_cq():
            if extra_cqs:
                name = extra_cqs.pop()
                for key in [k for k, wl in admitted.items()
                            if wl.status.admission.cluster_queue == name]:
                    admitted.pop(key)
                for key in [k for k, wl in assumed.items()
                            if wl.status.admission.cluster_queue == name]:
                    assumed.pop(key)
                cache.delete_cluster_queue(name)

        ops = ([op_admit] * 8 + [op_assume] * 4 + [op_forget] * 3
               + [op_delete] * 5 + [op_cq_nonstructural] * 3
               + [op_cq_structural] + [op_flavor] + [op_cohort]
               + [op_add_cq] + [op_del_cq])
        check(cache, "initial")
        for step in range(400):
            rng.choice(ops)()
            check(cache, f"step {step}")
        # all three paths actually exercised (single-CQ quota edits now
        # take the per-CQ partial rebuild instead of a full rebuild)
        assert cache.snapshot_stats["incremental"] > 50, cache.snapshot_stats
        assert cache.snapshot_stats["full"] > 5, cache.snapshot_stats
        assert cache.snapshot_stats["partial"] > 3, cache.snapshot_stats

    def test_single_cq_edit_storm_stays_partial(self):
        # Randomized replay==rebuild equivalence focused on the per-CQ
        # path: ONLY workload deltas and single-CQ quota edits (the
        # flavor-churn scenario's steady diet) — every structural sync
        # must take the partial path, never a full rebuild.
        rng = random.Random(777)
        cache = build_cache()
        check(cache, "initial")
        full_before = cache.snapshot_stats["full"]
        admitted: list = []
        nominal = {i: 10 for i in range(6)}
        for step in range(120):
            roll = rng.random()
            if roll < 0.5:
                wl = admitted_workload(f"p{step}", f"cq{rng.randrange(6)}",
                                       rng.randint(1, 4),
                                       flavor=rng.choice(["f0", "f1"]))
                cache.add_or_update_workload(wl)
                admitted.append(wl)
            elif roll < 0.7 and admitted:
                cache.delete_workload(admitted.pop(
                    rng.randrange(len(admitted))))
            else:
                i = rng.randrange(6)
                nominal[i] += rng.choice([-1, 1, 2])  # always a real change
                lending = {1: 4, 3: 2}.get(i)
                cohort = {0: "left", 1: "left", 2: "left",
                          3: "right", 4: "right", 5: ""}[i]
                cache.update_cluster_queue(make_cq(
                    f"cq{i}", cohort, nominal=nominal[i], lending=lending))
            check(cache, f"partial-storm step {step}")
        assert cache.snapshot_stats["full"] == full_before, \
            cache.snapshot_stats
        assert cache.snapshot_stats["partial"] > 20, cache.snapshot_stats

    def test_multiple_dirty_cqs_rebuild_in_one_partial_sync(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq0", 3))
        cache.add_or_update_workload(admitted_workload("w2", "cq3", 2))
        check(cache, "pre")
        partial_before = cache.snapshot_stats["partial"]
        full_before = cache.snapshot_stats["full"]
        # two single-CQ edits in different cohorts before the next sync,
        # plus an interleaved workload delta that must still replay
        cache.update_cluster_queue(make_cq("cq0", "left", nominal=14))
        cache.add_or_update_workload(admitted_workload("w3", "cq4", 1))
        cache.update_cluster_queue(make_cq("cq3", "right", nominal=7,
                                           lending=2))
        snap = check(cache, "two dirty CQs")
        assert cache.snapshot_stats["partial"] == partial_before + 1
        assert cache.snapshot_stats["full"] == full_before
        live = cache.hm.cluster_queues
        for name in ("cq0", "cq3"):
            assert snap.cluster_queues[name].resource_node.quotas \
                == live[name].resource_node.quotas, name
        assert "default/w3" in snap.cluster_queues["cq4"].workloads

    def test_cohort_edge_move_falls_back_to_full(self):
        cache = build_cache()
        check(cache, "pre")
        full_before = cache.snapshot_stats["full"]
        # same quota, different cohort: the graph shape changed, the
        # per-CQ path must not claim it
        cache.update_cluster_queue(make_cq("cq0", "right"))
        check(cache, "edge move")
        assert cache.snapshot_stats["full"] == full_before + 1

    def test_cq_edit_mixed_with_wider_epoch_falls_back_to_full(self):
        cache = build_cache()
        check(cache, "pre")
        full_before = cache.snapshot_stats["full"]
        partial_before = cache.snapshot_stats["partial"]
        # a single-CQ edit AND a flavor-spec change between syncs: the
        # dirty-CQ scope is subsumed by the full rebuild
        cache.update_cluster_queue(make_cq("cq1", "left", nominal=13,
                                           lending=4))
        cache.add_or_update_resource_flavor(
            make_flavor("f1", node_labels={"zone": "z9"}))
        check(cache, "mixed")
        assert cache.snapshot_stats["full"] == full_before + 1
        assert cache.snapshot_stats["partial"] == partial_before
        # and the dirty set was consumed: the next single-CQ edit is
        # partial again, not poisoned by the stale scope
        cache.update_cluster_queue(make_cq("cq1", "left", nominal=9,
                                           lending=4))
        check(cache, "post-mixed edit")
        assert cache.snapshot_stats["partial"] == partial_before + 1

    def test_terminate_cluster_queue_takes_partial_path(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq2", 2))
        check(cache, "pre")
        partial_before = cache.snapshot_stats["partial"]
        cache.terminate_cluster_queue("cq2")
        snap = check(cache, "terminated")
        assert cache.snapshot_stats["partial"] == partial_before + 1
        # terminating flips the CQ inactive: hidden from the handout,
        # usage still bubbling through its cohort (hidden master)
        assert "cq2" not in snap.cluster_queues
        assert "cq2" in snap.inactive_cluster_queue_sets

    def test_journal_overflow_falls_back_to_rebuild(self):
        cache = build_cache()
        cache._journal_cap = 5
        check(cache, "pre")
        full_before = cache.snapshot_stats["full"]
        wls = [admitted_workload(f"o{i}", f"cq{i % 6}", 1) for i in range(12)]
        for wl in wls:  # 12 entries against a cap of 5: overflow
            cache.add_or_update_workload(wl)
        check(cache, "overflowed")
        assert cache.snapshot_stats["full"] == full_before + 1
        # back to steady state: small deltas replay incrementally again
        incr_before = cache.snapshot_stats["incremental"]
        cache.delete_workload(wls[0])
        check(cache, "post-overflow delta")
        assert cache.snapshot_stats["incremental"] == incr_before + 1

    def test_pods_ready_tracking_replay(self):
        cache = build_cache(pods_ready_tracking=True)
        check(cache, "initial")
        wl = admitted_workload("w1", "cq0", 3)
        cache.add_or_update_workload(wl)  # no PodsReady condition: not ready
        snap = check(cache, "unready")
        assert snap.cluster_queues["cq0"].workloads_not_ready == {"default/w1"}
        cache.mark_workload_pods_ready(wl)
        snap = check(cache, "ready")
        assert not snap.cluster_queues["cq0"].workloads_not_ready

    def test_inactive_cq_usage_bubbles_through_replay(self):
        cache = build_cache()
        cq = (ClusterQueueWrapper("ghost").cohort("left")
              .resource_group(flavor_quotas("missing", cpu="10")).obj())
        cache.add_cluster_queue(cq)  # missing flavor: inactive
        assert not cache.cluster_queue_active("ghost")
        check(cache, "inactive added")
        # Admitted usage in the inactive CQ still bubbles into the live
        # cohort tree; replay must mirror it via the hidden master.
        cache.add_or_update_workload(admitted_workload("g1", "ghost", 4))
        snap = check(cache, "inactive usage")
        assert "ghost" not in snap.cluster_queues
        left = snap.cluster_queues["cq0"].cohort
        assert left.resource_node.usage.get(FR, 0) >= 4000

    def test_consumed_entries_drop_their_info_payload(self):
        # a registered-but-stalled solver consumer retains entries, but
        # once the snapshot maintainer has consumed them their aux
        # (Info, not_ready) payload must be stripped so the journal
        # never pins deleted workloads' object graphs
        cache = build_cache()
        cache.enable_usage_journal()  # solver cursor registered, never drained
        wls = [admitted_workload(f"w{i}", "cq0", 1) for i in range(6)]
        for wl in wls:
            cache.add_or_update_workload(wl)
        check(cache, "adds consumed")  # snapshot consumer drains
        assert cache._journal, "solver backlog should be retained"
        assert all(e[5] is None for e in cache._journal), cache._journal
        # the solver's view of the retained entries is intact
        entries, overflow = cache.drain_usage_journal(
            cache._journal_seq, consumer="solver")
        assert not overflow and len(entries) == 6
        assert all(e[1] == "add" and e[4] for e in entries)

    def test_light_snapshots_do_not_disturb_the_maintainer(self):
        cache = build_cache()
        check(cache, "initial")
        wl = admitted_workload("w1", "cq0", 2)
        cache.add_or_update_workload(wl)
        for _ in range(3):
            light = cache.snapshot(light=True)
            assert light.light
        incr_before = cache.snapshot_stats["incremental"]
        check(cache, "after lights")
        assert cache.snapshot_stats["incremental"] == incr_before + 1


class TestCopyOnWriteContract:
    def test_cycle_mutation_does_not_poison_the_persistent_copy(self):
        cache = build_cache()
        wl = admitted_workload("w1", "cq0", 8)
        cache.add_or_update_workload(wl)
        s1 = cache.snapshot()
        info = s1.cluster_queues["cq0"].workloads["default/w1"]
        s1.remove_workload(info)  # preemption simulation
        s1.cluster_queues["cq1"].add_usage({FR: 1000})  # reserve accounting
        assert s1.cluster_queues["cq0"].usage_for(FR) == 0
        # the next snapshot must be clean AND equal to a fresh rebuild
        s2 = check(cache, "after mutation")
        assert s2.cluster_queues["cq0"].usage_for(FR) == 8000
        assert s2.cluster_queues["cq1"].usage_for(FR) == 0
        assert "default/w1" in s2.cluster_queues["cq0"].workloads
        # and the mutated handout keeps its own view
        assert s1.cluster_queues["cq0"].usage_for(FR) == 0
        assert s1.cluster_queues["cq1"].usage_for(FR) == 1000

    def test_handout_is_frozen_at_its_journal_seq(self):
        cache = build_cache()
        wl = admitted_workload("w1", "cq0", 8)
        cache.add_or_update_workload(wl)
        s1 = cache.snapshot()
        cache.delete_workload(wl)  # cache moves on
        s2 = check(cache, "after delete")
        # s1 still shows the pre-delete state (master privatized the
        # containers before replaying the delete onto them)
        assert s1.cluster_queues["cq0"].usage_for(FR) == 8000
        assert "default/w1" in s1.cluster_queues["cq0"].workloads
        assert s2.cluster_queues["cq0"].usage_for(FR) == 0
        # mutating the stale handout is still safe for future snapshots
        s1.cluster_queues["cq0"].add_usage({FR: 500})
        check(cache, "after stale mutation")

    def test_cohort_chain_cow_covers_sibling_subtrees(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq0", 12))
        s1 = cache.snapshot()
        # simulate on one member: privatizes cq0 + left + root shells
        s1.remove_workload(s1.cluster_queues["cq0"]
                           .workloads["default/w1"])
        # a sibling-subtree member of the same handout still sees the
        # un-mutated shared nodes, then privatizes on its own first write
        s1.cluster_queues["cq3"].add_usage({FR: 7000})
        s2 = check(cache, "after sibling mutations")
        root = s2.cluster_queues["cq3"].cohort.root()
        # persistent copy: w1's full usage bubbled to root (no lending
        # limit on cq0 => guaranteed quota 0), the simulation didn't
        assert root.resource_node.usage.get(FR, 0) == 12000


class TestShellReuse:
    def test_released_handout_shells_are_recycled(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq0", 2))
        s1 = check(cache, "initial")
        cache.release_snapshot(s1)
        shells1 = dict(s1.cluster_queues)
        cache.add_or_update_workload(admitted_workload("w2", "cq1", 3))
        s2 = check(cache, "after release")  # equal to a fresh rebuild
        m = cache._maintainer
        assert m.shell_reuses > 0
        # untouched CQs keep the recycled objects; the replayed one is
        # rebuilt so the released snapshot's frozen view stays... gone —
        # it was RELEASED; identity reuse is the whole point:
        assert s2.cluster_queues["cq2"] is shells1["cq2"]
        assert s2.cluster_queues["cq1"] is not shells1["cq1"]

    def test_materialized_shells_are_not_recycled(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq0", 2))
        s1 = cache.snapshot()
        s1.cluster_queues["cq3"].add_usage({FR: 1000})  # cycle accounting
        cache.release_snapshot(s1)
        old_cq3 = s1.cluster_queues["cq3"]
        s2 = check(cache, "after materialized release")
        assert s2.cluster_queues["cq3"] is not old_cq3
        assert s2.cluster_queues["cq3"].usage_for(FR) == 0

    def test_unreleased_handouts_are_never_reused(self):
        cache = build_cache()
        s1 = cache.snapshot()
        s2 = check(cache, "no release")
        for name in s1.cluster_queues:
            assert s2.cluster_queues[name] is not s1.cluster_queues[name]

    def test_stale_release_is_ignored(self):
        cache = build_cache()
        s1 = cache.snapshot()
        cache.snapshot()  # a newer handout exists
        cache.release_snapshot(s1)  # stale: must not enter the pool
        s3 = check(cache, "after stale release")
        for name in s1.cluster_queues:
            assert s3.cluster_queues[name] is not s1.cluster_queues[name]

    def test_reuse_through_scheduler_cycles(self):
        # the scheduler releases its sync-cycle snapshot, so steady-state
        # cycles recycle shells — and decisions stay correct
        from tests.test_scheduler import Env
        env = Env()
        env.add_flavor("default")
        for c in range(3):
            env.add_cq(ClusterQueueWrapper(f"cq{c}")
                       .resource_group(flavor_quotas("default", cpu="100"))
                       .obj(), f"lq{c}")
        for i in range(4):
            # only cq0 is touched per cycle: cq1/cq2 shells recycle
            env.submit(WorkloadWrapper(f"w{i}").queue("lq0")
                       .pod_set(count=1, cpu="1").obj())
            env.cycle()
            assert f"default/w{i}" in env.client.applied
        assert env.cache._maintainer.shell_reuses > 0


class TestBackgroundAdvance:
    def test_light_stretch_catches_up_before_cursor_overflow(self):
        # A long pipelined all-fit stretch takes only light snapshots;
        # the journal backlog passing half the cap must trigger a
        # background replay so the next sync snapshot is still served
        # incrementally (no surprise full rebuild).
        cache = build_cache()
        check(cache, "establish")
        cache._journal_cap = 40
        m = cache._maintainer
        wls = []
        for i in range(60):  # > cap journal entries, light-only stretch
            wl = admitted_workload(f"bg{i}", f"cq{i % 3}", 1)
            cache.add_or_update_workload(wl)
            wls.append(wl)
            cache.snapshot(light=True)
        assert m.background_advances > 0
        full_before = m.full_rebuilds
        check(cache, "sync after light stretch")
        assert m.full_rebuilds == full_before  # incremental, not rebuild


class TestJournalOverflowRaces:
    """Multi-consumer journal overflow racing release_snapshot (ISSUE 3
    satellite): an overflow landing while the maintainer advances in
    the background must cost exactly ONE full rebuild, a laggard
    consumer's overflow must not disturb the other consumer, released
    shells must never be recycled across a rebuild, and double-release
    of the same handout must be a guarded no-op."""

    def test_overflow_during_catch_up_rebuilds_exactly_once(self):
        cache = build_cache()
        cache.enable_usage_journal()  # a second (solver) consumer
        check(cache, "establish")
        cache._journal_cap = 10
        m = cache._maintainer
        full_before = m.full_rebuilds
        # A burst past the cap with NO snapshot in between: both
        # consumers' cursors are overrun, flagged, and force-advanced —
        # then more entries accumulate behind the advanced cursor so
        # the light snapshot's backlog check fires catch_up while the
        # overflow flag is still pending: the maintainer consumes ITS
        # flag there (one rebuild, no handout).
        wls = [admitted_workload(f"r{i}", f"cq{i % 6}", 1)
               for i in range(18)]
        for wl in wls:
            cache.add_or_update_workload(wl)
        cache.snapshot(light=True)
        assert m.full_rebuilds == full_before + 1
        # The next sync snapshot replays incrementally — the overflow
        # was consumed exactly once, not re-observed.
        cache.add_or_update_workload(admitted_workload("post", "cq1", 2))
        check(cache, "post-overflow sync")
        assert m.full_rebuilds == full_before + 1
        # The laggard solver consumer sees ITS overflow exactly once,
        # independently of the maintainer's.
        _, overflow = cache.drain_usage_journal(cache._journal_seq,
                                                consumer="solver")
        assert overflow
        _, overflow = cache.drain_usage_journal(cache._journal_seq,
                                                consumer="solver")
        assert not overflow

    def test_released_shells_are_not_recycled_across_a_rebuild(self):
        cache = build_cache()
        s1 = cache.snapshot()
        cache.release_snapshot(s1)
        cache._journal_cap = 4
        for i in range(10):  # overflow: the next sync must full-rebuild
            cache.add_or_update_workload(
                admitted_workload(f"x{i}", "cq0", 1))
        full_before = cache._maintainer.full_rebuilds
        s2 = check(cache, "post-overflow")
        assert cache._maintainer.full_rebuilds == full_before + 1
        # Every master was rebuilt: the released shells are stale and
        # none may be recycled into the post-rebuild handout.
        for name in s1.cluster_queues:
            assert s2.cluster_queues[name] is not s1.cluster_queues[name], \
                name

    def test_double_release_same_handout_is_a_guarded_noop(self):
        cache = build_cache()
        cache.add_or_update_workload(admitted_workload("w1", "cq0", 2))
        s1 = cache.snapshot()
        cache.release_snapshot(s1)
        cache.release_snapshot(s1)  # double release: guarded no-op
        s2 = check(cache, "after double release")
        # the recycled pool was consumed by s2; releasing s1 AGAIN (now
        # stale by generation) must not resurrect its shells
        cache.release_snapshot(s1)
        s3 = check(cache, "after stale re-release")
        for name in s3.cluster_queues:
            assert s3.cluster_queues[name] is not s2.cluster_queues[name], \
                name


class TestIncrementalSmoke:
    def test_three_cycle_steady_state_takes_the_incremental_path(self):
        # a 3-cycle steady-state scheduler run: exactly one full build
        # (the establishing snapshot), every later cycle replays
        from tests.test_scheduler import Env
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .resource_group(flavor_quotas("default", cpu="100"))
                   .obj(), "lq")
        stats = env.cache.snapshot_stats
        for i in range(3):
            env.submit(WorkloadWrapper(f"w{i}").queue("lq")
                       .pod_set(count=1, cpu="1").obj())
            env.cycle()
            assert f"default/w{i}" in env.client.applied
        assert stats["full"] == 1, stats
        assert stats["incremental"] == 2, stats
        m = env.cache._maintainer
        assert m.full_rebuilds == 1 and m.incremental_advances == 2
