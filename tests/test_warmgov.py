"""Compile governor (kueue_tpu/solver/warmgov.py + solver/COMPILE.md):
ladder derivation, the scheduler's cpu-warmup route gate, warmup chaos
(a wedged/erroring compile must never wedge startup or trip the
breaker), restart reuse through the persistent compilation cache, and
the operator surface (/debug/warmup, dumper section, manager wiring).
"""

import os

import pytest

from kueue_tpu.metrics import Registry
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.breaker import CLOSED
from kueue_tpu.resilience.faultinject import (
    DELAY, RAISE, SITE_WARMUP, FaultInjector)
from kueue_tpu.solver import warmgov
from kueue_tpu.solver.warmgov import (
    B_SKIPPED, B_WARM, GOV_IDLE, GOV_PARTIAL, GOV_WARM, GOV_WARMING,
    CompileGovernor, rank_ladder, snapshot_cohort_members, width_ladder)
from tests.test_scheduler import Env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faultinject.uninstall()


def simple_env(num_cqs=1, cohort=None):
    env = Env()
    env.add_flavor("default")
    for i in range(num_cqs):
        cq = ClusterQueueWrapper(f"cq{i}") \
            .resource_group(flavor_quotas("default", cpu="100"))
        if cohort is not None:
            cq = cq.cohort(cohort)
        env.add_cq(cq.obj(), f"lq{i}")
    return env


class StubWarmSolver:
    """Warm-capable solver stub: the governor's control flow (ladder,
    supervision, fault containment, provenance plumbing) without paying
    real compiles. ``programs_per_call`` is what each warm helper
    reports."""

    max_podsets = 4

    def __init__(self):
        self.warm_calls = []

    def warm_setup(self, snapshot, expected_pending=None):
        ctx = type("Ctx", (), {})()
        ctx.topo = None
        return ctx

    def warm_router(self, ctx, width):
        self.warm_calls.append(("router", width))
        return 1

    def warm_bucket(self, ctx, width, max_ranks=(8, 32),
                    deltas_buckets=(8,), fair_sharing=False):
        self.warm_calls.append(("bucket", width))
        return 2

    def warm_scatter(self, ctx):
        self.warm_calls.append(("scatter", None))
        return 1


class TestLadderDerivation:
    def test_width_ladder_is_geometric_largest_first(self):
        assert width_ladder(1) == [8]
        assert width_ladder(8) == [8]
        assert width_ladder(9) == [32, 8]
        assert width_ladder(2048) == [2048, 512, 128, 32, 8]
        # max_width caps the full-backlog bucket
        assert width_ladder(100_000, max_width=512) == [512, 128, 32, 8]

    def test_rank_ladder_covers_through_one_past_the_bound(self):
        # largest cohort 1 CQ -> bound 8 -> ladder through 32
        assert rank_ladder({"a": 1}) == (8, 32)
        # largest cohort 20 CQs -> bound 32 -> ladder through 128
        assert rank_ladder({"a": 20, "b": 2}) == (8, 32, 128)

    def test_cohort_members_from_snapshot(self):
        env = simple_env(num_cqs=3, cohort="co")
        members = snapshot_cohort_members(env.cache.snapshot())
        assert members == {"co": 3}
        env2 = simple_env(num_cqs=2)  # cohort-less: keyed by CQ name
        assert snapshot_cohort_members(env2.cache.snapshot()) \
            == {"cq0": 1, "cq1": 1}

    def test_extra_rungs_become_warmed_shapes(self):
        """Satellite: the soak_run --shapes feed closes the loop — an
        adversarially-synthesized off-ladder (B, K) key, fed back as an
        ``extra`` rung, becomes a first-class warmed shape at the
        reclaim geometry."""
        from kueue_tpu.sim.adversary import preempt_shape_report
        from kueue_tpu.solver.warmgov import (parse_shape_rung,
                                              preempt_shape_ladder)
        rep = preempt_shape_report(seed=0, samples=32)
        assert rep["off_ladder"], "sweep found no off-ladder shapes"
        rung = rep["suggested_rungs"][0]
        members = {"cohort-0": rep["topology"]["tenants"]}
        base = preempt_shape_ladder(members, width=64)
        fed = preempt_shape_ladder(members, width=64, extra=[rung])
        b, k = parse_shape_rung(rung)
        keys = {(s["B"], s["K"]) for s in fed}
        assert (b, k) in keys
        assert (b, k) not in {(s["B"], s["K"]) for s in base}
        # dedup: feeding a rung the ladder already covers is a no-op
        covered = (base[0]["B"], base[0]["K"])
        assert preempt_shape_ladder(members, width=64,
                                    extra=[covered]) == base
        # both accepted spellings agree
        assert parse_shape_rung(f"B{b}xK{k}") == parse_shape_rung((b, k))

    def test_governor_plumbs_extra_rungs(self):
        """extra_preempt_rungs reaches the governor's warm walk: the
        synthesized rung shows up in the preempt shape set start()
        derives."""
        from kueue_tpu.solver.warmgov import CompileGovernor
        env = simple_env(num_cqs=2, cohort="co")
        gov = CompileGovernor(StubWarmSolver(), env.cache,
                              extra_preempt_rungs=("B256xK512",))
        gov.run_sync()
        keys = {(s["B"], s["K"]) for s in gov._preempt_shapes}
        assert (256, 512) in keys


class TestRouteGate:
    def test_idle_governor_never_gates(self):
        gov = CompileGovernor(StubWarmSolver(), None)
        assert gov.state == GOV_IDLE
        assert gov.route_ready(1) and gov.route_ready(2048)

    def test_started_governor_gates_until_the_bucket_is_warm(self):
        gov = CompileGovernor(StubWarmSolver(), None)
        gov.state = GOV_WARMING  # as start() sets before the walk
        assert not gov.route_ready(10)
        gov._warm_widths = frozenset([32])
        assert gov.route_ready(10)      # _bucket(10) == 32
        assert not gov.route_ready(100)  # _bucket(100) == 128: unwarmed

    def test_scheduler_routes_cpu_warmup_and_requests_the_bucket(self):
        env = simple_env()
        from kueue_tpu.solver import BatchSolver
        env.scheduler.solver = BatchSolver()
        env.scheduler.solver_min_heads = 0
        env.scheduler.metrics = Registry()
        gov = CompileGovernor(StubWarmSolver(), env.cache)
        gov.start = lambda: None  # no background thread in this test
        gov.state = GOV_WARMING
        env.scheduler.warm_gov = gov
        env.submit(WorkloadWrapper("w").queue("lq0")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        # The cycle admitted on the CPU path under the distinct route
        # name — no device dispatch, no compile, not a router sample.
        assert "default/w" in env.client.applied
        assert env.scheduler.cycle_counts == {"cpu-warmup": 1}
        assert gov.unwarm_routed == 1
        assert not env.scheduler._route_stats
        assert env.scheduler.solver.counters["dispatches"] == 0
        # The un-warmed bucket was queued for a background warm.
        assert list(gov._requests) == [8]

    def test_mesh_backend_is_vacuously_warm(self):
        """warm_setup returns None for mesh/native backends (their
        dispatch paths cache separately): the governor must report warm
        AND the gate must never divert — an empty _warm_widths with a
        non-idle state would otherwise pin every cycle to cpu-warmup."""
        class MeshSolver(StubWarmSolver):
            def warm_setup(self, snapshot, expected_pending=None):
                return None

        env = simple_env()
        gov = CompileGovernor(MeshSolver(), env.cache)
        gov.run_sync()
        assert gov.state == GOV_WARM
        assert gov.route_ready(8) and gov.route_ready(2048)
        gov.request(8)  # no-op: nothing to warm on this backend
        assert not gov._requests and gov.unwarm_routed == 0

    def test_request_created_bucket_refreshed_by_walk(self):
        """A request() between start() and the walk creates its bucket
        with the placeholder ranks and no scatter claim; the walk must
        refresh it against the real ladder (and re-warm it), not skip
        it because the width key already exists."""
        env = simple_env(num_cqs=30, cohort="co")
        gov = CompileGovernor(StubWarmSolver(), env.cache)
        gov.start = lambda: None  # no background thread in this test
        gov.state = GOV_WARMING
        gov.request(20)  # width bucket 32, placeholder ranks
        assert gov.buckets[32].ranks == (8, 32)
        assert not gov.buckets[32].scatter
        gov.run_sync()
        assert gov.state == GOV_WARM
        # largest cohort 30 CQs -> bound 32 -> ladder through 128
        assert gov.buckets[32].ranks == (8, 32, 128)
        assert gov.buckets[32].scatter  # largest width carries scatter

    def test_warmed_sync_dispatch_counts_no_mid_traffic_compiles(self):
        """End-to-end key agreement: a real governor warm followed by a
        real sync device dispatch — every variant key the dispatch
        computes (including the normalized fs_strategies for a cycle
        with no fair batch) must have been registered by the warm
        helpers, so mid_traffic_compiles stays 0."""
        env = simple_env()
        from kueue_tpu.solver import BatchSolver
        solver = BatchSolver()
        env.scheduler.solver = solver
        env.scheduler.solver_min_heads = 0
        # Production wiring binds cache+queues at Scheduler
        # construction, BEFORE the governor warms — warm_setup keys the
        # arena decision on it (an arena-capable solver warms the
        # arena-gather variant at the floor capacity).
        solver.bind_cache(env.cache)
        solver.bind_queues(env.scheduler.queues)
        # warm_preempt off: this test pins the FIT-path key agreement;
        # the preemption-path analog (which needs the full preempt
        # shape ladder) lives in tests/test_preempt_batched.py
        gov = CompileGovernor(solver, env.cache, warm_preempt=False)
        assert gov.run_sync() > 0
        assert gov.state == GOV_WARM
        env.scheduler.warm_gov = gov
        env.submit(WorkloadWrapper("w").queue("lq0")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert "default/w" in env.client.applied
        assert env.scheduler.cycle_counts.get("device") == 1
        assert solver.counters["mid_traffic_compiles"] == 0

    def test_warm_bucket_routes_device_again(self):
        env = simple_env()
        from kueue_tpu.solver import BatchSolver
        env.scheduler.solver = BatchSolver()
        env.scheduler.solver_min_heads = 0
        gov = CompileGovernor(StubWarmSolver(), env.cache)
        gov.state = GOV_WARM
        gov._warm_widths = frozenset([8])
        env.scheduler.warm_gov = gov
        env.submit(WorkloadWrapper("w").queue("lq0")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert "default/w" in env.client.applied
        assert env.scheduler.cycle_counts.get("device") == 1
        assert gov.unwarm_routed == 0


class TestWarmupChaos:
    def test_hang_then_error_skips_the_bucket_not_startup(self):
        """The ISSUE 7 chaos contract: a wedged remote compile (DELAY at
        compile_warmup) is abandoned by the per-bucket deadline, the
        bucket retries at the ladder tail, a second fault skips it, and
        the walk COMPLETES — startup is never wedged, the scheduler
        keeps admitting via cpu-warmup, and the breaker never sees a
        fault (a warmup fault is not a device-path fault)."""
        env = simple_env()
        metrics = Registry()
        gov = CompileGovernor(StubWarmSolver(), env.cache,
                              metrics=metrics, bucket_deadline_s=0.05)
        faultinject.install(FaultInjector(
            {SITE_WARMUP: {0: (DELAY, 0.3), 1: RAISE}}))
        gov.run_sync()
        faultinject.uninstall()
        assert gov.state == GOV_PARTIAL
        (b,) = gov.buckets.values()
        assert b.state == B_SKIPPED and b.attempts == 2
        assert "deadline" in b.error or "Injected" in b.error \
            or "Timeout" in b.error or b.error
        assert gov.warmup_faults == 2
        assert metrics.warmup_faults_total.value() == 2
        # a skipped bucket is an operator decision: request() won't
        # re-queue it
        gov.request(4)
        assert not gov._requests
        # the scheduler still admits (cpu-warmup — the gate holds), and
        # warmup faults never touched the breaker
        env.scheduler.warm_gov = gov
        from kueue_tpu.solver import BatchSolver
        env.scheduler.solver = BatchSolver()
        env.scheduler.solver_min_heads = 0
        env.submit(WorkloadWrapper("w").queue("lq0")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert "default/w" in env.client.applied
        assert env.scheduler.cycle_counts == {"cpu-warmup": 1}
        assert env.scheduler.breaker.state == CLOSED
        assert env.scheduler.solver_faults == 0
        gov.stop()

    def test_background_start_completes_under_chaos(self):
        """The supervised background walk (the production startup path)
        finishes despite a first-bucket fault; the retry at the ladder
        tail succeeds and the governor reaches fully warm."""
        env = simple_env()
        solver = StubWarmSolver()
        gov = CompileGovernor(solver, env.cache, bucket_deadline_s=5.0)
        faultinject.install(FaultInjector({SITE_WARMUP: {0: RAISE}}))
        gov.start()
        assert gov.state == GOV_WARMING  # the gate engages immediately
        try:
            import time
            deadline = time.time() + 10.0
            while gov.state == GOV_WARMING and time.time() < deadline:
                time.sleep(0.01)
        finally:
            faultinject.uninstall()
            gov.stop()
        assert gov.state == GOV_WARM
        (b,) = gov.buckets.values()
        assert b.state == B_WARM and b.attempts == 2
        assert gov.warmup_faults == 1

    def test_background_walk_rewalks_on_structural_change(self):
        """The topology gate releases on the FIRST reconciled CQ, which
        can be mid-startup: the background walk re-walks until the
        structural generation token is stable across a walk, so the
        ladder is never frozen from a partial topology."""
        import time

        env = simple_env()
        solver = StubWarmSolver()
        gov = CompileGovernor(solver, env.cache)
        toks = iter([1, 2])  # changed across the first walk, then stable
        gov._gen_token = lambda: next(toks, 2)
        gov.start()
        deadline = time.time() + 10.0
        while gov.state == GOV_WARMING and time.time() < deadline:
            time.sleep(0.01)
        gov.stop()
        assert gov.state == GOV_WARM
        # two full walks: the partial-topology one, then the stable one
        assert len([c for c in solver.warm_calls
                    if c[0] == "bucket"]) == 2

    def test_walk_level_failure_is_contained(self):
        """A warm_setup failure (snapshot/encode error) degrades to the
        route gate — logged and counted, never raised to the caller."""
        class BrokenSolver(StubWarmSolver):
            def warm_setup(self, snapshot, expected_pending=None):
                raise RuntimeError("boom")

        env = simple_env()
        metrics = Registry()
        gov = CompileGovernor(BrokenSolver(), env.cache, metrics=metrics)
        assert gov.run_sync() == 0  # no raise
        assert gov.state == GOV_PARTIAL
        assert gov.warmup_faults == 1
        assert metrics.warmup_faults_total.value() == 1


class TestRestartReuse:
    def test_second_instance_is_fully_warm_with_zero_fresh_compiles(
            self, tmp_path, monkeypatch):
        """Two solver instances sharing one persistent cache dir: the
        first compiles the ladder fresh; after a simulated restart
        (cleared jit caches + a new solver), the second governor
        reaches fully-warm purely from the cache — zero fresh compiles,
        asserted via the compile-event counters."""
        import jax

        from kueue_tpu.solver import BatchSolver
        from kueue_tpu.solver import service as svc

        # One rank bucket + no delta variants: the smallest real ladder
        # (the provenance machinery is what's under test, not coverage
        # of every variant — tests/test_solver.py owns kernel coverage).
        monkeypatch.setattr(warmgov, "rank_ladder", lambda members: (8,))
        cache_dir = str(tmp_path / "compile-cache")
        # A clean first "process": earlier tests may have left these
        # programs in the in-process jit cache, which would keep
        # instance 1 from compiling (and therefore persisting) them.
        jax.clear_caches()
        svc.reset_seen_programs()

        def one_instance():
            env = simple_env()
            reg = Registry()
            gov = CompileGovernor(BatchSolver(), env.cache, metrics=reg,
                                  cache_dir=cache_dir, deltas_buckets=())
            warmed = gov.run_sync()
            return gov, reg, warmed

        gov1, reg1, warmed1 = one_instance()
        assert gov1.state == GOV_WARM and warmed1 > 0
        assert gov1.cache_subdir.startswith(cache_dir)
        if not any(files for _, _, files in os.walk(cache_dir)):
            pytest.skip("persistent compilation cache not supported on "
                        "this backend/jax build")
        # the fresh compiles were seen by the event counters
        assert sum(v for k, v in
                   reg1.compile_events_total.values.items()
                   if k[1] == "fresh") > 0

        # --- simulated restart ---
        jax.clear_caches()
        svc.reset_seen_programs()
        gov2, reg2, warmed2 = one_instance()
        assert gov2.state == GOV_WARM and warmed2 == warmed1
        for b in gov2.buckets.values():
            assert b.state == B_WARM
            assert b.source == "cache-hit", b.to_dict()
        # zero fresh compiles in the restarted instance
        assert sum(v for k, v in
                   reg2.compile_events_total.values.items()
                   if k[1] == "fresh") == 0
        assert sum(v for k, v in
                   reg2.compile_events_total.values.items()
                   if k[1] == "cache-hit") > 0

    def test_topology_change_lands_in_a_different_cache_subdir(
            self, monkeypatch):
        """The per-topology stamp: different topology dims -> different
        cache layout, so a restart can never replay stale executables."""
        import numpy as np

        class Topo:
            def __init__(self, q):
                self.nominal = np.zeros((q, 2, 3))
                self.cohort_subtree = np.zeros((4, 2, 3))
                self.cq_chain = np.zeros((q, 1))

        fp_a = warmgov.topology_fingerprint(Topo(8), 4)
        fp_b = warmgov.topology_fingerprint(Topo(9), 4)
        fp_c = warmgov.topology_fingerprint(Topo(8), 2)
        assert fp_a == warmgov.topology_fingerprint(Topo(8), 4)
        assert len({fp_a, fp_b, fp_c}) == 3


class TestOperatorSurface:
    def test_debug_warmup_endpoint_and_dumper(self):
        import io

        from kueue_tpu.debugger import Dumper
        from kueue_tpu.obs import DebugEndpoints, warmup_status

        env = simple_env()
        ep = DebugEndpoints(env.scheduler)
        unattached = ep.handle("/debug/warmup", {})
        # the endpoint additionally stamps the generation token it
        # rendered under (ISSUE 12 satellite)
        assert unattached.pop("generation") == \
            list(env.cache.generation_token())
        assert unattached == {"attached": False}

        gov = CompileGovernor(StubWarmSolver(), env.cache)
        gov.run_sync()
        env.scheduler.warm_gov = gov
        st = ep.handle("/debug/warmup", {})
        assert st["attached"] and st["state"] == GOV_WARM
        assert st["buckets"] and st["buckets"][0]["state"] == B_WARM
        assert st["cpu_warmup_cycles"] == 0
        st.pop("generation")  # the endpoint's staleness stamp
        assert st == warmup_status(env.scheduler)  # one producer

        out = io.StringIO()
        Dumper(env.cache, env.queues, out=out,
               scheduler=env.scheduler).write()
        dump = out.getvalue()
        assert "-- warmup --" in dump and "bucket width=8" in dump

    def test_governor_status_roundtrips_json(self):
        import json

        env = simple_env()
        gov = CompileGovernor(StubWarmSolver(), env.cache)
        gov.run_sync()
        json.dumps(gov.status())  # must be JSON-able for /debug/warmup

    def test_metrics_warmup_state_gauge(self):
        reg = Registry()
        for state, code in (("idle", 0), ("warming", 1), ("warm", 2),
                            ("partial", 3)):
            reg.set_warmup_state(state)
            assert reg.warmup_state.value() == code


class TestManagerWiring:
    def test_manager_attaches_governor_and_knobs(self, tmp_path):
        from kueue_tpu import config as cfgpkg
        from kueue_tpu.api.meta import FakeClock
        from kueue_tpu.manager import KueueManager
        from kueue_tpu.solver import BatchSolver

        cache_dir = str(tmp_path / "cc")
        cfg = cfgpkg.Configuration()
        cfg.solver.enable = True
        cfg.solver.compile_cache_dir = cache_dir
        cfg.solver.warmup_deadline_s = 7.0
        mgr = KueueManager(cfg=cfg, clock=FakeClock(0.0),
                           solver=BatchSolver())
        gov = mgr.warm_governor
        assert gov is not None
        assert mgr.scheduler.warm_gov is gov
        assert gov.cache_dir == cache_dir
        assert gov.bucket_deadline_s == 7.0
        # warmupAtStartup defaults off: deterministic drivers see an
        # idle (non-gating) governor
        assert gov.state == GOV_IDLE and gov._thread is None

    def test_manager_without_solver_has_no_governor(self):
        from kueue_tpu.api.meta import FakeClock
        from kueue_tpu.manager import KueueManager
        mgr = KueueManager(clock=FakeClock(0.0))
        assert mgr.warm_governor is None

    def test_config_knobs_parse_and_validate(self):
        from kueue_tpu import config as cfgpkg
        cfg = cfgpkg.load({"solver": {"compileCacheDir": "/x",
                                      "warmupAtStartup": True,
                                      "warmupDeadline": 30.0}})
        assert cfg.solver.compile_cache_dir == "/x"
        assert cfg.solver.warmup_at_startup is True
        assert cfg.solver.warmup_deadline_s == 30.0
        bad = cfgpkg.Configuration()
        bad.solver.warmup_deadline_s = 0
        assert any("warmupDeadline" in e for e in cfgpkg.validate(bad))
