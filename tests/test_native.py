"""Differential tests: the native (C++) solver backend vs the jitted
kernel vs the sequential CPU scheduler.

The native backend must be bit-identical to the jit kernel (same port of
the same semantics) and therefore also match the CPU conformance oracle
on fit-mode cycles.
"""

import random

import pytest

from kueue_tpu import native
from kueue_tpu.solver import BatchSolver
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable (no g++?)")


def build_native_env(setup):
    env = build_env(setup, solver=False)
    env.scheduler.solver = BatchSolver(backend="native")
    env.scheduler.solver_min_heads = 0
    env.scheduler.solver_sync_floor_ms = 0
    return env


def assert_three_way(setup, workloads, cycles=1):
    """CPU oracle, jit solver and native solver must all agree."""
    envs = {
        "cpu": build_env(setup, solver=False),
        "jit": build_env(setup, solver=True),
        "native": build_native_env(setup),
    }
    for env in envs.values():
        for w in workloads():
            env.submit(w)
        for _ in range(cycles):
            env.cycle()
    results = {name: admitted_map(env) for name, env in envs.items()}
    assert results["native"] == results["jit"], \
        f"native {sorted(results['native'])} != jit {sorted(results['jit'])}"
    assert results["native"] == results["cpu"], \
        f"native {sorted(results['native'])} != cpu {sorted(results['cpu'])}"
    return results["native"]


class TestNativeBackend:
    def test_basic_fit(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(),
                       "lq")

        result = assert_three_way(
            setup,
            lambda: [WorkloadWrapper("w").queue("lq").pod_set(count=2, cpu="2").obj()])
        assert "default/w" in result

    def test_cohort_borrowing_contention(self):
        def setup(env):
            env.add_flavor("default")
            for name in ("a", "b"):
                env.add_cq(ClusterQueueWrapper(name).cohort("team")
                           .resource_group(flavor_quotas("default", cpu="5")).obj(),
                           f"lq-{name}")

        def workloads():
            return [
                WorkloadWrapper("w1").queue("lq-a").priority(5).creation(1)
                .pod_set(count=1, cpu="8").obj(),
                WorkloadWrapper("w2").queue("lq-b").priority(1).creation(2)
                .pod_set(count=1, cpu="8").obj(),
            ]

        result = assert_three_way(setup, workloads)
        assert set(result) == {"default/w1"}

    def test_flavor_order_and_try_next(self):
        def setup(env):
            env.add_flavor("spot")
            env.add_flavor("on-demand")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .flavor_fungibility(when_can_borrow="TryNextFlavor")
                       .resource_group(flavor_quotas("spot", cpu="4"),
                                       flavor_quotas("on-demand", cpu="8")).obj(),
                       "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("spot", cpu="4")).obj(),
                       "lq-b")

        def workloads():
            # 6 cpu: spot would need borrowing; TryNextFlavor prefers the
            # no-borrow on-demand fit
            return [WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="6").obj()]

        result = assert_three_way(setup, workloads)
        assert result["default/w"][0][0][0][1] == "on-demand"

    @pytest.mark.parametrize("seed", range(20))
    def test_random_three_way(self, seed):
        rng = random.Random(1000 + seed)
        n_cohorts = rng.randint(1, 3)
        n_cqs = rng.randint(2, 6)
        flavors = [f"f{i}" for i in range(rng.randint(1, 3))]

        cq_specs = []
        for i in range(n_cqs):
            cohort = f"cohort-{rng.randrange(n_cohorts)}" if rng.random() < 0.8 else ""
            fqs = []
            for f in flavors:
                nominal = rng.choice(["2", "5", "10"])
                borrowing = rng.choice([None, "0", "5", None])
                lending = rng.choice([None, "1", None])
                fqs.append(flavor_quotas(f, cpu=(nominal, borrowing, lending)))
            cq_specs.append((f"cq{i}", cohort, fqs))

        def setup(env):
            for f in flavors:
                env.add_flavor(f)
            for name, cohort, fqs in cq_specs:
                w = ClusterQueueWrapper(name)
                if cohort:
                    w = w.cohort(cohort)
                env.add_cq(w.resource_group(*fqs).obj(), f"lq-{name}")

        wl_specs = []
        for i in range(rng.randint(3, 14)):
            cq = rng.randrange(n_cqs)
            wl_specs.append((f"w{i}", f"lq-cq{cq}", rng.randint(0, 3),
                            float(i), rng.choice(["1", "2", "4", "7", "12"])))

        def workloads():
            return [WorkloadWrapper(name).queue(q).priority(p).creation(ts)
                    .pod_set(count=1, cpu=cpu).obj()
                    for name, q, p, ts, cpu in wl_specs]

        assert_three_way(setup, workloads)

    def test_kernel_level_agreement(self):
        """Compare raw kernel outputs (incl. usage tensors) on an encoded
        batch — stricter than the admitted-set comparison."""
        import numpy as np
        from kueue_tpu.solver import encode
        from kueue_tpu.solver.kernel import solve_cycle, topo_to_device

        def setup(env):
            env.add_flavor("f0")
            env.add_flavor("f1")
            for name in ("a", "b", "c"):
                env.add_cq(ClusterQueueWrapper(name).cohort("team")
                           .resource_group(flavor_quotas("f0", cpu=("5", "5", "2")),
                                           flavor_quotas("f1", cpu="5")).obj(),
                           f"lq-{name}")

        env = build_env(setup, solver=False)
        rng = random.Random(7)
        for i in range(10):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-{rng.choice('abc')}")
                       .priority(rng.randint(0, 2)).creation(float(i))
                       .pod_set(count=1, cpu=rng.choice(["2", "4", "8"])).obj())
        heads = env.queues.heads_nonblocking()
        snapshot = env.cache.snapshot()
        topo = encode.encode_topology(snapshot)
        state = encode.encode_state(snapshot, topo)
        batch = encode.encode_workloads(heads, snapshot, topo)

        jit_out = solve_cycle(
            topo_to_device(topo), state.usage, state.cohort_usage,
            batch.requests, batch.podset_active, batch.wl_cq, batch.priority,
            batch.timestamp, batch.eligible, batch.solvable, num_podsets=4)
        nat_out = native.solve_cycle_native(
            topo, state.usage, state.cohort_usage, batch.requests,
            batch.podset_active, batch.wl_cq, batch.priority, batch.timestamp,
            batch.eligible, batch.solvable)

        for key in ("admitted", "fit", "borrows"):
            assert np.array_equal(np.asarray(jit_out[key]), nat_out[key]), key
        assert np.array_equal(np.asarray(jit_out["usage"]), nat_out["usage"])
        assert np.array_equal(np.asarray(jit_out["cohort_usage"]),
                              nat_out["cohort_usage"])
        # chosen flavors must agree wherever a podset is active & admitted
        jit_chosen = np.asarray(jit_out["chosen"])
        mask = batch.podset_active[:, :, None] & \
            np.asarray(jit_out["admitted"])[:, None, None] & \
            (batch.requests > 0)
        assert np.array_equal(jit_chosen[mask], nat_out["chosen"][mask])
