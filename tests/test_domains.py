"""Domain planner (parallel/domains.py) + multi-host mesh layout tests
(ISSUE 13): randomized planner-vs-naive balance properties, plan
determinism across process restarts (warm-ladder key stability), the
mesh executable-cache key fix, and the ≥2-simulated-hosts bit-identity
gate (subprocess via tools/mesh_probe.py — the host-platform device
count must be forced before jax initializes)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from kueue_tpu.parallel import domains

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBalancedPartition:
    def test_lpt_bound_randomized(self):
        # LPT guarantee: max load <= (4/3 - 1/(3m)) * OPT, and
        # OPT >= max(total/m, heaviest item).
        for seed in range(20):
            rng = np.random.default_rng(seed)
            n = int(rng.integers(1, 200))
            m = int(rng.integers(1, 9))
            w = rng.integers(1, 1000, size=n)
            bins, loads = domains.balanced_partition(w, m)
            assert loads.sum() == w.sum()
            # every item assigned to a valid bin
            assert ((bins >= 0) & (bins < m)).all()
            opt_lb = max(w.sum() / m, w.max())
            assert loads.max() <= opt_lb * (4 / 3) + 1e-9

    def test_beats_round_robin_on_residue_skew(self):
        # The pre-planner layout (domain d -> device d mod n) collapses
        # when heavy domains share a residue class — the exact shape a
        # big tenant's cohorts land in with stable domain ids. LPT
        # spreads them; round-robin stacks every heavy domain on one
        # device.
        n = 4
        w = np.ones(32, np.int64)
        w[::n] = 1000  # heavies all ≡ 0 (mod n)
        _, lpt_loads = domains.balanced_partition(w, n)
        _, rr_loads = domains.round_robin_partition(w, n)
        assert lpt_loads.max() < rr_loads.max()
        assert domains.imbalance_ratio(lpt_loads) < 1.5
        assert domains.imbalance_ratio(rr_loads) > 3.0

    def test_deterministic(self):
        rng = np.random.default_rng(7)
        w = rng.integers(1, 100, size=64)
        a1, l1 = domains.balanced_partition(w, 5)
        a2, l2 = domains.balanced_partition(w.copy(), 5)
        assert (a1 == a2).all() and (l1 == l2).all()


class TestDomainPlan:
    def _inputs(self, seed=0, Q=16, C=4, W=48, F=3, R=2):
        rng = np.random.default_rng(seed)
        cq_cohort = np.where(rng.random(Q) < 0.5,
                             rng.integers(0, C, size=Q), -1).astype(np.int32)
        cohort_root = np.arange(C, dtype=np.int32)
        offered = rng.random((Q, F, R)) < 0.7
        wl_cq = rng.integers(0, Q, size=W).astype(np.int32)
        return wl_cq, cq_cohort, cohort_root, offered

    def test_every_occupied_domain_exactly_once(self):
        wl_cq, cq_cohort, cohort_root, offered = self._inputs()
        plan = domains.plan_domains(wl_cq, cq_cohort, cohort_root,
                                    offered, 4)
        dom = domains.workload_domains(wl_cq, cq_cohort, cohort_root)
        assigned = plan.columns[plan.columns >= 0]
        assert sorted(assigned.tolist()) == sorted(set(dom.tolist()))
        assert plan.occupied == len(set(dom.tolist()))
        assert plan.imbalance >= 1.0
        assert plan.columns.shape == (4, plan.d_cols)

    def test_weights_are_count_times_flavor_width(self):
        # one CQ with wide flavors, one with a single flavor, equal
        # workload counts: the wide CQ's domain must carry more weight.
        Q, C, F, R = 2, 0, 4, 1
        cq_cohort = np.full(Q, -1, np.int32)
        cohort_root = np.zeros(0, np.int32)
        offered = np.zeros((Q, F, R), bool)
        offered[0, :, 0] = True      # flavor width 4
        offered[1, 0, 0] = True      # flavor width 1
        wl_cq = np.array([0] * 4 + [1] * 4, np.int32)
        plan = domains.plan_domains(wl_cq, cq_cohort, cohort_root,
                                    offered, 2)
        # each synthetic domain lands on its own device; the wide one
        # carries 4x the load
        loads = sorted(plan.loads.tolist())
        assert loads == [4, 16]

    def test_fingerprint_stable_across_processes(self):
        # Warm-ladder key stability: the fingerprint must be a pure
        # function of the layout (blake2b over bytes — no hash()/id()),
        # so a restarted process re-plans to the identical key.
        wl_cq, cq_cohort, cohort_root, offered = self._inputs(seed=3)
        p1 = domains.plan_domains(wl_cq, cq_cohort, cohort_root,
                                  offered, 4)
        code = (
            "import numpy as np, json, sys;"
            "from kueue_tpu.parallel import domains;"
            "a=[np.asarray(x) for x in json.load(sys.stdin)];"
            "p=domains.plan_domains(a[0],a[1],a[2],np.asarray(a[3],bool),4);"
            "print(p.fingerprint)")
        payload = json.dumps([wl_cq.tolist(), cq_cohort.tolist(),
                              cohort_root.tolist(), offered.tolist()])
        out = subprocess.run(
            [sys.executable, "-c", code], input=payload, cwd=REPO,
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == p1.fingerprint
        # and it is layout-sensitive
        p2 = domains.plan_domains(wl_cq, cq_cohort, cohort_root,
                                  offered, 2)
        assert p2.fingerprint != p1.fingerprint

    def test_plan_problems_roundtrip(self):
        rng = np.random.default_rng(5)
        for n_dev in (1, 3, 4):
            weights = rng.integers(1, 50, size=11)
            perm, inv, b_local = domains.plan_problems(weights, n_dev)
            assert len(perm) == n_dev * b_local
            # pad lanes point at the sentinel row (== N)
            real = perm[perm < len(weights)]
            assert sorted(real.tolist()) == list(range(len(weights)))
            # inv restores original order through the permuted layout
            gathered = perm.copy()  # "output" in perm order
            assert (gathered[inv] == np.arange(len(weights))).all()


class TestWarmLadderMeshFingerprint:
    def test_mesh_shape_keys_fingerprint(self):
        from kueue_tpu.solver.warmgov import topology_fingerprint

        class T:
            nominal = np.zeros((4, 2, 2))
            cohort_subtree = np.zeros((2, 2, 2))
            cq_chain = np.zeros((4, 1))

        class MeshLike:
            def __init__(self, names, shape):
                self.axis_names = names

                class D:
                    pass
                self.devices = np.empty(shape, object)

        base = topology_fingerprint(T, 4)
        assert base == topology_fingerprint(T, 4)  # deterministic
        one = topology_fingerprint(T, 4, mesh=MeshLike(("cohorts",), (8,)))
        two = topology_fingerprint(T, 4,
                                   mesh=MeshLike(("hosts", "cohorts"),
                                                 (2, 4)))
        four = topology_fingerprint(T, 4,
                                    mesh=MeshLike(("hosts", "cohorts"),
                                                  (4, 2)))
        assert len({base, one, two, four}) == 4  # every layout distinct
        assert two == topology_fingerprint(
            T, 4, mesh=MeshLike(("hosts", "cohorts"), (2, 4)))


class TestShardedExecutableCache:
    def test_cache_keys_on_layout_not_identity(self):
        # ISSUE 13 satellite: the pre-v4 cache keyed on id(mesh) — a
        # recycled allocation (or a re-built mesh over a different host
        # count) could be served a stale executable. The key is now the
        # full (axis names, shape, device set) fingerprint: two Mesh
        # OBJECTS over the same layout share one entry; a different
        # axis layout over the same device gets its own.
        import jax

        from kueue_tpu.parallel import mesh as meshmod
        from kueue_tpu.solver.encode import State
        from kueue_tpu.solver.synth import synth_solver_inputs
        import jax.numpy as jnp

        topo, usage, cohort_usage, wl = synth_solver_inputs(
            num_cqs=4, num_cohorts=1, num_flavors=2, num_resources=2,
            num_workloads=8)
        topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}

        class B:
            requests = wl["requests"]
            podset_active = wl["podset_active"]
            wl_cq = wl["wl_cq"]
            priority = wl["priority"]
            timestamp = wl["timestamp"]
            eligible = wl["eligible"]
            solvable = wl["solvable"]

        state = State(usage=usage, cohort_usage=cohort_usage)
        dev = jax.devices()[:1]
        meshmod._SHARDED_CACHE.clear()
        m1 = meshmod.make_mesh(dev)
        m2 = meshmod.make_mesh(dev)  # re-built mesh, same layout
        assert meshmod.mesh_fingerprint(m1) == meshmod.mesh_fingerprint(m2)
        meshmod.solve_cycle_sharded(m1, topo_dev, state, B, 1)
        n1 = len(meshmod._SHARDED_CACHE)
        meshmod.solve_cycle_sharded(m2, topo_dev, state, B, 1)
        assert len(meshmod._SHARDED_CACHE) == n1  # layout hit, no rebuild
        m3 = meshmod.make_host_mesh(dev, hosts=1)  # two-axis layout
        assert meshmod.mesh_fingerprint(m3) != meshmod.mesh_fingerprint(m1)
        r3 = meshmod.solve_cycle_sharded(m3, topo_dev, state, B, 1)
        assert len(meshmod._SHARDED_CACHE) == n1 + 1  # distinct entry
        # and the two-axis single-device program is still bit-identical
        r1 = meshmod.solve_cycle_sharded(m1, topo_dev, state, B, 1)
        assert bool(jnp.array_equal(r1["admitted"], r3["admitted"]))


@pytest.mark.slow
class TestMultiHostIdentitySweep:
    def test_probe_identity_wide(self):
        # The wide randomized sweep (tier-1 runs the smoke via
        # tests/test_tools.py::TestMeshProbe).
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "mesh_probe.py"),
             "--hosts", "1,2,4,8", "--devices", "8", "--check-identity",
             "--seed", "11", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=580,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr[-2000:]
        verdict = json.loads(out.stdout.strip().splitlines()[-1])
        assert verdict["ok"] and not verdict["identity_failures"]
