"""Scheduler cycle semantics: fit, borrowing, ordering, fungibility,
preemption, partial admission, StrictFIFO head blocking.

Transliterated from the core cases of the reference's
pkg/scheduler/scheduler_test.go, flavorassigner_test.go and
preemption_test.go.
"""

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import Taint
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.cache import Cache
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.queue import Manager
from kueue_tpu.scheduler import Scheduler
from kueue_tpu.scheduler.scheduler import SchedulerClient
from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)

CPU = "cpu"


class FakeClient(SchedulerClient):
    def __init__(self):
        self.applied = {}        # wl key -> workload (admission writes)
        self.evicted = {}        # wl key -> workload
        self.pending_patches = []
        self.events = []
        self.namespaces = {"default": {}}
        self.limitranges = {}

    def namespace_labels(self, namespace):
        return self.namespaces.get(namespace)

    def limit_ranges(self, namespace):
        return self.limitranges.get(namespace, [])

    def apply_admission(self, wl):
        if wlpkg.is_evicted(wl):
            self.evicted[wlpkg.key(wl)] = wl
        else:
            self.applied[wlpkg.key(wl)] = wl

    def patch_not_admitted(self, wl):
        self.pending_patches.append(wl)

    def event(self, wl, event_type, reason, message):
        self.events.append((wlpkg.key(wl), reason))


class Env:
    def __init__(self, fair_sharing=False, fs_strategies=None):
        self.clock = FakeClock(1000.0)
        self.cache = Cache()
        self.queues = Manager(clock=self.clock)
        self.client = FakeClient()
        self.scheduler = Scheduler(self.queues, self.cache, self.client,
                                   clock=self.clock, fair_sharing_enabled=fair_sharing,
                                   fs_preemption_strategies=fs_strategies)

    def add_flavor(self, name, labels=None, taints=None):
        self.cache.add_or_update_resource_flavor(make_flavor(name, labels, taints))

    def add_cohort(self, name, parent="", *fqs):
        from tests.wrappers import make_cohort
        self.cache.add_or_update_cohort(make_cohort(name, parent, *fqs))

    def add_cq(self, cq, lq_name=None):
        self.cache.add_cluster_queue(cq)
        self.queues.add_cluster_queue(cq)
        self.queues.add_local_queue(
            make_local_queue(lq_name or f"lq-{cq.metadata.name}", "default",
                             cq.metadata.name))

    def admit_existing(self, wl):
        """Pre-admitted workload occupying quota."""
        self.cache.add_or_update_workload(wl)

    def submit(self, wl):
        assert self.queues.add_or_update_workload(wl)

    def cycle(self):
        return self.scheduler.schedule(timeout=0.01)

    def usage(self, cq, flavor="default", resource=CPU):
        reservation, _ = self.cache.usage_for_cluster_queue(cq)
        return reservation.get(FlavorResource(flavor, resource), 0)


def simple_env(nominal="10", strategy=api.BEST_EFFORT_FIFO):
    env = Env()
    env.add_flavor("default")
    env.add_cq(ClusterQueueWrapper("cq").queueing_strategy(strategy)
               .resource_group(flavor_quotas("default", cpu=nominal)).obj(), "lq")
    return env


class TestBasicAdmission:
    def test_admits_when_fits(self):
        env = simple_env()
        w = WorkloadWrapper("w").queue("lq").pod_set(count=2, cpu="2").obj()
        env.submit(w)
        env.cycle()
        applied = env.client.applied["default/w"]
        assert wlpkg.has_quota_reservation(applied)
        assert wlpkg.is_admitted(applied)  # no admission checks
        psa = applied.status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "default"
        assert psa.resource_usage[CPU] == 4000
        assert env.usage("cq") == 4000

    def test_pending_when_no_quota(self):
        env = simple_env(nominal="1")
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="2").obj()
        env.submit(w)
        env.cycle()
        assert "default/w" not in env.client.applied
        assert env.client.pending_patches  # Pending condition written
        assert env.queues.cluster_queues["cq"].pending_inadmissible() == 1

    def test_namespace_selector_mismatch(self):
        env = Env()
        env.add_flavor("default")
        from kueue_tpu.api.meta import LabelSelector
        cq = (ClusterQueueWrapper("cq")
              .resource_group(flavor_quotas("default", cpu="10")).obj())
        cq.spec.namespace_selector = LabelSelector(match_labels={"team": "x"})
        env.add_cq(cq, "lq")
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        env.submit(w)
        env.cycle()
        assert "default/w" not in env.client.applied

    def test_requests_exceeding_limits_rejected(self):
        env = simple_env()
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        w.spec.pod_sets[0].template.spec.containers[0].limits[CPU] = 500
        env.submit(w)
        env.cycle()
        assert "default/w" not in env.client.applied

    def test_admission_checks_keep_admitted_false(self):
        env = Env()
        env.add_flavor("default")
        from kueue_tpu.api.meta import Condition, set_condition
        ac = api.AdmissionCheck()
        ac.metadata.name = "prov"
        set_condition(ac.status.conditions, Condition(
            type=api.ADMISSION_CHECK_ACTIVE, status="True"), 1.0)
        env.cache.add_or_update_admission_check(ac)
        env.add_cq(ClusterQueueWrapper("cq").admission_checks("prov")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq")
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        env.submit(w)
        env.cycle()
        applied = env.client.applied["default/w"]
        assert wlpkg.has_quota_reservation(applied)
        assert not wlpkg.is_admitted(applied)


class TestCohortBorrowing:
    def make_cohort_env(self):
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("a").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")
        return env

    def test_borrows_cohort_capacity(self):
        env = self.make_cohort_env()
        w = WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="15").obj()
        env.submit(w)
        env.cycle()
        assert "default/w" in env.client.applied

    def test_non_borrowing_admitted_first(self):
        env = self.make_cohort_env()
        # borrower (12 > nominal 10) vs non-borrower; both fit only one.
        big = WorkloadWrapper("big").queue("lq-a").priority(100).creation(1) \
            .pod_set(count=1, cpu="12").obj()
        small = WorkloadWrapper("small").queue("lq-b").priority(0).creation(2) \
            .pod_set(count=1, cpu="10").obj()
        env.submit(big)
        env.submit(small)
        env.cycle()
        # small doesn't borrow -> goes first despite lower priority; big then
        # no longer fits (only 10 left in cohort).
        assert "default/small" in env.client.applied
        assert "default/big" not in env.client.applied

    def test_borrowing_limit_respected(self):
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("a").cohort("team")
                   .resource_group(flavor_quotas("default", cpu=("10", "2", None))).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")
        w = WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="13").obj()
        env.submit(w)
        env.cycle()
        assert "default/w" not in env.client.applied


class TestFlavorFungibility:
    def make_two_flavor_env(self, **fung):
        env = Env()
        env.add_flavor("spot")
        env.add_flavor("on-demand")
        cq = (ClusterQueueWrapper("cq")
              .resource_group(flavor_quotas("spot", cpu="5"),
                              flavor_quotas("on-demand", cpu="10")))
        if fung:
            cq = cq.flavor_fungibility(**fung)
        env.add_cq(cq.obj(), "lq")
        return env

    def test_second_flavor_when_first_full(self):
        env = self.make_two_flavor_env()
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="8").obj()
        env.submit(w)
        env.cycle()
        psa = env.client.applied["default/w"].status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "on-demand"

    def test_first_flavor_when_fits(self):
        env = self.make_two_flavor_env()
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="4").obj()
        env.submit(w)
        env.cycle()
        psa = env.client.applied["default/w"].status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "spot"

    def test_untolerated_taint_skips_flavor(self):
        env = Env()
        env.add_flavor("tainted", taints=[Taint(key="gpu", value="y", effect="NoSchedule")])
        env.add_flavor("clean")
        env.add_cq(ClusterQueueWrapper("cq")
                   .resource_group(flavor_quotas("tainted", cpu="10"),
                                   flavor_quotas("clean", cpu="10")).obj(), "lq")
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        env.submit(w)
        env.cycle()
        psa = env.client.applied["default/w"].status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "clean"

    def test_node_selector_picks_matching_flavor(self):
        env = Env()
        env.add_flavor("zone-a", labels={"zone": "a"})
        env.add_flavor("zone-b", labels={"zone": "b"})
        env.add_cq(ClusterQueueWrapper("cq")
                   .resource_group(flavor_quotas("zone-a", cpu="10"),
                                   flavor_quotas("zone-b", cpu="10")).obj(), "lq")
        w = (WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1")
             .node_selector("zone", "b").obj())
        env.submit(w)
        env.cycle()
        psa = env.client.applied["default/w"].status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "zone-b"

    def test_affinity_in_expression(self):
        env = Env()
        env.add_flavor("zone-a", labels={"zone": "a"})
        env.add_flavor("zone-b", labels={"zone": "b"})
        env.add_cq(ClusterQueueWrapper("cq")
                   .resource_group(flavor_quotas("zone-a", cpu="10"),
                                   flavor_quotas("zone-b", cpu="10")).obj(), "lq")
        w = (WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1")
             .affinity_in("zone", "b").obj())
        env.submit(w)
        env.cycle()
        psa = env.client.applied["default/w"].status.admission.pod_set_assignments[0]
        assert psa.flavors[CPU] == "zone-b"


class TestPreemption:
    def make_preempt_env(self, **preemption):
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(**preemption)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq")
        return env

    def test_preempts_lower_priority_in_cq(self):
        env = self.make_preempt_env(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        victim = (WorkloadWrapper("victim").queue("lq").priority(0)
                  .pod_set(count=1, cpu="8").reserve("cq", now=100.0).obj())
        env.admit_existing(victim)
        preemptor = (WorkloadWrapper("pre").queue("lq").priority(100)
                     .pod_set(count=1, cpu="8").obj())
        env.submit(preemptor)
        env.cycle()
        # victim evicted, preemptor pending the preemption
        assert "default/victim" in env.client.evicted
        evicted = env.client.evicted["default/victim"]
        assert wlpkg.is_evicted(evicted)
        assert "default/pre" not in env.client.applied
        # simulate the controller processing the eviction:
        env.cache.delete_workload(victim)
        env.queues.queue_inadmissible_workloads({"cq"})
        env.cycle()
        assert "default/pre" in env.client.applied

    def test_no_preemption_when_policy_never(self):
        env = self.make_preempt_env()
        victim = (WorkloadWrapper("victim").queue("lq").priority(0)
                  .pod_set(count=1, cpu="8").reserve("cq", now=100.0).obj())
        env.admit_existing(victim)
        preemptor = (WorkloadWrapper("pre").queue("lq").priority(100)
                     .pod_set(count=1, cpu="8").obj())
        env.submit(preemptor)
        env.cycle()
        assert not env.client.evicted

    def test_equal_priority_not_preempted_with_lower_priority_policy(self):
        env = self.make_preempt_env(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        victim = (WorkloadWrapper("victim").queue("lq").priority(100)
                  .pod_set(count=1, cpu="8").reserve("cq", now=100.0).obj())
        env.admit_existing(victim)
        preemptor = (WorkloadWrapper("pre").queue("lq").priority(100)
                     .pod_set(count=1, cpu="8").obj())
        env.submit(preemptor)
        env.cycle()
        assert not env.client.evicted

    def test_lower_or_newer_equal_priority(self):
        env = self.make_preempt_env(
            within_cluster_queue=api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY)
        victim = (WorkloadWrapper("victim").queue("lq").priority(100).creation(200.0)
                  .pod_set(count=1, cpu="8").reserve("cq", now=300.0).obj())
        env.admit_existing(victim)
        preemptor = (WorkloadWrapper("pre").queue("lq").priority(100).creation(100.0)
                     .pod_set(count=1, cpu="8").obj())
        env.submit(preemptor)
        env.cycle()
        assert "default/victim" in env.client.evicted

    def test_reclaim_within_cohort(self):
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("a").cohort("team")
                   .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq-b")
        # b borrows the whole cohort
        borrower = (WorkloadWrapper("borrower").queue("lq-b").priority(100)
                    .pod_set(count=1, cpu="18").reserve("b", now=100.0).obj())
        env.admit_existing(borrower)
        # a reclaims its nominal quota, even against higher priority (Any)
        reclaimer = (WorkloadWrapper("reclaimer").queue("lq-a").priority(0)
                     .pod_set(count=1, cpu="8").obj())
        env.submit(reclaimer)
        env.cycle()
        assert "default/borrower" in env.client.evicted

    def test_minimal_set_preempted(self):
        env = self.make_preempt_env(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        for i, cpu in enumerate(["4", "4", "2"]):
            v = (WorkloadWrapper(f"v{i}").queue("lq").priority(i)
                 .pod_set(count=1, cpu=cpu).reserve("cq", now=100.0 + i).obj())
            env.admit_existing(v)
        preemptor = (WorkloadWrapper("pre").queue("lq").priority(100)
                     .pod_set(count=1, cpu="4").obj())
        env.submit(preemptor)
        env.cycle()
        # needs only 4 cpus; v0 (lowest prio, 4 cpu) suffices after fill-back
        assert set(env.client.evicted) == {"default/v0"}


class TestPartialAdmission:
    def test_count_reduced_to_fit(self):
        env = simple_env(nominal="6")
        w = (WorkloadWrapper("w").queue("lq")
             .pod_set(count=10, min_count=2, cpu="1").obj())
        env.submit(w)
        env.cycle()
        applied = env.client.applied["default/w"]
        psa = applied.status.admission.pod_set_assignments[0]
        assert psa.count == 6
        assert psa.resource_usage[CPU] == 6000

    def test_no_partial_when_gate_disabled(self):
        from kueue_tpu import features
        env = simple_env(nominal="6")
        w = (WorkloadWrapper("w").queue("lq")
             .pod_set(count=10, min_count=2, cpu="1").obj())
        env.submit(w)
        with features.override(PartialAdmission=False):
            env.cycle()
        assert "default/w" not in env.client.applied


class TestStrictFIFO:
    def test_head_blocks_queue(self):
        env = simple_env(nominal="5", strategy=api.STRICT_FIFO)
        big = WorkloadWrapper("big").queue("lq").creation(1).pod_set(count=1, cpu="8").obj()
        small = WorkloadWrapper("small").queue("lq").creation(2).pod_set(count=1, cpu="1").obj()
        env.submit(big)
        env.submit(small)
        env.cycle()
        env.cycle()
        assert "default/small" not in env.client.applied  # blocked behind big

    def test_best_effort_skips_head(self):
        env = simple_env(nominal="5", strategy=api.BEST_EFFORT_FIFO)
        big = WorkloadWrapper("big").queue("lq").creation(1).pod_set(count=1, cpu="8").obj()
        small = WorkloadWrapper("small").queue("lq").creation(2).pod_set(count=1, cpu="1").obj()
        env.submit(big)
        env.submit(small)
        env.cycle()
        env.cycle()
        assert "default/small" in env.client.applied


class TestFairSharing:
    def test_lower_share_admitted_first(self):
        env = Env(fair_sharing=True)
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("a").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="8")).obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="8")).obj(), "lq-b")
        env.add_cq(ClusterQueueWrapper("c").cohort("team")
                   .resource_group(flavor_quotas("default", cpu="8")).obj(), "lq-c")
        # a is already borrowing heavily
        hog = (WorkloadWrapper("hog").queue("lq-a").pod_set(count=1, cpu="16")
               .reserve("a", now=50.0).obj())
        env.admit_existing(hog)
        # both borrow, but b would borrow less than a's hypothetical second
        wa = WorkloadWrapper("wa").queue("lq-a").creation(1).pod_set(count=1, cpu="8").obj()
        wb = WorkloadWrapper("wb").queue("lq-b").creation(2).pod_set(count=1, cpu="8").obj()
        env.submit(wa)
        env.submit(wb)
        env.cycle()
        assert "default/wb" in env.client.applied
        assert "default/wa" not in env.client.applied


class TestAdaptiveRouter:
    """Regime-keyed adaptive engine routing (VERDICT r4 ask #2): the
    fit and preempt backlog shapes carry independent per-engine
    estimates; compile-inflated samples are damped by a median-rate
    estimator; exploration of a badly losing engine is backed off."""

    @staticmethod
    def _env():
        env = Env()
        env.scheduler.solver = object()  # routing only inspects presence
        env.scheduler.solver_min_heads = 0
        env.scheduler.solver_routing = "adaptive"
        return env

    def _sched(self):
        return self._env().scheduler

    def test_mandatory_samples_per_regime(self):
        s = self._sched()
        heads = [object()]
        assert s._route_mode(heads) == "device"  # no device samples yet
        s._cycle_regime = "fit"
        s._route_record("device", 10, 1.0)
        s._route_record("device", 10, 1.0)
        assert s._route_mode(heads) == "cpu"     # no cpu samples yet
        s._route_record("cpu", 10, 1.0)
        s._route_record("cpu", 10, 1.0)
        assert s._route_mode(heads) in ("cpu", "device")
        # a regime never seen still needs its own samples
        s._last_regime = "preempt"
        assert s._route_mode(heads) == "device"

    def test_regimes_route_independently(self):
        s = self._sched()
        heads = [object()]
        for _ in range(3):
            s._cycle_regime = "fit"
            s._route_record("device", 100, 1.0)   # device wins fit
            s._route_record("cpu", 50, 1.0)
            s._cycle_regime = "preempt"
            s._route_record("device", 10, 1.0)    # cpu wins preempt
            s._route_record("cpu", 90, 1.0)
        s._last_regime = "fit"
        assert s._route_mode(heads) == "device"
        s._last_regime = "preempt"
        assert s._route_mode(heads) == "cpu"

    def test_median_rate_survives_multiple_compile_outliers(self):
        s = self._sched()
        heads = [object()]
        s._cycle_regime = "fit"
        # 3 compile-inflated device cycles out of 7: trim-one would stay
        # poisoned; the median rate is a clean sample
        for t in (30.0, 20.0, 10.0):   # compiles
            s._route_record("device", 100, t)
        for _ in range(4):
            s._route_record("device", 100, 0.5)  # warm: 200/s
        for _ in range(4):
            s._route_record("cpu", 100, 1.0)     # 100/s
        s._last_regime = "fit"
        assert s._route_mode(heads) == "device"

    def test_exploration_backoff_when_losing_badly(self):
        s = self._sched()
        heads = [object()]
        s._cycle_regime = "fit"
        for _ in range(4):
            s._route_record("device", 1, 1.0)    # 1/s: hopeless
            s._route_record("cpu", 100, 1.0)     # 100/s
        s._last_regime = "fit"
        routes = [s._route_mode(heads) for _ in range(64)]
        assert routes.count("device") == 1       # 1/64, not 4/64
        # close race: explore at the fast 1/16 period
        s2 = self._sched()
        s2._cycle_regime = "fit"
        for _ in range(4):
            s2._route_record("device", 60, 1.0)
            s2._route_record("cpu", 100, 1.0)
        s2._last_regime = "fit"
        routes = [s2._route_mode(heads) for _ in range(64)]
        assert routes.count("device") == 4

    def test_pure_eviction_cycle_credits_progress(self):
        """A cycle that only issues evictions must record nonzero
        progress (admissions + evictions): an all-zero rate pair would
        pin the router to its device tie-break in eviction-heavy
        regimes."""
        env2 = self._env()
        env2.add_flavor("default")
        env2.add_cq(ClusterQueueWrapper("cq")
                    .preemption(
                        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                    .resource_group(flavor_quotas("default", cpu=4)).obj(),
                    "lq")
        env2.admit_existing(WorkloadWrapper("victim").queue("lq").priority(0)
                            .pod_set(count=1, cpu="4").reserve("cq").obj())
        env2.submit(WorkloadWrapper("pre").queue("lq").priority(10)
                    .pod_set(count=1, cpu="4").obj())
        s = env2.scheduler
        s._route_stats = {("device", "fit"): [(1, 1.0), (1, 1.0)],
                          ("cpu", "fit"): [(9, 1.0), (9, 1.0)]}
        s._last_regime = "fit"  # router picks cpu; cycle observed preempt
        s.schedule(timeout=0)
        samples = s._route_stats.get(("cpu", "preempt"), [])
        assert samples and samples[0][0] == 1, samples  # 1 eviction credited
        assert env2.client.evicted  # the victim was evicted


class TestStarvationPredicateChurn:
    """ADVICE r5 medium: sustained HEALTHY preemption churn — entries
    that issue evictions every cycle (PENDING_PREEMPTION) — must not
    ratchet _blocked_preempt_streak to the strict-cycle bound. The sync
    path's blocked predicate excludes progressing preemptors, mirroring
    _collect_pipelined_preempt's reset-on-progress."""

    def test_eviction_churn_keeps_streak_at_zero(self):
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(),
                   "lq")
        sched = env.scheduler
        sched.strict_after_blocked_cycles = 4
        for i in range(10):
            victim = (WorkloadWrapper(f"victim{i}").queue("lq").priority(0)
                      .pod_set(count=1, cpu="10").reserve("cq").obj())
            env.admit_existing(victim)
            env.submit(WorkloadWrapper(f"preemptor{i}").queue("lq")
                       .priority(100).creation(float(i))
                       .pod_set(count=1, cpu="10").obj())
            env.cycle()  # issues the eviction: progress, not starvation
            assert f"default/victim{i}" in env.client.evicted, i
            assert sched._blocked_preempt_streak == 0, (
                i, sched._blocked_preempt_streak)
            # the eviction completes and the preemptor admits
            env.cache.delete_workload(victim)
            env.queues.queue_inadmissible_workloads({"cq"})
            env.cycle()
            admitted = env.client.applied.pop(f"default/preemptor{i}", None)
            assert admitted is not None, i
            env.cache.delete_workload(admitted)  # completes before round i+1
            assert sched._blocked_preempt_streak \
                < sched.strict_after_blocked_cycles, i
        # churn never engaged the strict-cycle bound
        assert "cpu-strict" not in sched.cycle_counts, sched.cycle_counts

    def test_overlap_skipped_preemptor_is_not_blocked(self):
        # two preemptors select the SAME victim: the first issues the
        # eviction, the second is _set_skipped with overlapping targets.
        # Both are progressing (the skip resolves by itself next cycle)
        # — neither may feed the starvation bound, mirroring the
        # pipelined collector where an overlap skip never sets
        # blocked_any.
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(),
                   "lq")
        env.admit_existing(WorkloadWrapper("victim").queue("lq").priority(0)
                           .pod_set(count=1, cpu="10").reserve("cq").obj())
        for name, ts in (("pre-a", 1.0), ("pre-b", 2.0)):
            env.submit(WorkloadWrapper(name).queue("lq").priority(100)
                       .creation(ts).pod_set(count=1, cpu="10").obj())
        env.cycle()
        assert "default/victim" in env.client.evicted
        assert env.scheduler._blocked_preempt_streak == 0

    def test_truly_blocked_preemptor_still_feeds_the_bound(self):
        # the fix must not weaken the bound: a preemptor with NO feasible
        # targets (all candidates at higher priority) stays blocked and
        # the streak still ratchets
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(),
                   "lq")
        env.admit_existing(WorkloadWrapper("occupant").queue("lq")
                           .priority(200).pod_set(count=1, cpu="10")
                           .reserve("cq").obj())
        env.submit(WorkloadWrapper("preemptor").queue("lq").priority(100)
                   .creation(1.0).pod_set(count=1, cpu="10").obj())
        sched = env.scheduler
        for i in range(3):
            env.cycle()
            env.queues.queue_inadmissible_workloads({"cq"})
            assert sched._blocked_preempt_streak == i + 1

    def test_stale_streak_decays_on_preempt_less_cycles(self):
        # ADVICE r5 follow-up: after the blocked preemptor VANISHES, the
        # accumulated evidence decays one cycle at a time once the
        # preempt-less stretch outlives the grace window (the bound) —
        # never a wholesale reset, and never within the grace, so a
        # parked preemptor that re-heaps on capacity releases keeps
        # accumulating while the evidence can't carry over to an
        # unrelated preemptor long after the original one vanished.
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(),
                   "lq")
        # a second CQ with free capacity keeps preempt-less cycles busy
        env.add_cq(ClusterQueueWrapper("side")
                   .resource_group(flavor_quotas("default", cpu="100"))
                   .obj(), "lq-side")
        env.admit_existing(WorkloadWrapper("occupant").queue("lq")
                           .priority(200).pod_set(count=1, cpu="10")
                           .reserve("cq").obj())
        pre = (WorkloadWrapper("preemptor").queue("lq").priority(100)
               .creation(1.0).pod_set(count=1, cpu="10").obj())
        env.submit(pre)
        sched = env.scheduler
        sched.strict_after_blocked_cycles = 4  # grace == 4 cycles
        for _ in range(3):  # ratchet the evidence (stays sub-bound)
            env.cycle()
            env.queues.queue_inadmissible_workloads({"cq"})
        assert sched._blocked_preempt_streak == 3
        env.queues.delete_workload(pre)  # the preemptor vanishes
        n = 0

        def fit_cycle():
            nonlocal n
            env.submit(WorkloadWrapper(f"fit{n}").queue("lq-side")
                       .creation(10.0 + n).pod_set(count=1, cpu="1").obj())
            env.cycle()
            n += 1

        for _ in range(4):  # within the grace: evidence intact
            fit_cycle()
            assert sched._blocked_preempt_streak == 3
        for want in (2, 1, 0):  # past the grace: decay, not reset
            fit_cycle()
            assert sched._blocked_preempt_streak == want
        assert sched._blocked_preempt_streak == 0

    def test_sparse_reattempts_still_reach_the_bound(self):
        # The decay grace must not defeat the bound: a preemptor that
        # re-attempts only every other cycle (capacity releases are
        # sparse) still accumulates, because arrival-only gaps shorter
        # than the grace leave the streak untouched.
        env = Env()
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu="10")).obj(),
                   "lq")
        env.add_cq(ClusterQueueWrapper("side")
                   .resource_group(flavor_quotas("default", cpu="100"))
                   .obj(), "lq-side")
        env.admit_existing(WorkloadWrapper("occupant").queue("lq")
                           .priority(200).pod_set(count=1, cpu="10")
                           .reserve("cq").obj())
        env.submit(WorkloadWrapper("preemptor").queue("lq").priority(100)
                   .creation(1.0).pod_set(count=1, cpu="10").obj())
        sched = env.scheduler
        sched.strict_after_blocked_cycles = 3
        n = 0
        for i in range(3):
            # a capacity-release event re-heaps the parked preemptor
            env.queues.queue_inadmissible_workloads({"cq"})
            env.cycle()  # blocked attempt (then parks inadmissible again)
            assert sched._blocked_preempt_streak == i + 1, i
            if i == 2:
                break  # bound reached; engaged-mode bleed takes over
            # two arrival-only cycles between attempts (< grace of 3):
            # sub-bound evidence must survive the gap untouched
            for _ in range(2):
                env.submit(WorkloadWrapper(f"fit{n}").queue("lq-side")
                           .creation(10.0 + n).pod_set(count=1, cpu="1")
                           .obj())
                env.cycle()
                n += 1
            assert sched._blocked_preempt_streak == i + 1, i
        assert sched._blocked_preempt_streak \
            >= sched.strict_after_blocked_cycles
