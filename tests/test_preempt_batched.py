"""Randomized equivalence + property suite for the batched (parallel
prefix / auction) preemption solve — ISSUE 9.

The batched device path (solver/preempt.py solve_preempt_impl +
solver/fairpreempt.py solve_fair_impl) must match the CPU oracle
(scheduler/preemption.py minimal_preemptions / fair_preemptions)
BIT-EXACTLY: same victim sets, same reasons, same admitted maps. That
is stronger than the documented equivalence class in solver/PREEMPT.md
(equal victim count + equal preempted quota + policy-order ties) — the
class exists to define what a future relaxation would have to preserve;
today's implementation does not use the slack, and this suite pins it.

Also here:
- DRF dominant-share decomposition property: the fair kernel's masked
  max-ratio reduction (candidates.share_view constants + the
  share_of_row formula) reproduces ClusterQueueSnapshot.
  dominant_resource_share for every CQ, across borrowing/cohort-depth
  shapes.
- fill-back auction stats surfaced on the scheduler
  (last_preempt_plan / router_status) and the preempt-plan trace
  annotation.
- dedup-table bucketing (encode_problems pads the candidate row table
  to a power-of-four bucket so preemption program shapes are warmable).
- CompileGovernor registers preemption/fair program variants in the
  warm ladder (warm_preempt_bucket wiring).
"""

import random

import numpy as np
import pytest

from kueue_tpu.api import kueue as api
from tests.test_preempt_solver import assert_preemption_differential
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas


class TestBatchedOracleEquivalenceFuzz:
    """Randomized scenarios tuned for the batched solve's hard parts:
    nested cohort trees with CQs attached at DIFFERENT depths (a shared
    ancestor node sits at different chain positions per CQ — the
    depth-ordered flow merge in _chain_flows_fwd), multi-resource
    requests, borrowWithinCohort thresholds, and high-variance victim
    sizes (fill-back heavy)."""

    @pytest.mark.parametrize("seed", range(8))
    def test_random_batched_differential(self, seed):
        rng = random.Random(7700 + seed)
        policies = [api.PREEMPTION_NEVER, api.PREEMPTION_LOWER_PRIORITY,
                    api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY]
        reclaims = [api.PREEMPTION_ANY, api.PREEMPTION_LOWER_PRIORITY,
                    api.PREEMPTION_NEVER]
        n_cqs = rng.randint(3, 6)
        deep = rng.random() < 0.6

        cq_specs = []
        for i in range(n_cqs):
            if deep:
                # mixed attachment depth: directly under the root, or
                # under one of two child cohorts
                cohort = rng.choice(["root", "left", "right"])
            else:
                cohort = rng.choice(["root", ""])
            bwc = None
            if cohort and rng.random() < 0.35:
                bwc = api.BorrowWithinCohort(
                    policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY,
                    max_priority_threshold=rng.choice([None, 2, 5]))
            cq_specs.append((f"cq{i}", cohort, rng.choice(["4", "8", "12"]),
                             rng.choice(policies), rng.choice(reclaims),
                             bwc))

        def setup(env):
            env.add_flavor("default")
            if deep:
                env.add_cohort("root")
                env.add_cohort("left", "root")
                env.add_cohort("right", "root")
            for name, cohort, nominal, wcq, rwc, bwc in cq_specs:
                w = ClusterQueueWrapper(name)
                if cohort:
                    w = w.cohort(cohort)
                w = w.preemption(within_cluster_queue=wcq,
                                 reclaim_within_cohort=rwc,
                                 borrow_within_cohort=bwc)
                env.add_cq(w.resource_group(
                    flavor_quotas("default", cpu=nominal,
                                  memory=f"{int(nominal) * 2}Gi")).obj(),
                    f"lq-{name}")

        existing_specs = []
        for i in range(rng.randint(2, 9)):
            cq = rng.randrange(n_cqs)
            # high-variance victim sizes: many smalls plus a big one so
            # the greedy over-removes and fill-back has work to do
            cpu = rng.choice(["1", "1", "2", "2", "3", "8", "10"])
            existing_specs.append(
                (f"old{i}", f"cq{cq}", rng.randint(0, 6), cpu, float(i)))

        pending_specs = []
        for i in range(rng.randint(1, 4)):
            cq = rng.randrange(n_cqs)
            pending_specs.append(
                (f"new{i}", f"lq-cq{cq}", rng.randint(2, 10),
                 rng.choice(["4", "7", "10"]), float(100 + i)))

        def existing():
            return [WorkloadWrapper(n).queue(f"lq-{cq}").priority(p)
                    .pod_set(count=1, cpu=c, memory=f"{c}Gi")
                    .reserve(cq, now=ts).obj()
                    for n, cq, p, c, ts in existing_specs]

        def workloads():
            return [WorkloadWrapper(n).queue(q).priority(p).creation(ts)
                    .pod_set(count=1, cpu=c, memory=f"{c}Gi").obj()
                    for n, q, p, c, ts in pending_specs]

        assert_preemption_differential(setup, existing, workloads, cycles=2)


class TestMultiDepthSharedNode:
    """A cohort node shared at DIFFERENT chain positions: cq-top hangs
    directly off the root (root at chain position 0), cq-deep off a
    child cohort (root at position 1). The prefix solver must merge
    their flows at the root in depth order, not chain-position order —
    a bug here over- or under-clamps the bubbled usage and diverges
    from the oracle."""

    def test_shared_root_different_positions(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cohort("root")
            env.add_cohort("child", "root")
            env.add_cq(ClusterQueueWrapper("top").cohort("root")
                       .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                       .resource_group(
                           flavor_quotas("default", cpu="10")).obj(),
                       "lq-top")
            env.add_cq(ClusterQueueWrapper("deep").cohort("child")
                       .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                       .resource_group(
                           flavor_quotas("default", cpu="6")).obj(),
                       "lq-deep")

        def existing():
            # deep borrows past its nominal 6 with several victims; the
            # removals must bubble through child AND root correctly
            return [WorkloadWrapper(f"v{i}").queue("lq-deep").priority(0)
                    .pod_set(count=1, cpu="3").reserve("deep",
                                                       now=float(i)).obj()
                    for i in range(4)]

        def workloads():
            return [WorkloadWrapper("claimant").queue("lq-top").priority(10)
                    .pod_set(count=1, cpu="10").obj()]

        cpu_env, _ = assert_preemption_differential(setup, existing,
                                                    workloads)
        assert cpu_env.client.evicted, "scenario must actually preempt"


class TestFillbackAuctionStats:
    """Fill-back heavy scenario: small victims ordered before a big one
    force the greedy to over-remove and the auction rounds to return
    the smalls. Exact oracle equality plus the operator surface: the
    kernel's stats land on scheduler.last_preempt_plan, /debug/router,
    and the preempt-plan trace annotation; the encode's dedup table is
    bucketed."""

    def _scenario(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .preemption(
                           within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                       .resource_group(
                           flavor_quotas("default", cpu="14")).obj(),
                       "lq")

        def existing():
            # order: prio asc -> three smalls first, then the big one
            out = [WorkloadWrapper(f"small{i}").queue("lq").priority(i)
                   .pod_set(count=1, cpu="2").reserve("cq",
                                                      now=float(i)).obj()
                   for i in range(3)]
            out.append(WorkloadWrapper("big").queue("lq").priority(5)
                       .pod_set(count=1, cpu="8").reserve("cq",
                                                          now=9.0).obj())
            return out

        def workloads():
            return [WorkloadWrapper("high").queue("lq").priority(10)
                    .pod_set(count=1, cpu="8").obj()]

        return setup, existing, workloads

    def test_fillback_and_stats_surface(self, monkeypatch):
        import kueue_tpu.solver.preempt as devpreempt
        captured = []
        orig = devpreempt.encode_problems

        def capture(*a, **k):
            b = orig(*a, **k)
            captured.append(b)
            return b

        monkeypatch.setattr(devpreempt, "encode_problems", capture)
        setup, existing, workloads = self._scenario()
        cpu_env, tpu_env = assert_preemption_differential(
            setup, existing, workloads)
        # the greedy removes smalls then big, and fill-back returns the
        # smalls — only the big is evicted
        assert set(cpu_env.client.evicted) == {"default/big"}

        plan = tpu_env.scheduler.last_preempt_plan
        assert plan and "minimal" in plan, plan
        st = plan["minimal"]
        assert st["pool"] >= 4
        assert st["filled_back"] >= 3, st
        assert st["fillback_rounds_max"] >= 1

        # /debug/router surfaces the same stats
        from kueue_tpu.obs import router_status
        rs = router_status(tpu_env.scheduler)
        assert rs["preempt_plan"] == plan

        # trace annotation on the cycle that planned preemptions
        annos = [a for tr in tpu_env.scheduler.recorder.traces()
                 for a in tr.annotations if a["kind"] == "preempt-plan"]
        assert annos and annos[-1]["minimal_filled_back"] >= 3

        # encode-side: the dedup row table is padded to a power-of-four
        # bucket (warmable program shapes — solver/COMPILE.md)
        assert captured, "device preemption encode did not run"
        u = captured[0].cand_usage.shape[0]
        assert u in {1, 4, 16, 64, 256, 1024}, u


class TestDRFShareDecomposition:
    """Property: the share decomposition the fair kernel consumes
    (DomainCandidates.share_view constants + the masked max-ratio
    row formula) reproduces ClusterQueueSnapshot.dominant_resource_share
    exactly, across borrowing shapes and cohort depths."""

    MAXSHARE = np.int64(2**62)

    def _device_share(self, domain, sv, slots, cq):
        qi = domain.cq_index[cq.name]
        u = np.asarray([cq.resource_node.usage.get(fr, 0) for fr in slots],
                       np.int64)
        nom = np.asarray([cq.quota_for(fr).nominal for fr in slots],
                         np.int64)
        borrow_fr = np.maximum(0, u - nom)
        resources = [fr.resource for fr in slots]
        borrow_res = np.asarray(
            [sum(b for b, r2 in zip(borrow_fr, resources) if r2 == r)
             for r in resources], np.int64) + sv["base_other"][qi]
        lend = sv["lendable"]
        ratio = np.where((borrow_res > 0) & (lend > 0),
                         borrow_res * 1000 // np.maximum(lend, 1),
                         np.int64(-1))
        drs = max(int(ratio.max(initial=-1)), int(sv["floor_ratio"][qi]))
        any_b = bool((borrow_res > 0).any()) or bool(sv["floor_any"][qi])
        w = int(sv["weight"][qi])
        if w == 0:
            return int(self.MAXSHARE)
        return drs * 1000 // w if any_b else 0

    @pytest.mark.parametrize("seed", range(6))
    def test_share_view_matches_snapshot(self, seed):
        rng = random.Random(4400 + seed)
        n_cqs = rng.randint(2, 5)
        depth = rng.choice([1, 2])

        def setup(env):
            env.add_flavor("default")
            if depth == 2:
                env.add_cohort("root")
                env.add_cohort("mid", "root")
            for i in range(n_cqs):
                cohort = "mid" if (depth == 2 and i % 2) else "root"
                env.add_cq(
                    ClusterQueueWrapper(f"cq{i}").cohort(cohort)
                    .preemption(reclaim_within_cohort=api.PREEMPTION_ANY)
                    .fair_weight(rng.choice([1000, 2000, 500]))
                    .resource_group(flavor_quotas(
                        "default", cpu=rng.choice(["2", "4", "6"]),
                        memory="8Gi")).obj(),
                    f"lq-cq{i}")

        env = build_env(setup, solver=False, fair_sharing=True)
        # borrow-heavy population: usage above nominal on several CQs
        for i in range(n_cqs):
            for v in range(rng.randint(0, 4)):
                env.admit_existing(
                    WorkloadWrapper(f"w{i}-{v}").queue(f"lq-cq{i}")
                    .pod_set(count=1, cpu=rng.choice(["1", "2", "3"]),
                             memory="1Gi")
                    .reserve(f"cq{i}", now=float(v)).obj())

        from kueue_tpu.core import workload as wlpkg
        from kueue_tpu.solver.candidates import CandidateIndex
        snapshot = env.cache.snapshot()
        idx = CandidateIndex(snapshot, wlpkg.Ordering(), 0.0)
        for name, cq in snapshot.cluster_queues.items():
            if cq.cohort is None:
                continue
            domain = idx.domain_for(cq)
            slots = tuple(sorted(domain.all_frs()))
            if not slots:
                continue
            sv = domain.share_view(slots)
            want, _ = cq.dominant_resource_share()
            got = self._device_share(domain, sv, slots, cq)
            assert got == want, (name, got, want)


class TestWarmPreemptLadder:
    """The governor's walk warms preemption/fair program variants on the
    largest bucket (warm_preempt_bucket wiring), and the shapes it
    enumerates are the bucketed dims encode_problems produces."""

    def test_shape_ladder_buckets(self):
        from kueue_tpu.solver.warmgov import preempt_shape_ladder
        shapes = preempt_shape_ladder({"a": 3, "b": 7}, 100)
        # two geometries x three descending B rungs (B buckets by the
        # cycle's PROBLEM count, not the batch width: full backlog,
        # width/4, width/16)
        assert len(shapes) == 6
        assert {ps["QL"] == 1 for ps in shapes} == {True, False}
        reclaim = [ps for ps in shapes if ps["QL"] > 1]
        assert len({ps["B"] for ps in shapes}) == 3
        assert max(ps["B"] for ps in shapes) >= 100
        assert min(ps["B"] for ps in shapes) < 100 // 4
        # every dim is a power-of-four bucket from its minimum
        for ps in shapes:
            for dim, v in ps.items():
                assert v >= 1 and (v in (1,) or v % 4 == 0 or v == 8), \
                    (dim, v)
        assert reclaim[0]["QL"] >= 7  # spans the widest cohort

    def test_shape_ladder_dedups_cohortless_geometries(self):
        """With no cohorts the reclaim geometry collapses onto the
        within-CQ one: one shape per B rung, not two."""
        from kueue_tpu.solver.warmgov import preempt_shape_ladder
        shapes = preempt_shape_ladder({"solo": 1}, 8)
        assert all(ps["QL"] == 1 for ps in shapes)
        assert len(shapes) == len({ps["B"] for ps in shapes})

    def test_governor_walk_warms_preempt(self, monkeypatch, tmp_path):
        from kueue_tpu.solver.warmgov import CompileGovernor

        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq").cohort("team")
                       .resource_group(
                           flavor_quotas("default", cpu="4")).obj(), "lq")

        env = build_env(setup, solver=True)
        solver = env.scheduler.solver
        calls = []
        monkeypatch.setattr(solver, "warm_router", lambda *a, **k: 0)
        monkeypatch.setattr(solver, "warm_bucket", lambda *a, **k: 0)
        monkeypatch.setattr(solver, "warm_scatter", lambda *a, **k: 0)
        monkeypatch.setattr(
            solver, "warm_preempt_bucket",
            lambda ctx, width, pshapes, **k: calls.append(
                (width, tuple(pshapes), k)) or 1)
        gov = CompileGovernor(solver, env.cache,
                              fair_sharing=True, fs_flags=(True, True, True))
        warmed = gov.run_sync()
        assert calls, "walk never warmed a preemption variant"
        assert warmed >= len(calls)
        for _w, shapes, kw in calls:
            assert kw.get("fair_sharing") is True
            assert kw.get("fs_flags") == (True, True, True)
            # one chunk = one B rung at one rank rung, so each call is
            # a bounded compile batch under its own supervised window
            assert len({ps["B"] for ps in shapes}) == 1
            assert len(kw.get("max_ranks", ())) == 1
        # across the chunks, every rank rung and the descending B
        # rungs are covered (dispatch prices max_rank from the batch's
        # conflict domains and B from the cycle's problem count, so
        # the top rungs alone would miss most cycles)
        all_ranks = {r for _w, _s, kw in calls
                     for r in kw.get("max_ranks", ())}
        all_b = {ps["B"] for _w, shapes, _k in calls for ps in shapes}
        assert len(all_ranks) >= 2
        assert len(all_b) >= 2
        # both flavor-resume twins warm (requeued heads after an
        # eviction dispatch the start_rank variant mid-storm)
        assert {kw.get("start_rank") for _w, _s, kw in calls} \
            == {False, True}

    def test_governor_warm_preempt_off(self, monkeypatch):
        from kueue_tpu.solver.warmgov import CompileGovernor

        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(
                           flavor_quotas("default", cpu="4")).obj(), "lq")

        env = build_env(setup, solver=True)
        solver = env.scheduler.solver
        calls = []
        monkeypatch.setattr(solver, "warm_router", lambda *a, **k: 0)
        monkeypatch.setattr(solver, "warm_bucket", lambda *a, **k: 0)
        monkeypatch.setattr(solver, "warm_scatter", lambda *a, **k: 0)
        monkeypatch.setattr(
            solver, "warm_preempt_bucket",
            lambda *a, **k: calls.append(a) or 1)
        gov = CompileGovernor(solver, env.cache, warm_preempt=False)
        gov.run_sync()
        assert not calls

    def test_warmed_preempt_dispatch_counts_no_mid_traffic_compiles(self):
        """End-to-end key agreement for the preemption path: a real
        governor warm followed by a real device preemption cycle. The
        dispatch key buckets B by the cycle's problem count and
        max_rank by the batch's conflict domains — warming only the
        width-derived B at the top rank rung (the pre-review ladder)
        missed every real preemption dispatch, so this pins the full
        rung coverage."""
        from kueue_tpu.solver.warmgov import GOV_WARM, CompileGovernor

        def setup(env):
            env.add_flavor("default")
            env.add_cq(
                ClusterQueueWrapper("cq")
                .preemption(
                    within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                .resource_group(flavor_quotas("default", cpu="10")).obj(),
                "lq")

        env = build_env(setup, solver=True)
        sv = env.scheduler.solver
        sv.bind_cache(env.cache)
        sv.bind_queues(env.scheduler.queues)
        gov = CompileGovernor(sv, env.cache)
        assert gov.run_sync() > 0
        assert gov.state == GOV_WARM
        env.scheduler.warm_gov = gov
        env.admit_existing(WorkloadWrapper("low").queue("lq").priority(1)
                           .pod_set(count=1, cpu="8").reserve("cq").obj())
        env.submit(WorkloadWrapper("high").queue("lq").priority(10)
                   .pod_set(count=1, cpu="8").obj())
        env.cycle()
        assert set(env.client.evicted) == {"default/low"}
        assert env.scheduler.cycle_counts.get("device") == 1
        assert env.scheduler.preemption_fallbacks == 0
        assert sv.counters["mid_traffic_compiles"] == 0

    def test_fair_sharing_warm_covers_every_dispatch_variant(
            self, monkeypatch):
        """Under fair sharing a cycle dispatches a MINIMAL-only batch
        (all-same-queue entries: fshapes=(), fs_strategies normalized
        to ()), a FAIR-only batch (pshapes=()), or a mixed pair of a
        within-CQ minimal batch with a cohort-wide fair batch
        (build_fair_problems). The warm must register all three key
        families — the homogeneous (minimal, fair) pairing over one
        geometry matches no production dispatch. Kernels are stubbed:
        this checks key structure, not compiles."""
        from kueue_tpu.solver import service

        def setup(env):
            env.add_flavor("default")
            for i in range(2):
                env.add_cq(
                    ClusterQueueWrapper(f"cq{i}").cohort("team")
                    .resource_group(
                        flavor_quotas("default", cpu="10")).obj(),
                    f"lq{i}")

        env = build_env(setup, solver=True, fair_sharing=True)
        sv = env.scheduler.solver
        sv.bind_cache(env.cache)
        sv.bind_queues(env.scheduler.queues)
        ctx = sv.warm_setup(env.cache.snapshot())

        class _Done:
            def block_until_ready(self):
                return self

        for fn in ("solve_cycle_with_preempt", "solve_cycle_resident",
                   "solve_cycle_resident_arena"):
            # both wire formats: the warm helper blocks on "dec_bits"
            # for compact-capable topologies, "admitted" otherwise
            monkeypatch.setattr(service, fn,
                                lambda *a, **k: {"admitted": _Done(),
                                                 "dec_bits": _Done()})
        keys = []
        monkeypatch.setattr(service, "note_program",
                            lambda key: keys.append(key) or True)

        from kueue_tpu.solver.warmgov import preempt_shape_ladder
        shapes = preempt_shape_ladder({"team": 2}, 8)
        flags = (True, True, False)
        sv.warm_preempt_bucket(ctx, 8, shapes, max_ranks=(8,),
                               fair_sharing=True, fs_flags=flags)
        sync = [k for k in keys if k[0] == "preempt"]
        # key layout: ("preempt", dims, W, P, max_rank, fair_sharing,
        #              sr, pshapes, fshapes, flags, compact, kdim)
        minimal_only = [k for k in sync if k[7] and not k[8]]
        fair_only = [k for k in sync if not k[7] and k[8]]
        mixed = [k for k in sync if k[7] and k[8]]
        assert minimal_only and fair_only and mixed
        for k in minimal_only:
            assert k[9] == (), "no fair batch => fs_strategies ()"
            assert k[7][0][1] == 1, "minimal problems are same-queue"
        for k in fair_only + mixed:
            assert k[9] == flags
        for k in mixed:
            # heterogeneous pairing: within-CQ minimal (QL bucket 1)
            # with a cohort-wide fair batch (QL bucket > 1)
            assert k[7][0][1] == 1 and k[8][0][1] > 1
        # resident/arena variants mirror the same families (key tail:
        # ..., pshapes, fshapes, flags, compact, kdim — kdim is the
        # ISSUE-13 cluster-column dims, None on every warmed variant)
        res = [k for k in keys if k[0] in ("resident", "arena")]
        assert all(k[-1] is None for k in res)
        assert any(k[-5] and not k[-4] for k in res)
        assert any(not k[-5] and k[-4] for k in res)
        assert any(k[-5] and k[-4] for k in res)


class TestTenantStormRouteCoverage:
    """PR-8 tenant-storm scenario with the production solver attached:
    the storm's preemption-heavy cycles are tagged on traces and the
    route mix is recorded; the device-route gate itself follows the
    cross-backend honesty policy (enforced on a device backend, refused
    with a recorded reason on CPU fallback)."""

    @pytest.mark.slow
    def test_storm_route_mix_recorded(self):
        import jax

        from kueue_tpu.sim.scenarios import run_tenant_storm
        res = run_tenant_storm(seed=0, scale="smoke", solver=True)
        assert res.ok, res.violations
        mix = res.counters["storm_route_mix"]
        assert mix, "no storm/drain cycles traced"
        assert res.counters["storm_preempt_cycles"] > 0, mix
        if jax.default_backend() == "cpu":
            assert "route_gate_refused" in res.counters
        else:
            assert res.counters["storm_preempt_device_cycles"] > 0
