"""Workload encode arena: arena-assembled batches must be bit-identical
to a from-scratch encode_workloads (the equivalence oracle), across
resource_version bumps, topology token bumps, slot reuse after delete,
>P-podset CPU-fallback rows, unknown-CQ rows and flavor-resume state.
Also pins the arena slot lifecycle (queue-manager delta feed, admission
release), the eligibility-cache half-eviction, and the scheduler-level
arena engagement.
"""

import random

import numpy as np
import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.cache import Cache
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.queue import Manager
from kueue_tpu.solver import encode
from kueue_tpu.solver.arena import WorkloadArena
from tests.wrappers import (
    ClusterQueueWrapper, WorkloadWrapper, flavor_quotas, make_flavor,
    make_local_queue)

BATCH_FIELDS = ("requests", "podset_active", "wl_cq", "priority",
                "timestamp", "eligible", "solvable", "start_rank")


def _assert_batches_equal(a, b, msg=""):
    assert a.n == b.n, msg
    for name in BATCH_FIELDS:
        va, vb = getattr(a, name), getattr(b, name)
        assert np.array_equal(va, vb), f"{msg}: batch.{name} diverged"


def _fresh_batch(entries, snapshot, topo, ordering, max_podsets):
    """The oracle: from-scratch encode with every per-Info cache and
    resume-state side effect isolated from the arena path."""
    resumes = [info.last_assignment for info in entries]
    for info in entries:
        info.__dict__.pop("_solver_enc", None)
    batch = encode.encode_workloads(entries, snapshot, topo,
                                    ordering=ordering,
                                    max_podsets=max_podsets)
    for info, la in zip(entries, resumes):
        # fill_start_ranks may null an outdated resume on the first pass;
        # restore so the arena pass sees the identical input state.
        if info.last_assignment is None and la is not None:
            info.last_assignment = la
    return batch


class ArenaEnv:
    """Cache + queue Manager + arena wired through the delta feed, the
    way the BatchSolver binds them in production."""

    def __init__(self, num_cqs=4, flavors=("f0", "f1"), max_podsets=2):
        self.clock = FakeClock(1000.0)
        self.cache = Cache()
        self.queues = Manager(clock=self.clock)
        self.ordering = wlpkg.Ordering()
        self.max_podsets = max_podsets
        self.arena = WorkloadArena(max_podsets)
        self.queues.add_workload_listener(self.arena.note)
        self.flavors = list(flavors)
        for f in self.flavors:
            # Tainted odd flavors: eligibility rows differ per toleration
            taints = None
            if int(f[1:]) % 2:
                from kueue_tpu.api.corev1 import Taint
                taints = [Taint(key="spot", value="true",
                                effect="NoSchedule")]
            self.cache.add_or_update_resource_flavor(
                make_flavor(f, taints=taints))
        self.num_cqs = 0
        for _ in range(num_cqs):
            self.add_cq()

    def add_cq(self):
        i = self.num_cqs
        self.num_cqs += 1
        cq = (ClusterQueueWrapper(f"cq{i}")
              .cohort(f"cohort-{i % 2}")
              .resource_group(*[flavor_quotas(f, cpu="10")
                                for f in self.flavors]).obj())
        self.cache.add_cluster_queue(cq)
        self.queues.add_cluster_queue(cq)
        self.queues.add_local_queue(make_local_queue(f"lq{i}", "default",
                                                     f"cq{i}"))

    def submit(self, wl):
        assert self.queues.add_or_update_workload(wl)

    def infos(self):
        out = {}
        for items in self.queues.local_queues.values():
            out.update(items.items)
        return out

    def topo(self):
        snapshot = self.cache.snapshot()
        return snapshot, encode.encode_topology(snapshot)

    def both_batches(self, entries, snapshot, topo):
        self.arena.begin_cycle(topo)
        arena_batch, slots = self.arena.assemble(
            entries, snapshot, topo, self.ordering, self.max_podsets)
        fresh = _fresh_batch(entries, snapshot, topo, self.ordering,
                             self.max_podsets)
        return arena_batch, fresh, slots


def _make_wl(env, name, rng):
    i = rng.randrange(env.num_cqs)
    w = (WorkloadWrapper(name).queue(f"lq{i}")
         .priority(rng.randrange(-2, 3))
         .creation(float(rng.randrange(10_000))))
    npods = rng.choice([1, 1, 1, 2, env.max_podsets + 1])  # sometimes >P
    for p in range(npods):
        w.pod_set(name=f"ps{p}", count=rng.randrange(1, 3),
                  cpu=str(rng.randrange(1, 5)))
        if rng.random() < 0.5:
            w.toleration("spot", "true")
    w.wl.metadata.resource_version = 1
    return w.obj()


class TestArenaEquivalence:
    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_arena_matches_fresh(self, seed):
        rng = random.Random(seed)
        env = ArenaEnv(num_cqs=4, max_podsets=2)
        live: dict = {}  # name -> Workload
        n = 0
        for cycle in range(10):
            # churn: arrivals, updates (rv bump + changed requests),
            # deletions (slot free + reuse), occasional topology bumps
            for _ in range(rng.randrange(1, 6)):
                name = f"w{n}"
                n += 1
                wl = _make_wl(env, name, rng)
                live[name] = wl
                env.submit(wl)
            for name in rng.sample(sorted(live), min(2, len(live))):
                if rng.random() < 0.5:
                    wl = _make_wl(env, name, rng)
                    wl.metadata.resource_version = \
                        live[name].metadata.resource_version + 1
                    live[name] = wl
                    env.submit(wl)
                else:
                    env.queues.delete_workload(live.pop(name))
            if cycle in (4, 7):
                env.add_cq()  # topology epoch bump -> new topo token
            snapshot, topo = env.topo()
            infos = env.infos()
            if not infos:
                continue
            entries = [infos[k] for k in rng.sample(sorted(infos),
                                                    rng.randrange(
                                                        1, len(infos) + 1))]
            # flavor-resume state on a few entries (start_rank input)
            for info in rng.sample(entries, min(2, len(entries))):
                info.last_assignment = wlpkg.AssignmentClusterQueueState(
                    last_tried_flavor_idx=[{"cpu": rng.choice([-1, 0, 1])}],
                    cluster_queue_generation=10**9,  # never outdated
                    cohort_generation=10**9)
            arena_batch, fresh, _ = env.both_batches(entries, snapshot, topo)
            _assert_batches_equal(arena_batch, fresh,
                                  f"seed={seed} cycle={cycle}")

    def test_unknown_cq_row_matches_oracle(self):
        env = ArenaEnv(num_cqs=2)
        wl = (WorkloadWrapper("w0").queue("lq0").pod_set(cpu="1").obj())
        info = wlpkg.Info(wl)
        info.cluster_queue = "no-such-cq"
        snapshot, topo = env.topo()
        arena_batch, fresh, _ = env.both_batches([info], snapshot, topo)
        _assert_batches_equal(arena_batch, fresh)
        assert not arena_batch.solvable[0]

    def test_resource_version_bump_reencodes(self):
        # The (token, resourceVersion) key is enforced via object
        # identity: a bump always rides a fresh Workload object (store
        # clone semantics), and the queue manager wraps it in a fresh
        # Info + fires the upsert feed — the row must re-encode.
        env = ArenaEnv(num_cqs=1)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="2").obj()
        wl.metadata.resource_version = 1
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        before = env.arena.encoded_rows
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        assert env.arena.encoded_rows == before  # unchanged row: no work
        wl2 = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="5").obj()
        wl2.metadata.resource_version = 2
        env.submit(wl2)
        info2 = env.infos()["default/w0"]
        batch, _ = env.arena.assemble([info2], snapshot, topo,
                                      env.ordering, 2)
        assert env.arena.encoded_rows == before + 1
        assert batch.requests[0].max() == 5000

    def test_manager_upsert_reencodes_in_place_rebuild(self):
        # A requests rebuild that keeps the same Info AND obj must be
        # re-pushed through the Manager (the reclaimable-pods controller
        # path does): the upsert feed invalidates the row.
        env = ArenaEnv(num_cqs=1)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="2").obj()
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        before = env.arena.encoded_rows
        info.total_requests[0].requests["cpu"] = 7000
        env.submit(wl)  # manager re-push -> upsert feed
        info2 = env.infos()["default/w0"]
        batch, _ = env.arena.assemble([info2], snapshot, topo,
                                      env.ordering, 2)
        assert env.arena.encoded_rows == before + 1
        fresh = _fresh_batch([info2], snapshot, topo, env.ordering, 2)
        _assert_batches_equal(batch, fresh)

    def test_delete_frees_slot_and_reuse(self):
        env = ArenaEnv(num_cqs=1)
        w0 = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="1").obj()
        env.submit(w0)
        snapshot, topo = env.topo()
        info0 = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        _, slots0 = env.arena.assemble([info0], snapshot, topo,
                                       env.ordering, 2)
        env.queues.delete_workload(w0)
        w1 = WorkloadWrapper("w1").queue("lq0").pod_set(cpu="3").obj()
        env.submit(w1)
        info1 = env.infos()["default/w1"]
        batch, slots1 = env.arena.assemble([info1], snapshot, topo,
                                           env.ordering, 2)
        assert slots1[0] == slots0[0]  # recycled slot
        assert "default/w0" not in env.arena.slot_of
        fresh = _fresh_batch([info1], snapshot, topo, env.ordering, 2)
        _assert_batches_equal(batch, fresh)

    def test_admission_release_recycles_slot(self):
        env = ArenaEnv(num_cqs=1)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="1").obj()
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        assert "default/w0" in env.arena.slot_of
        env.arena.release("default/w0")
        env.arena._drain()
        assert "default/w0" not in env.arena.slot_of
        assert env.arena.free

    def test_topology_token_bump_invalidates_all_rows(self):
        env = ArenaEnv(num_cqs=2)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="1").obj()
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        before = env.arena.encoded_rows
        env.add_cq()  # epoch bump
        snapshot2, topo2 = env.topo()
        assert topo2.token != topo.token
        arena_batch, fresh, _ = env.both_batches([info], snapshot2, topo2)
        assert env.arena.encoded_rows == before + 1  # re-encoded once
        _assert_batches_equal(arena_batch, fresh)


class TestBatchedChurnEncode:
    """ISSUE 6 satellite: churn batches >= _BATCH_ENCODE_MIN take the
    vectorized multi-row encode (one fancy-indexed write per arena
    field) instead of paying ~15us/row of small-numpy dispatch; small
    batches keep the per-row path. Both must stay bit-identical to the
    from-scratch oracle."""

    def _spy(self, env):
        calls = {"batch": 0, "row": 0}
        orig_rows, orig_row = env.arena._encode_rows, env.arena._encode_row

        def spy_rows(*a, **k):
            calls["batch"] += 1
            return orig_rows(*a, **k)

        def spy_row(*a, **k):
            calls["row"] += 1
            return orig_row(*a, **k)

        env.arena._encode_rows = spy_rows
        env.arena._encode_row = spy_row
        return calls

    def test_large_churn_is_vectorized_and_bit_identical(self):
        rng = random.Random(5)
        env = ArenaEnv(num_cqs=4, max_podsets=2)
        for i in range(40):
            env.submit(_make_wl(env, f"w{i}", rng))
        snapshot, topo = env.topo()
        infos = env.infos()
        entries = [infos[k] for k in sorted(infos)]
        calls = self._spy(env)
        arena_batch, fresh, slots = env.both_batches(entries, snapshot,
                                                     topo)
        _assert_batches_equal(arena_batch, fresh, "vectorized first sight")
        assert calls["batch"] == 1 and calls["row"] == 0

    def test_small_churn_keeps_per_row_path(self):
        from kueue_tpu.solver.arena import _BATCH_ENCODE_MIN
        rng = random.Random(6)
        env = ArenaEnv(num_cqs=4, max_podsets=2)
        live = {}
        for i in range(20):
            wl = _make_wl(env, f"w{i}", rng)
            live[f"w{i}"] = wl
            env.submit(wl)
        snapshot, topo = env.topo()
        infos = env.infos()
        entries = [infos[k] for k in sorted(infos)]
        env.both_batches(entries, snapshot, topo)  # steady state
        churn = _BATCH_ENCODE_MIN - 1
        for name in sorted(live)[:churn]:
            wl = _make_wl(env, name, rng)
            wl.metadata.resource_version = \
                live[name].metadata.resource_version + 1
            env.submit(wl)
        infos = env.infos()
        entries = [infos[k] for k in sorted(infos)]
        calls = self._spy(env)
        arena_batch, fresh, _ = env.both_batches(entries, snapshot, topo)
        _assert_batches_equal(arena_batch, fresh, "per-row churn")
        assert calls["batch"] == 0 and calls["row"] == churn

    def test_failed_encode_leaves_slot_retryable(self):
        # An encode that raises (the scheduler's _prepare_failed sync
        # fallback is an anticipated path) must NOT mark the slot as
        # freshly encoded — the next cycle retries instead of riding a
        # cleared row for the workload's whole pending lifetime.
        rng = random.Random(7)
        env = ArenaEnv(num_cqs=2, max_podsets=2)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="2").obj()
        wl.metadata.resource_version = 1
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        orig = env.arena._encode_row

        def boom(*a, **k):
            raise RuntimeError("encode blew up")

        env.arena._encode_row = boom
        with pytest.raises(RuntimeError):
            env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        env.arena._encode_row = orig
        env.arena._last_ids = None  # the failed cycle never completed
        batch, _ = env.arena.assemble([info], snapshot, topo,
                                      env.ordering, 2)
        fresh = _fresh_batch([info], snapshot, topo, env.ordering, 2)
        _assert_batches_equal(batch, fresh, "post-failure retry")
        assert batch.solvable[0]

    def test_slot_generations_track_encodes_and_deltas(self):
        env = ArenaEnv(num_cqs=2)
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="2").obj()
        wl.metadata.resource_version = 1
        env.submit(wl)
        snapshot, topo = env.topo()
        info = env.infos()["default/w0"]
        env.arena.begin_cycle(topo)
        _, slots = env.arena.assemble([info], snapshot, topo,
                                      env.ordering, 2)
        g0 = env.arena.slot_generations(slots)
        # a requeue of the unchanged Info moves nothing
        env.arena.assemble([info], snapshot, topo, env.ordering, 2)
        assert np.array_equal(env.arena.slot_generations(slots), g0)
        # an upsert delta bumps the generation BEFORE the re-encode
        wl2 = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="5").obj()
        wl2.metadata.resource_version = 2
        env.submit(wl2)
        g1 = env.arena.slot_generations(slots)
        assert g1[0] > g0[0]
        # ...and the re-encode bumps it again
        info2 = env.infos()["default/w0"]
        env.arena.assemble([info2], snapshot, topo, env.ordering, 2)
        assert env.arena.slot_generations(slots)[0] > g1[0]


class TestEligibilityCacheEviction:
    def test_evicts_oldest_half_not_all(self):
        cache = {i: i for i in range(10)}
        encode._evict_oldest_half(cache)
        assert sorted(cache) == [5, 6, 7, 8, 9]
        cache[3] = 3  # re-primed row lands at the tail, surviving eviction
        encode._evict_oldest_half(cache)
        assert list(cache) == [8, 9, 3]

    def test_hit_refreshes_recency(self):
        # eligibility_row moves entries to the tail on every hit, so the
        # oldest-half eviction drops the LEAST-RECENTLY-USED half — a
        # permanently-hot shared row survives cap trips.
        env = ArenaEnv(num_cqs=1, flavors=("f0",))
        wl = WorkloadWrapper("w0").queue("lq0").pod_set(cpu="1").obj()
        env.submit(wl)
        w2 = WorkloadWrapper("w1").queue("lq0").pod_set(cpu="1")
        w2.node_selector("zone", "a")  # distinct eligibility signature
        env.submit(w2.obj())
        snapshot, topo = env.topo()
        infos = env.infos()
        cq = snapshot.cluster_queues["cq0"]
        qi = topo.cq_index["cq0"]
        encode.eligibility_row(infos["default/w0"], 0, qi, cq, snapshot,
                               topo)
        encode.eligibility_row(infos["default/w1"], 0, qi, cq, snapshot,
                               topo)
        first = next(iter(topo.elig_cache))
        # hit the older entry: it must move behind the newer one
        encode.eligibility_row(infos["default/w0"], 0, qi, cq, snapshot,
                               topo)
        assert len(topo.elig_cache) == 2
        assert list(topo.elig_cache)[-1] == first


class TestSchedulerArenaIntegration:
    def test_scheduler_cycles_engage_arena_and_match_cpu(self):
        from kueue_tpu.solver import BatchSolver
        from tests.test_scheduler import Env

        def build(solver):
            env = Env()
            if solver:
                env.scheduler.solver = BatchSolver()
                env.scheduler.solver_min_heads = 0
            env.add_flavor("default")
            for i in range(4):
                env.add_cq(ClusterQueueWrapper(f"cq{i}").cohort("co")
                           .resource_group(
                               flavor_quotas("default", cpu="4")).obj(),
                           f"lq{i}")
            return env

        admitted = {}
        for solver in (False, True):
            env = build(solver)
            n = 0
            for wave in range(3):
                for i in range(4):
                    env.submit(WorkloadWrapper(f"w{wave}-{i}")
                               .queue(f"lq{i}").priority(n % 3)
                               .creation(float(n)).pod_set(cpu="2").obj())
                    n += 1
                env.cycle()
            env.cycle()
            admitted[solver] = sorted(env.client.applied)
            if solver:
                arena = env.scheduler.solver._arena
                assert arena.gathers > 0
                # steady-state cycles re-encode only changed rows: after
                # the first sight of each workload, requeued heads ride
                # their cached slots
                assert arena.encoded_rows <= n
        assert admitted[False] == admitted[True]
