"""Hot-standby replicated control plane suite (ISSUE 15 acceptance).

Layers under test, bottom up:

1. the DurableLog's tail-streaming surface (sim/durable.py) —
   generation-stamped segment rotation (a checkpoint used to reopen
   the WAL ``"wb"``, which a naive byte-offset tailer read as silent
   truncation), cursors streaming ACROSS rotations, the
   beyond-retention resync fallback, and torn-tail-mid-stream parking,
   on BOTH the memory and file backings;
2. the leader lease with fencing epochs — acquisition/renew/expiry,
   epoch bumps on every holder change, and the ``Fenced`` backstop at
   the Store's commit path and at the log's own append;
3. the ``StandbyReplica`` — warm bootstrap, incremental tail replay
   converging bit-for-bit with the leader (admitted sets + usage),
   lag bookkeeping, the aging-watch lag monitor;
4. sub-cycle promotion — drain + fence + first-cycle-sync posture,
   exactly-once admission across the leadership change, the
   deposed-leader speculative-commit regression (the ISSUE 15
   acceptance bullet), and the operator surface (/debug/recovery
   standby + promotion sections, gauges, system events);
5. the incremental cold-restore satellite — restore() routed through
   the follower's apply path is equivalent to the PR-10 collapsed
   replay.
"""

import importlib.util
import os

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.resilience import faultinject, recovery
from kueue_tpu.resilience.faultinject import (CRASH, FaultInjector,
                                              InjectedCrash)
from kueue_tpu.resilience.replica import (FencingToken, StandbyReplica,
                                          lead)
from kueue_tpu.sim.durable import DurableLog, Fenced


@pytest.fixture(autouse=True)
def _no_injector_leak():
    yield
    faultinject.uninstall()


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------

def make_flavor(name="f0"):
    return api.ResourceFlavor(metadata=ObjectMeta(name=name,
                                                  uid=f"rf-{name}"))


def make_cq(name, cohort="co", quota=100_000):
    cq = api.ClusterQueue(metadata=ObjectMeta(name=name, uid=f"cq-{name}"))
    cq.spec.namespace_selector = LabelSelector()
    cq.spec.cohort = cohort
    cq.spec.resource_groups.append(api.ResourceGroup(
        covered_resources=["cpu"],
        flavors=[api.FlavorQuotas(name="f0", resources=[
            api.ResourceQuota(name="cpu", nominal_quota=quota)])]))
    return cq


def make_lq(name, cq):
    lq = api.LocalQueue(metadata=ObjectMeta(name=name,
                                            namespace="default",
                                            uid=f"lq-{name}"))
    lq.spec.cluster_queue = cq
    return lq


def make_workload(name, lq, cpu=2000, creation=0.0):
    wl = api.Workload(metadata=ObjectMeta(
        name=name, namespace="default", uid=f"wl-{name}",
        creation_timestamp=creation))
    wl.spec.queue_name = lq
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": cpu})]))))
    return wl


def _mk_leader(clock, checkpoint_every=0, num_cqs=2):
    cfg = cfgpkg.Configuration()
    cfg.store.durable = True
    cfg.store.checkpoint_every = checkpoint_every
    mgr = KueueManager(cfg=cfg, clock=clock)
    mgr.store.create(make_flavor())
    for i in range(num_cqs):
        mgr.store.create(make_cq(f"cq{i}"))
        mgr.store.create(make_lq(f"lq{i}", f"cq{i}"))
    mgr.run_until_idle()
    return mgr


def _submit(mgr, waves, num_cqs=2, start=0):
    n = start * num_cqs
    for w in range(start, start + waves):
        for i in range(num_cqs):
            mgr.store.create(make_workload(f"w{w}-{i}", f"lq{i}",
                                           creation=float(n)))
            n += 1
    mgr.run_until_idle()


def _drive(mgr, clock, cycles=4, standby=None):
    for _ in range(cycles):
        if standby is not None:
            standby.poll()
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle()
        clock.advance(1.0)


def admitted_keys(mgr):
    return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                  if wlpkg.has_quota_reservation(wl))


def _load_crash_run():
    spec = importlib.util.spec_from_file_location(
        "crash_run", os.path.join(os.path.dirname(__file__),
                                  "..", "tools", "crash_run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fill(log, n, start=0, t=0.0):
    for i in range(start, start + n):
        log.append("ADDED", "Kind", f"k{i}",
                   make_flavor(f"obj{i}"), t=t + i)


def _keys(records):
    return [key for _e, _k, key, _o, _t in records]


# ----------------------------------------------------------------------
# 1. segment rotation + tail cursors (mem AND file)
# ----------------------------------------------------------------------

@pytest.fixture(params=["memory", "file"])
def log_factory(request, tmp_path):
    def make(**kw):
        if request.param == "memory":
            return DurableLog(**kw)
        return DurableLog(dir=str(tmp_path / "wal"), **kw)
    make.backing = request.param
    return make


class TestTailStreaming:
    def test_cursor_reads_only_new_records(self, log_factory):
        log = log_factory()
        _fill(log, 3)
        cur = log.cursor()
        _fill(log, 2, start=3)
        batch = log.read_tail(cur)
        assert not batch.resync
        assert _keys(batch.records) == ["k3", "k4"]
        # drained: the advanced cursor reads nothing further
        again = log.read_tail(batch.cursor)
        assert again.records == [] and not again.resync
        assert log.records_ahead(batch.cursor) == 0

    def test_bootstrap_cursor_is_atomic_with_load(self, log_factory):
        log = log_factory()
        _fill(log, 4)
        parts, cur = log.load_with_cursor()
        assert len(parts.records) == 4
        _fill(log, 1, start=4)
        batch = log.read_tail(cur)
        # exactly once: nothing duplicated, nothing missed
        assert _keys(batch.records) == ["k4"]

    def test_cursor_streams_across_rotation(self, log_factory):
        """The satellite fix: checkpoint() rotates the segment instead
        of truncating in place, so a cursor parked BEFORE the rotation
        still reads every record — first the retired segment's
        remainder, then the fresh one."""
        log = log_factory()
        _fill(log, 3)
        cur = log.cursor()          # generation 0, mid-segment
        _fill(log, 2, start=3)
        log.checkpoint({"Kind": {}}, rv=5)   # rotation -> generation 1
        _fill(log, 2, start=5)
        assert log.generation == 1
        assert log.records_ahead(cur) == 4
        batch = log.read_tail(cur)
        assert not batch.resync
        assert batch.segments_crossed == 1
        assert _keys(batch.records) == ["k3", "k4", "k5", "k6"]
        assert batch.cursor.generation == 1

    def test_cursor_streams_across_many_rotations(self, log_factory):
        log = log_factory(retain_segments=8)
        cur = log.cursor()
        for r in range(3):
            _fill(log, 2, start=2 * r)
            log.checkpoint({"Kind": {}}, rv=r)
        batch = log.read_tail(cur)
        assert not batch.resync and batch.segments_crossed == 3
        assert len(batch.records) == 6

    def test_beyond_retention_resyncs(self, log_factory):
        log = log_factory(retain_segments=1)
        cur = log.cursor()          # generation 0
        for r in range(3):          # retires 0,1,2; keeps only 2
            _fill(log, 2, start=2 * r)
            log.checkpoint({"Kind": {}}, rv=r)
        batch = log.read_tail(cur)
        assert batch.resync and batch.records == []
        assert log.records_ahead(cur) is None
        # the resync protocol: re-bootstrap, then tail cleanly
        parts, cur2 = log.load_with_cursor()
        _fill(log, 1, start=99)
        assert _keys(log.read_tail(cur2).records) == ["k99"]

    def test_torn_tail_mid_stream_parks_then_resumes(self, log_factory):
        """A reader that catches an append mid-flight (or a crash's
        torn tail) sees only complete records and its cursor PARKS at
        the boundary; when the bytes complete the stream resumes with
        no loss or duplication."""
        log = log_factory()
        _fill(log, 2)
        cur = log.cursor()
        _fill(log, 2, start=2)
        log.truncate_tail(5)        # k3's record loses its tail bytes
        batch = log.read_tail(cur)
        assert not batch.resync
        assert _keys(batch.records) == ["k2"]       # complete one only
        parked = batch.cursor
        assert log.read_tail(parked).records == []   # still parked
        # the "append completes later" half: the leader (here: a fresh
        # append after the torn bytes are truncated away by the next
        # writer) — simulate by chopping the partial record entirely
        # and appending a new one
        sz = log.wal_size()
        log.truncate_tail(sz - parked.offset)
        _fill(log, 1, start=9)
        assert _keys(log.read_tail(parked).records) == ["k9"]

    def test_load_tolerates_torn_tail(self, log_factory):
        log = log_factory()
        _fill(log, 3)
        log.truncate_tail(3)
        parts = log.load_parts()
        assert parts.torn_records == 1
        assert _keys(parts.records) == ["k0", "k1"]
        res = log.load()
        assert res.torn_records == 1 and res.records_replayed == 2

    def test_record_timestamps_drive_lag_seconds(self, log_factory):
        log = log_factory()
        _fill(log, 2, t=100.0)
        assert log.last_append_t == 101.0
        parts = log.load_parts()
        assert [t for *_rest, t in parts.records] == [100.0, 101.0]

    def test_memory_clone_is_independent(self):
        log = DurableLog(checkpoint_every=0)
        _fill(log, 2)
        log.checkpoint({"Kind": {}}, rv=2)
        _fill(log, 1, start=2)
        twin = log.clone()
        _fill(log, 5, start=10)
        assert twin.appends == 3 and twin.generation == 1
        assert len(twin.load_parts().records) == 1

    def test_file_clone_rejected(self, tmp_path):
        log = DurableLog(dir=str(tmp_path / "w"))
        with pytest.raises(ValueError):
            log.clone()


# ----------------------------------------------------------------------
# 2. leader lease + fencing epochs
# ----------------------------------------------------------------------

class TestLeaseFencing:
    def test_epoch_bumps_on_every_holder_change(self):
        log = DurableLog()
        assert log.acquire_lease("a", now=0.0, duration=10.0) == 1
        # renewal by the holder keeps the epoch
        assert log.acquire_lease("a", now=5.0, duration=10.0) == 1
        # a live lease blocks others...
        assert log.acquire_lease("b", now=9.0) is None
        # ...until expiry; takeover bumps
        assert log.acquire_lease("b", now=20.0, duration=10.0) == 2
        # a returning holder past expiry bumps too
        assert log.acquire_lease("a", now=40.0, duration=10.0) == 3
        assert log.fencing_epoch == 3

    def test_force_acquire_fences_live_holder(self):
        log = DurableLog()
        log.acquire_lease("a", now=0.0, duration=100.0)
        tok_a = FencingToken(log, "a", 1)
        assert tok_a.valid()
        assert log.acquire_lease("b", now=1.0, force=True) == 2
        assert not tok_a.valid()
        with pytest.raises(Fenced):
            tok_a.check()
        with pytest.raises(Fenced):
            log.append("ADDED", "K", "k", make_flavor(), fence=("a", 1))
        # the new holder appends fine
        log.append("ADDED", "K", "k", make_flavor(), fence=("b", 2))

    def test_no_lease_regime_means_no_fencing(self):
        log = DurableLog()
        log.check_epoch("anyone", 0)  # no lease ever taken: no-op
        log.append("ADDED", "K", "k", make_flavor(), fence=("x", 0))

    def test_release_hands_off_without_bump(self):
        log = DurableLog()
        log.acquire_lease("a", now=0.0, duration=100.0)
        log.release_lease("a")
        st = log.lease_status(now=1.0)
        assert st["holder"] == "" and st["expired"]
        assert log.acquire_lease("b", now=1.0) == 2

    def test_renew_fails_for_deposed_holder(self):
        log = DurableLog()
        log.acquire_lease("a", now=0.0)
        log.acquire_lease("b", now=1.0, force=True)
        assert not log.renew_lease("a", now=2.0)
        assert log.renew_lease("b", now=2.0)

    def test_deposed_checkpoint_cannot_clobber_the_log(self):
        """Review regression: checkpoint() is fenced too — a deposed
        leader's graceful shutdown used to replace the checkpoint with
        its STALE image and rotate away the new leader's live WAL
        tail, silently losing every admission committed since the
        takeover."""
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="a", duration=1000.0)
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="b")
        _submit(mgr, 1)
        promoted = standby.promote(force=True)
        # the NEW leader admits and journals
        _drive(promoted, clock, cycles=2)
        admitted = admitted_keys(promoted)
        assert admitted
        # deposed direct checkpoint: fenced
        with pytest.raises(Fenced):
            mgr.store.checkpoint_now()
        # deposed graceful shutdown: survives, but writes nothing
        mgr.shutdown()
        loaded = mgr.durable.load()
        survived = sorted(
            wlpkg.key(wl)
            for wl in loaded.objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        assert survived == admitted

    def test_fence_rejects_before_local_mutation(self):
        """Review regression: the fence is checked BEFORE the local
        bucket mutates, so a deposed-but-alive leader that survives
        Fenced holds no phantom objects — a retried create raises
        Fenced again, never AlreadyExists."""
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="a")
        mgr.durable.acquire_lease("b", now=clock.now(), force=True)
        for _ in range(2):
            with pytest.raises(Fenced):
                mgr.store.create(make_workload("phantom", "lq0"))
        assert mgr.store.try_get("Workload", "default",
                                 "phantom") is None
        rv_before = mgr.store._rv
        with pytest.raises(Fenced):
            mgr.store.delete("LocalQueue", "default", "lq0")
        assert mgr.store.try_get("LocalQueue", "default",
                                 "lq0") is not None
        assert mgr.store._rv == rv_before

    def test_store_commit_path_is_fenced(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        token = lead(mgr, mgr.durable, identity="a")
        mgr.store.create(make_workload("ok", "lq0"))
        mgr.durable.acquire_lease("b", now=clock.now(), force=True)
        with pytest.raises(Fenced):
            mgr.store.create(make_workload("fenced", "lq0"))
        # the fenced write never reached the WAL
        assert "default/fenced" not in {
            key for _e, _k, key, _o, _t in mgr.durable.load_parts().records}
        assert not token.valid()


# ----------------------------------------------------------------------
# 3. the standby replica
# ----------------------------------------------------------------------

class TestStandbyReplica:
    def test_follower_converges_with_leader(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock, checkpoint_every=16)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="standby-0")
        assert standby.status()["role"] == "standby"
        _submit(mgr, 3)
        assert standby.lag_records > 0
        _drive(mgr, clock, cycles=4, standby=standby)
        standby.poll()
        assert standby.lag_records == 0
        assert standby.lag_seconds == 0.0
        assert admitted_keys(standby.mgr) == admitted_keys(mgr)
        crash_run = _load_crash_run()
        ok, msg = crash_run.usage_consistent(standby.mgr)
        assert ok, msg

    def test_follower_never_schedules(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        _submit(mgr, 2)
        standby.poll()
        # un-promoted follower's scheduler is leader-gated shut
        standby.mgr.scheduler.schedule(timeout=0)
        assert admitted_keys(standby.mgr) == []

    def test_follower_streams_across_compaction(self):
        """checkpoint_every small enough that rotations happen mid-
        traffic: the follower must stream across them (zero resyncs)
        — the regression the generation-stamped rotation exists for."""
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock, checkpoint_every=8)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        for w in range(4):
            _submit(mgr, 1, start=w)
            _drive(mgr, clock, cycles=1, standby=standby)
        standby.poll()
        assert mgr.durable.checkpoints > 0
        assert standby.resyncs == 0
        assert admitted_keys(standby.mgr) == admitted_keys(mgr)

    def test_follower_resync_past_retention_recovers(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock, checkpoint_every=0)
        mgr.durable.retain_segments = 0   # every rotation discards
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        _submit(mgr, 2)
        mgr.store.checkpoint_now()        # cursor now unreachable
        _submit(mgr, 1, start=2)
        _drive(mgr, clock, cycles=3)
        standby.poll()
        assert standby.resyncs == 1
        standby.poll()
        assert admitted_keys(standby.mgr) == admitted_keys(mgr)

    def test_lag_monitor_rides_the_aging_watch(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        mon = standby.mgr.aging_watch.monitors["replication_lag_records"]
        _submit(mgr, 2)
        standby.poll()
        assert mon.samples >= 1
        # caught-up follower: flat at zero, verdict never a leak
        for _ in range(30):
            standby.poll()
        assert mon.verdict() in ("ok", "warming")
        st = standby.mgr.metrics.replication_lag_records.value()
        assert st == 0

    def test_standby_status_on_debug_recovery(self):
        from kueue_tpu.obs import DebugEndpoints
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="standby-0")
        payload = DebugEndpoints(standby.mgr.scheduler,
                                 standby.mgr.metrics).handle(
            "/debug/recovery", {})
        assert payload["standby"]["role"] == "standby"
        assert payload["standby"]["identity"] == "standby-0"
        assert "promotion" not in payload
        import json
        json.dumps(payload)  # wire-serializable


# ----------------------------------------------------------------------
# 4. promotion
# ----------------------------------------------------------------------

class TestPromotion:
    def _kill_leader(self, mgr, clock, hit=9):
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {hit: CRASH}}))
        with pytest.raises(InjectedCrash):
            _drive(mgr, clock, cycles=8)
        faultinject.uninstall()

    def test_promotion_after_crash_exactly_once(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock, checkpoint_every=32)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="standby-0")
        _submit(mgr, 3)
        standby.poll()
        self._kill_leader(mgr, clock)
        durable = mgr.durable
        pre = sorted(
            wlpkg.key(wl)
            for wl in durable.load().objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        promoted = standby.promote(force=True)
        assert promoted is standby.mgr
        # first post-promotion cycle is pinned synchronous
        assert promoted.scheduler._pipeline_cooldown >= 1
        _drive(promoted, clock, cycles=6)
        final = admitted_keys(promoted)
        # never lose a durable admission; converge; exactly-once
        assert set(pre) <= set(final)
        assert final == sorted(f"default/w{w}-{i}" for w in range(3)
                               for i in range(2))
        crash_run = _load_crash_run()
        ok, msg = crash_run.usage_consistent(promoted)
        assert ok, msg
        # the promoted store journals: a new admission reaches the WAL
        assert promoted.durable is durable
        assert durable.lease_status()["holder"] == "standby-0"

    def test_promotion_drains_unpolled_tail(self):
        """Cold lag state: the follower never polled after bootstrap —
        promote() itself drains the whole tail before scheduling."""
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        _submit(mgr, 3)
        _drive(mgr, clock, cycles=3)
        lag = standby.lag_records
        assert lag > 0
        promoted = standby.promote(force=True)
        rep = standby.last_promotion
        assert rep.drained_records == lag
        assert rep.lag_records_at_entry == lag
        assert admitted_keys(promoted) == admitted_keys(mgr)

    def test_promotion_truncates_torn_crash_tail(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock)
        _submit(mgr, 2)
        _drive(mgr, clock, cycles=2)
        mgr.durable.truncate_tail(7)   # the dead leader's torn append
        promoted = standby.promote(force=True)
        assert standby.last_promotion.torn_records == 1
        assert promoted.recorder.by_reason("Promoted")
        # post-checkpoint the WAL is clean: new appends parse fine
        _submit(promoted, 1, start=5)
        assert promoted.durable.load_parts().torn_records == 0

    def test_promotion_requires_force_or_expiry(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0", duration=100.0)
        standby = StandbyReplica(mgr.durable, clock=clock)
        with pytest.raises(RuntimeError):
            standby.promote()          # live lease, no force
        clock.advance(200.0)           # lease expired: no force needed
        promoted = standby.promote()
        assert promoted is standby.mgr
        assert standby.promoted

    def test_deposed_leader_speculative_commit_rejected(self):
        """THE acceptance regression: a deposed-but-alive leader's
        in-flight speculative cycle can never commit — the fencing
        check rides _validate_speculation, and the store write behind
        it raises Fenced. The follower admits exactly once."""
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        token = lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="standby-0")
        _submit(mgr, 2)
        _drive(mgr, clock, cycles=1, standby=standby)
        standby.poll()
        # the partition: the standby force-promotes OVER the live leader
        promoted = standby.promote(force=True)
        assert not token.valid()
        # (a) the speculative commit gate reads the bumped epoch: any
        # in-flight cycle aborts with reason "fenced" before decode
        ok, reason = mgr.scheduler._validate_speculation(
            type("P", (), {"token": None})())
        assert (ok, reason) == (False, "fenced")
        # (b) the deposed leader's whole cycle is gated off...
        before = admitted_keys(mgr)
        mgr.scheduler.schedule(timeout=0)
        assert admitted_keys(mgr) == before
        # (c) ...and even a direct admission write cannot reach the log
        # (a REAL status change — a no-op write short-circuits before
        # the commit point and proves nothing)
        wl = mgr.store.get("Workload", "default", "w1-0")
        patch = wlpkg.clone_for_status_update(wl)
        wlpkg.set_quota_reservation(
            patch, api.Admission(cluster_queue="cq0"), clock.now())
        with pytest.raises(Fenced):
            mgr.scheduler.client.apply_admission(patch)
        assert "default/w1-0" not in sorted(
            wlpkg.key(w)
            for w in mgr.durable.load().objects.get("Workload",
                                                    {}).values()
            if wlpkg.has_quota_reservation(w))
        # the new leader admits the remaining heads exactly once
        _drive(promoted, clock, cycles=4)
        assert admitted_keys(promoted) == sorted(
            f"default/w{w}-{i}" for w in range(2) for i in range(2))
        crash_run = _load_crash_run()
        ok, msg = crash_run.usage_consistent(promoted)
        assert ok, msg

    def test_promotion_operator_surface(self):
        from kueue_tpu.obs import DebugEndpoints
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0")
        standby = StandbyReplica(mgr.durable, clock=clock,
                                 identity="standby-0")
        _submit(mgr, 1)
        _drive(mgr, clock, cycles=1)
        promoted = standby.promote(force=True)
        # metrics
        m = promoted.metrics
        assert m.promotions_total.value() == 1
        assert m.promotion_seconds.count() == 1
        assert m.fencing_epoch_gauge.value() == 2
        assert m.replication_lag_records.value() == 0
        # flight-recorder trace with drain/settle spans
        traces = [t for t in promoted.flight_recorder.traces()
                  if t.route == "promotion"]
        assert len(traces) == 1
        names = {name for name, _s, _d in traces[0].spans}
        assert {"promotion.drain", "promotion.settle"} <= names
        # /debug/recovery: standby section flips to leader + report
        payload = DebugEndpoints(promoted.scheduler,
                                 promoted.metrics).handle(
            "/debug/recovery", {})
        assert payload["standby"]["role"] == "leader"
        assert payload["promotion"]["epoch"] == 2
        # system event
        assert promoted.recorder.by_reason("Promoted")

    def test_manager_standby_classmethod(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        standby = KueueManager.standby(mgr.durable, clock=clock)
        assert isinstance(standby, StandbyReplica)
        _submit(mgr, 1)
        standby.poll()
        assert standby.mgr.store.count("Workload") == 2

    def test_shutdown_releases_lease(self):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock)
        lead(mgr, mgr.durable, identity="leader-0", duration=1000.0)
        mgr.shutdown()
        st = mgr.durable.lease_status(now=clock.now())
        assert st["holder"] == "" and st["expired"]
        # successor acquires immediately, no force, epoch bumps
        assert mgr.durable.acquire_lease("next", now=clock.now()) == 2


class TestCrashRunFailoverSmoke:
    def test_one_failover_run_converges(self, capsys):
        """Tier-1 smoke of the tools/crash_run.py promotion arm: one
        seeded store-write kill with a lagged follower must converge
        with zero lost/double/stranded admissions. The full
        promotion-timing sweep (every site x lag states x 20 seeds)
        rides --sweep / the @slow recovery sweep."""
        crash_run = _load_crash_run()
        assert crash_run.one_run(7, faultinject.SITE_STORE, 30,
                                 lag_mode="lagged") == 0
        import json
        verdict = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert verdict["mode"] == "failover" and verdict["crashed"]
        assert verdict["promotion"]["epoch"] == 2


# ----------------------------------------------------------------------
# 5. incremental cold restore (satellite)
# ----------------------------------------------------------------------

class TestIncrementalRestore:
    def _crashed_log(self, checkpoint_every=16):
        clock = FakeClock(1000.0)
        mgr = _mk_leader(clock, checkpoint_every=checkpoint_every)
        _submit(mgr, 3)
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {9: CRASH}}))
        with pytest.raises(InjectedCrash):
            _drive(mgr, clock, cycles=8)
        faultinject.uninstall()
        return mgr.durable, clock

    def test_incremental_equals_collapsed_replay(self):
        durable, clock = self._crashed_log()
        twin = durable.clone()
        inc = recovery.restore(durable, clock=clock,
                               checkpoint_after=False)
        col = recovery.restore(twin, clock=clock,
                               checkpoint_after=False, incremental=False)
        assert inc.last_recovery.replay_mode == "incremental"
        assert col.last_recovery.replay_mode == "collapsed"
        assert admitted_keys(inc) == admitted_keys(col)
        assert inc.store.count("Workload") == col.store.count("Workload")
        assert (inc.last_recovery.admitted_restored
                == col.last_recovery.admitted_restored)
        # both drive to the same converged end state
        _drive(inc, clock, cycles=6)
        _drive(col, clock, cycles=6)
        assert admitted_keys(inc) == admitted_keys(col)

    def test_incremental_restore_applies_tail_as_events(self):
        durable, clock = self._crashed_log(checkpoint_every=0)
        mgr = recovery.restore(durable, clock=clock)
        rep = mgr.last_recovery
        assert rep.replay_mode == "incremental"
        # no checkpoint was ever taken: the WHOLE log is tail records
        assert not rep.checkpoint_loaded
        assert rep.wal_records_replayed > 0
        _drive(mgr, clock, cycles=6)
        assert admitted_keys(mgr) == sorted(
            f"default/w{w}-{i}" for w in range(3) for i in range(2))


# ----------------------------------------------------------------------
# 6. the promotion-timing sweep: every site x lag states x 20 seeds
#    (@slow; the CLI twin is `tools/crash_run.py --sweep`)
# ----------------------------------------------------------------------

def _failover_sweep_site(site, seeds=20):
    crash_run = _load_crash_run()
    import random
    import zlib
    lag_names = sorted(crash_run.LAG_MODES)
    fired = 0
    oracle_by_seed = {}
    for seed in range(seeds):
        # crc32, not hash(): string hashing is randomized per process
        rng = random.Random(
            (zlib.crc32(site.encode()) & 0xFFFF) * 100_000 + seed)
        hit = (rng.randint(5, 120) if site == faultinject.SITE_STORE
               else rng.randint(0, 8))
        if seed not in oracle_by_seed:
            oracle_by_seed[seed] = crash_run.run_oracle(seed)
        lag_mode = lag_names[seed % len(lag_names)]
        crash = crash_run.run_failover(seed, site, hit, lag_mode)
        v = crash_run.verdict(oracle_by_seed[seed], crash)
        fired += 1 if v["crashed"] else 0
        assert v["converged"], (site, seed, hit, lag_mode,
                                crash["promotion"])
        assert not v["lost_admissions"], (site, seed, hit, lag_mode)
        assert not v["double_admission"], (site, seed, hit, lag_mode)
        assert not v["stranded"], (site, seed, hit, lag_mode)
    assert fired > 0, f"site {site} never fired across {seeds} seeds"


@pytest.mark.slow
@pytest.mark.parametrize("site", [
    faultinject.SITE_STORE, faultinject.SITE_APPLY,
    faultinject.SITE_DISPATCH, faultinject.SITE_COLLECT,
    faultinject.SITE_SCATTER, faultinject.SITE_REPLAY,
    faultinject.SITE_SPECULATION,
])
def test_promotion_timing_sweep(site):
    """ISSUE 15 acceptance: for every injection site, >= 20 seeds and
    the follower promoted at varied lag states (hot/lagged/cold by
    seed), kill -> promote -> replay converges to the uncrashed
    oracle's admitted set with zero double admissions, zero lost
    admissions, and zero stranded state."""
    _failover_sweep_site(site, seeds=20)
