"""Test configuration.

Force JAX onto a virtual 8-device CPU platform BEFORE jax is imported
anywhere, so multi-chip sharding tests (Mesh/pjit/shard_map) run without
TPU hardware. Benchmarks (bench.py) run outside pytest on the real chip.
"""

import os
import sys

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
# The axon sitecustomize pre-registers the TPU backend and pins
# JAX_PLATFORMS=axon; override both for the test suite.
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from kueue_tpu import features  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_features():
    features.reset()
    yield
    features.reset()
