"""Queue manager semantics: heads pops one per CQ, priority/FIFO order,
StrictFIFO vs BestEffortFIFO requeue, inadmissible parking and flush.

Mirrors the reference's pkg/queue/{manager_test.go,cluster_queue_test.go}
core cases.
"""

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.queue import Manager, RequeueReason
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas, make_local_queue


def setup_manager(strategy=api.BEST_EFFORT_FIFO):
    m = Manager(clock=FakeClock(1000.0))
    cq = (ClusterQueueWrapper("cq").queueing_strategy(strategy)
          .resource_group(flavor_quotas("default", cpu="10")).obj())
    m.add_cluster_queue(cq)
    m.add_local_queue(make_local_queue("lq", "default", "cq"))
    return m


class TestHeads:
    def test_one_head_per_cq_in_priority_order(self):
        m = setup_manager()
        m.add_or_update_workload(WorkloadWrapper("low").queue("lq").priority(1)
                                 .creation(1).pod_set(count=1, cpu="1").obj())
        m.add_or_update_workload(WorkloadWrapper("high").queue("lq").priority(10)
                                 .creation(2).pod_set(count=1, cpu="1").obj())
        heads = m.heads_nonblocking()
        assert [h.obj.metadata.name for h in heads] == ["high"]
        heads = m.heads_nonblocking()
        assert [h.obj.metadata.name for h in heads] == ["low"]
        assert m.heads_nonblocking() == []

    def test_fifo_within_priority(self):
        m = setup_manager()
        m.add_or_update_workload(WorkloadWrapper("b").queue("lq").creation(2)
                                 .pod_set(count=1, cpu="1").obj())
        m.add_or_update_workload(WorkloadWrapper("a").queue("lq").creation(1)
                                 .pod_set(count=1, cpu="1").obj())
        assert m.heads_nonblocking()[0].obj.metadata.name == "a"

    def test_multiple_cqs_one_head_each(self):
        m = setup_manager()
        cq2 = (ClusterQueueWrapper("cq2")
               .resource_group(flavor_quotas("default", cpu="10")).obj())
        m.add_cluster_queue(cq2)
        m.add_local_queue(make_local_queue("lq2", "default", "cq2"))
        m.add_or_update_workload(WorkloadWrapper("w1").queue("lq")
                                 .pod_set(count=1, cpu="1").obj())
        m.add_or_update_workload(WorkloadWrapper("w2", "default").queue("lq2")
                                 .pod_set(count=1, cpu="1").obj())
        heads = m.heads_nonblocking()
        assert {h.obj.metadata.name for h in heads} == {"w1", "w2"}

    def test_workload_without_queue_not_queued(self):
        m = setup_manager()
        assert not m.add_or_update_workload(
            WorkloadWrapper("w").queue("nope").pod_set(count=1, cpu="1").obj())


class TestRequeue:
    def test_best_effort_parks_inadmissible(self):
        m = setup_manager(api.BEST_EFFORT_FIFO)
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        m.add_or_update_workload(w)
        info = m.heads_nonblocking()[0]
        assert m.requeue_workload(info, RequeueReason.GENERIC)
        cqh = m.cluster_queues["cq"]
        assert cqh.pending_inadmissible() == 1
        assert cqh.pending_active() == 0
        assert m.heads_nonblocking() == []

    def test_best_effort_requeues_after_nomination_failure(self):
        m = setup_manager(api.BEST_EFFORT_FIFO)
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        m.add_or_update_workload(w)
        info = m.heads_nonblocking()[0]
        assert m.requeue_workload(info, RequeueReason.FAILED_AFTER_NOMINATION)
        assert m.cluster_queues["cq"].pending_active() == 1

    def test_strict_fifo_requeues_to_heap(self):
        m = setup_manager(api.STRICT_FIFO)
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        m.add_or_update_workload(w)
        info = m.heads_nonblocking()[0]
        assert m.requeue_workload(info, RequeueReason.GENERIC)
        assert m.cluster_queues["cq"].pending_active() == 1
        assert m.cluster_queues["cq"].pending_inadmissible() == 0

    def test_cohort_flush_moves_parked(self):
        m = Manager(clock=FakeClock(1000.0))
        for name in ("cq1", "cq2"):
            cq = (ClusterQueueWrapper(name).cohort("team")
                  .resource_group(flavor_quotas("default", cpu="10")).obj())
            m.add_cluster_queue(cq)
        m.add_local_queue(make_local_queue("lq1", "default", "cq1"))
        m.add_local_queue(make_local_queue("lq2", "default", "cq2"))
        w = WorkloadWrapper("w").queue("lq1").pod_set(count=1, cpu="1").obj()
        m.add_or_update_workload(w)
        info = m.heads_nonblocking()[0]
        m.requeue_workload(info, RequeueReason.GENERIC)
        assert m.cluster_queues["cq1"].pending_inadmissible() == 1
        # An event on cq2 (same cohort) flushes cq1's parked workloads.
        m.queue_inadmissible_workloads({"cq2"})
        assert m.cluster_queues["cq1"].pending_inadmissible() == 0
        assert m.cluster_queues["cq1"].pending_active() == 1

    def test_requeue_during_cycle_goes_back_to_heap(self):
        # If a flush happened after Pop, requeue goes straight to the heap
        # (popCycle/queueInadmissibleCycle race avoidance).
        m = setup_manager(api.BEST_EFFORT_FIFO)
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        m.add_or_update_workload(w)
        info = m.heads_nonblocking()[0]
        m.queue_inadmissible_workloads({"cq"})  # during the cycle
        assert m.requeue_workload(info, RequeueReason.GENERIC)
        assert m.cluster_queues["cq"].pending_active() == 1

    def test_requeue_backoff_gates_heap(self):
        clock = FakeClock(1000.0)
        m = Manager(clock=clock)
        cq = (ClusterQueueWrapper("cq")
              .resource_group(flavor_quotas("default", cpu="10")).obj())
        m.add_cluster_queue(cq)
        m.add_local_queue(make_local_queue("lq", "default", "cq"))
        w = WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="1").obj()
        from kueue_tpu.api.meta import Condition, set_condition
        set_condition(w.status.conditions, Condition(
            type=api.WORKLOAD_EVICTED, status="True",
            reason=api.EVICTED_BY_PODS_READY_TIMEOUT), 900.0)
        w.status.requeue_state = api.RequeueState(count=1, requeue_at=1500.0)
        m.add_or_update_workload(w)
        # backoff not expired -> parked
        assert m.cluster_queues["cq"].pending_inadmissible() == 1
        clock.advance(600)
        m.queue_inadmissible_workloads({"cq"})
        assert m.cluster_queues["cq"].pending_active() == 1


class TestVisibilitySnapshot:
    def test_topn_snapshot(self):
        m = setup_manager()
        for i in range(5):
            m.add_or_update_workload(WorkloadWrapper(f"w{i}").queue("lq").creation(i)
                                     .pod_set(count=1, cpu="1").obj())
        assert m.update_snapshot("cq", 3)
        snap = m.get_snapshot("cq")
        assert len(snap) == 3
        assert snap[0][0] == "default/w0"
        assert not m.update_snapshot("cq", 3)  # unchanged


class TestLocalQueueLifecycle:
    def test_delete_local_queue_removes_items(self):
        m = setup_manager()
        m.add_or_update_workload(WorkloadWrapper("w").queue("lq")
                                 .pod_set(count=1, cpu="1").obj())
        m.delete_local_queue(make_local_queue("lq", "default", "cq"))
        assert m.heads_nonblocking() == []

    def test_update_local_queue_moves_items(self):
        m = setup_manager()
        cq2 = (ClusterQueueWrapper("cq2")
               .resource_group(flavor_quotas("default", cpu="10")).obj())
        m.add_cluster_queue(cq2)
        m.add_or_update_workload(WorkloadWrapper("w").queue("lq")
                                 .pod_set(count=1, cpu="1").obj())
        lq = make_local_queue("lq", "default", "cq2")
        m.update_local_queue(lq)
        heads = m.heads_nonblocking()
        assert len(heads) == 1
        assert heads[0].cluster_queue == "cq2"
