"""Virtual-time soak harness + adversarial traffic search (sim/soak.py,
sim/adversary.py; ISSUE 18).

Tier-1 runs the composed smoke soak (seeded, virtual-time, ~a second:
diurnal wave -> quota churn -> cluster loss -> crash -> mid-storm
failover on ONE manager) plus the params/spec serialization contracts,
the SLOSpec soak-gate units, the harness retention regression and the
adversary's search/shrink machinery against a stub runner. The
``slow`` tier runs the multi-day full preset and the end-to-end
acceptance hunt: given the planted weak-backoff fixture, the search
must find a violating trace, shrink it to a minimal seeded repro, and
that repro must replay red standalone while the same shape stays green
under the healthy backoff config.
"""

import json
import os
import random
from dataclasses import replace

import pytest

from kueue_tpu.perf.checker import SLOSpec, check_slo
from kueue_tpu.sim import adversary
from kueue_tpu.sim.scenarios import SCENARIOS, ScenarioResult
from kueue_tpu.sim.soak import PRESETS, SoakParams, run_soak


# ----------------------------------------------------------------------
# params serialization (the adversary's substrate)
# ----------------------------------------------------------------------

class TestSoakParams:
    def test_round_trip_and_unknown_key_rejected(self):
        p = replace(SoakParams(), storm_per_tenant=7,
                    pods_ready_outage_s=33.5)
        d = json.loads(json.dumps(p.to_dict()))   # JSON-safe
        assert SoakParams.from_dict(d) == p
        with pytest.raises(ValueError, match="unknown SoakParams"):
            SoakParams.from_dict({**d, "bogus_knob": 1})

    def test_spec_round_trip(self):
        p = replace(SoakParams(), backoff_max_s=2.0)
        spec = adversary.to_spec("soak_repro_s3", p, seed=3)
        name, seed, params = adversary.from_spec(
            json.loads(json.dumps(spec)))
        assert (name, seed, params) == ("soak_repro_s3", 3, p)


# ----------------------------------------------------------------------
# SLOSpec soak gates (perf/checker.py; counters-backed)
# ----------------------------------------------------------------------

def soak_result(**counters) -> ScenarioResult:
    res = ScenarioResult(name="unit", seed=0, scale="smoke")
    res.admitted = res.admissions = res.submitted = 1
    res.counters = counters
    return res


class TestSoakSLOGates:
    SPEC = SLOSpec(require_aging_green=True, max_journey_burn_rate=1.0,
                   max_mid_traffic_compiles_after_warm=0,
                   require_zero_live_handouts=True)
    GREEN = dict(
        aging={"ok": True, "failing": [], "verdicts": {}},
        journeys={"burn_rates": {"prod": 0.2}},
        mid_traffic_compiles_after_warm=0,
        live_handouts_at_teardown=0)

    def test_green_counters_pass(self):
        assert check_slo(soak_result(**self.GREEN), self.SPEC) == []

    def test_each_gate_trips_alone(self):
        red = {
            "aging": {"ok": False, "failing": ["rss_kb"],
                      "verdicts": {"rss_kb": "leaking"}},
            "journeys": {"burn_rates": {"prod": 2.5}},
            "mid_traffic_compiles_after_warm": 3,
            "live_handouts_at_teardown": 2,
        }
        for key, bad in red.items():
            viols = check_slo(
                soak_result(**{**self.GREEN, key: bad}), self.SPEC)
            assert len(viols) == 1, (key, viols)

    def test_missing_evidence_is_a_violation_not_a_pass(self):
        """A soak whose instrumentation never produced the counter
        must fail the gate — absence of evidence is absence of a
        green."""
        for key in self.GREEN:
            counters = {k: v for k, v in self.GREEN.items() if k != key}
            assert check_slo(soak_result(**counters), self.SPEC), key

    def test_gates_default_off(self):
        # a plain SLOSpec without soak fields ignores the counters
        assert check_slo(soak_result(), SLOSpec()) == []


# ----------------------------------------------------------------------
# the composed smoke soak (tier-1: ~a second, seeded, virtual time)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def smoke_result():
    return run_soak(PRESETS["smoke"], seed=0, scale="smoke")


class TestComposedSoakSmoke:
    def test_green_with_crash_failover_and_transitions(self, smoke_result):
        res = smoke_result
        assert res.violations == []
        soak = res.counters["soak"]
        # >= 4 phase transitions including a crash AND a failover
        assert soak["phase_transitions"] >= 4
        phases = [p["phase"] for p in soak["phases"]]
        assert phases == ["wave", "churn", "outage", "readiness",
                          "crash-storm", "failover-storm"]
        assert res.restarts >= 1 and res.promotions >= 1
        assert soak["quota_edits"] >= 2

    def test_aging_gate_green_at_run_end(self, smoke_result):
        aging = smoke_result.counters["aging"]
        assert aging["ok"] is True and aging["failing"] == []
        # every wired monitor rendered a verdict
        assert "live_handouts" in aging["verdicts"]

    def test_soak_gate_counters_stamped(self, smoke_result):
        c = smoke_result.counters
        assert c["mid_traffic_compiles_after_warm"] == 0
        assert c["live_handouts_at_teardown"] == 0
        assert c["journeys"]["burn_rates"]

    def test_retention_bounded_at_steady_state(self, smoke_result):
        """ISSUE 18 satellite: every long-lived harness structure
        reports its occupancy against an explicit cap — the memory
        shape a multi-day run must hold."""
        ret = smoke_result.counters["retention"]
        for val_k, cap_k in (("cycle_routes", "cycle_routes_cap"),
                             ("flight_ring", "flight_ring_cap"),
                             ("event_window", "event_window_cap"),
                             ("journeys_retained",
                              "journeys_retained_cap")):
            assert 0 < ret[val_k] <= ret[cap_k], (val_k, ret)
        # the route mix stays a small keyed dict, not a per-cycle log
        assert ret["route_mix_keys"] <= 64

    def test_deterministic_per_seed(self):
        a = run_soak(PRESETS["smoke"], seed=1, scale="smoke")
        b = run_soak(PRESETS["smoke"], seed=1, scale="smoke")
        assert json.dumps(a.to_dict(), sort_keys=True) == \
            json.dumps(b.to_dict(), sort_keys=True)


# ----------------------------------------------------------------------
# adversary machinery (stub runner: no control-plane runs in tier 1)
# ----------------------------------------------------------------------

class StubRun:
    def __init__(self, violations):
        self.violations = violations


def stub_runner(threshold=100.0):
    """Red iff the readiness outage exceeds ``threshold`` — a planted
    one-dimensional weakness with a known minimal repro."""
    def run(params, seed=0, scale="stub"):
        if params.pods_ready_outage_s > threshold:
            return StubRun([
                f"requeue amplification "
                f"{3.0 + params.pods_ready_outage_s / 100:.2f} "
                f"exceeds 3.00"])
        return StubRun([])
    return run


class TestAdversary:
    def test_mutate_seeded_and_constrained(self):
        base = SoakParams()
        a = adversary.mutate(base, random.Random(5))
        b = adversary.mutate(base, random.Random(5))
        assert a == b and a != base
        for i in range(200):
            m = adversary.mutate(base, random.Random(i))
            assert m.kill_hit_hi >= m.kill_hit_lo
            assert m.outage_end_frac > m.outage_start_frac
            # fair-play envelope: storm work stays drainable
            assert m.storm_per_tenant * m.storm_runtime_s <= \
                0.5 * m.day_s * m.quota_units + 1e-6
            for name, (lo, hi, _) in adversary.DIMENSIONS.items():
                if name not in ("kill_hit_hi", "outage_end_frac",
                                "storm_runtime_s"):
                    assert lo <= getattr(m, name) <= hi, name

    def test_interesting_filters_structural_artifacts(self):
        assert adversary.interesting([
            "composed soak never cold-restarted (crash-storm kill "
            "mis-armed?)",
            "requeue amplification 3.57 exceeds 3.00",
        ]) == ["requeue amplification 3.57 exceeds 3.00"]

    def test_search_finds_and_shrinks_to_minimal_repro(self):
        """Against the stub weakness the search must find a red probe
        and shrink it to the ONE dimension that matters, bisected to
        just past the threshold."""
        base = SoakParams()
        rep = adversary.search(base, seed=3, budget=16,
                               runner=stub_runner(threshold=100.0))
        assert rep["findings"]
        assert rep["probes"][0]["base"] and \
            not rep["probes"][0]["violations"]
        assert rep["repro"] is not None
        _, _, mini = adversary.from_spec(rep["repro"])
        delta = {k for k in SoakParams.__dataclass_fields__
                 if getattr(mini, k) != getattr(base, k)}
        assert delta == {"pods_ready_outage_s"}
        # bisection walked it toward the threshold, not the range top
        assert 100.0 < mini.pods_ready_outage_s < 125.0
        assert rep["shrink"]["violations"]

    def test_search_reports_red_base_without_shrink(self):
        def always_red(params, seed=0, scale=""):
            return StubRun(["requeue amplification 9.00 exceeds 3.00"])
        rep = adversary.search(SoakParams(), seed=0, budget=2,
                               runner=always_red)
        # base itself red -> reported, and shrink targets a MUTANT
        assert rep["probes"][0]["violations"]
        assert rep["findings"][0]["probe"] == 0

    def test_register_repro_installs_catalog_entry(self):
        spec = adversary.to_spec("soak_repro_unit", SoakParams(), seed=0)
        name = adversary.register_repro(spec)
        try:
            assert name == "soak_repro_unit"
            assert callable(SCENARIOS[name])
        finally:
            del SCENARIOS[name]

    def test_shape_report_feeds_the_ladder(self):
        """Satellite: adversarial storm geometries bucket to (B, rank)
        keys; the report is seeded-deterministic and flags only keys
        the current preempt ladder would not precompile."""
        rep = adversary.preempt_shape_report(SoakParams(), seed=2,
                                             samples=64)
        assert rep == adversary.preempt_shape_report(
            SoakParams(), seed=2, samples=64)
        assert rep["keys"] and rep["ladder_keys"]
        assert set(rep["off_ladder"]) <= set(rep["keys"])
        assert set(rep["off_ladder"]).isdisjoint(rep["ladder_keys"])
        assert rep["suggested_rungs"] == sorted(
            rep["off_ladder"], key=lambda k: -rep["off_ladder"][k])


# ----------------------------------------------------------------------
# the checked-in repro corpus (corpus/*.json): regression-locked, not
# aspirational — every entry was hunted + shrunk by soak_run --hunt
# against the weak-backoff fixture and must keep replaying RED through
# the catalog; a harness change that silences the detector fails here
# ----------------------------------------------------------------------

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "..", "corpus")


def _corpus_specs():
    import glob
    return sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


class TestReproCorpus:
    def test_corpus_is_not_empty(self):
        assert _corpus_specs(), \
            "corpus/ has no checked-in repro entries"

    @pytest.mark.parametrize(
        "path", _corpus_specs(),
        ids=[os.path.basename(p) for p in _corpus_specs()])
    def test_corpus_entry_replays_red_through_the_catalog(self, path):
        with open(path) as f:
            spec = json.load(f)
        name = adversary.register_repro(spec)
        try:
            assert name == spec["scenario"]
            replay = SCENARIOS[name]()
            assert adversary.interesting(replay.violations), (
                f"{os.path.basename(path)} no longer replays red — "
                "if a real fix made it green, move the entry to a "
                "green regression gate instead of deleting it")
            # seeded determinism: the lock is byte-stable run to run
            again = SCENARIOS[name]()
            assert again.violations == replay.violations
        finally:
            del SCENARIOS[name]


# ----------------------------------------------------------------------
# slow tier: the multi-day schedule + the end-to-end acceptance hunt
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestSoakFull:
    def test_full_preset_three_virtual_days_green(self):
        res = run_soak(PRESETS["full"], seed=0, scale="full")
        assert res.violations == []
        soak = res.counters["soak"]
        assert soak["days"] >= 3 and soak["day_s"] >= 86_400.0
        assert soak["phase_transitions"] >= 4
        assert res.restarts >= 1 and res.promotions >= 1
        assert res.counters["aging"]["ok"] is True

    def test_hunt_finds_planted_weakness_shrinks_and_replays(self):
        """ISSUE 18 acceptance: against the weak-backoff fixture the
        search finds a violating trace, shrinks it to a minimal seeded
        repro, and the emitted spec replays RED standalone while the
        same traffic shape is GREEN under the healthy backoff config —
        the violation attributes to the planted weakness, not to the
        weather."""
        rep = adversary.search(adversary.weak_backoff_fixture(),
                               seed=0, budget=12)
        assert rep["findings"], "hunt never found the planted weakness"
        assert rep["repro"] is not None
        name, seed, mini = adversary.from_spec(rep["repro"])

        # the minimal repro replays red standalone through the catalog
        adversary.register_repro(rep["repro"])
        try:
            replay = SCENARIOS[name]()
            assert adversary.interesting(replay.violations), \
                "shrunk repro did not replay red"
        finally:
            del SCENARIOS[name]

        # the same shape under the HEALTHY backoff config stays green:
        # exponential backoff keeps the eviction laps logarithmic
        healthy = replace(mini,
                          pods_ready_timeout_s=SoakParams().pods_ready_timeout_s,
                          backoff_base_s=SoakParams().backoff_base_s,
                          backoff_max_s=SoakParams().backoff_max_s)
        res = run_soak(healthy, seed=seed, scale="healthy")
        assert adversary.interesting(res.violations) == [], \
            res.violations
