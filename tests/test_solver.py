"""Differential tests: the batched TPU solver vs the sequential CPU
scheduler (the conformance oracle).

For single-cycle, fit-mode scenarios the solver must reproduce the CPU
scheduler's decisions exactly: same admitted set, same flavor choices,
same intra-cycle skip behavior (SURVEY.md §7 "semantic fidelity").
Randomized cases sweep cohorts, quotas, borrowing limits, flavors,
taints and priorities.
"""

import random

import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import Taint, Toleration
from kueue_tpu.solver import BatchSolver
from tests.test_scheduler import Env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas, make_local_queue


def build_env(setup, solver=False, fair_sharing=False, fs_strategies=None):
    env = Env(fair_sharing=fair_sharing, fs_strategies=fs_strategies)
    if solver:
        env.scheduler.solver = BatchSolver()
        env.scheduler.solver_min_heads = 0  # force the solver path
        env.scheduler.solver_sync_floor_ms = 0  # force device preemption
    setup(env)
    return env


def admitted_map(env):
    """key -> (flavors, count) per podset, from applied admissions."""
    out = {}
    for key, wl in env.client.applied.items():
        psas = wl.status.admission.pod_set_assignments
        out[key] = tuple((tuple(sorted(psa.flavors.items())), psa.count)
                         for psa in psas)
    return out


def assert_differential(setup, workloads, cycles=1, fair_sharing=False):
    """Run the same scenario through CPU-only and solver-enabled
    schedulers; decisions must match exactly."""
    envs = [build_env(setup, solver=False, fair_sharing=fair_sharing),
            build_env(setup, solver=True, fair_sharing=fair_sharing)]
    for env in envs:
        for w in workloads():
            env.submit(w)
        for _ in range(cycles):
            env.cycle()
    cpu, tpu = admitted_map(envs[0]), admitted_map(envs[1])
    assert cpu == tpu, f"CPU admitted {sorted(cpu)} but solver admitted {sorted(tpu)}"
    return cpu


class TestSolverMatchesCPU:
    def test_simple_fit(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq")

        result = assert_differential(
            setup, lambda: [WorkloadWrapper("w").queue("lq").pod_set(count=2, cpu="2").obj()])
        assert "default/w" in result

    def test_capacity_contention_order(self):
        # Two CQs in a cohort contending: higher priority wins, second is
        # skipped intra-cycle by both paths.
        def setup(env):
            env.add_flavor("default")
            for name in ("a", "b"):
                env.add_cq(ClusterQueueWrapper(name).cohort("team")
                           .resource_group(flavor_quotas("default", cpu="5")).obj(),
                           f"lq-{name}")

        def workloads():
            return [
                WorkloadWrapper("w1").queue("lq-a").priority(5).creation(1)
                .pod_set(count=1, cpu="8").obj(),
                WorkloadWrapper("w2").queue("lq-b").priority(1).creation(2)
                .pod_set(count=1, cpu="8").obj(),
            ]

        result = assert_differential(setup, workloads)
        assert set(result) == {"default/w1"}

    def test_borrowers_after_non_borrowers(self):
        def setup(env):
            env.add_flavor("default")
            for name in ("a", "b"):
                env.add_cq(ClusterQueueWrapper(name).cohort("team")
                           .resource_group(flavor_quotas("default", cpu="10")).obj(),
                           f"lq-{name}")

        def workloads():
            return [
                WorkloadWrapper("borrower").queue("lq-a").priority(100).creation(1)
                .pod_set(count=1, cpu="12").obj(),
                WorkloadWrapper("fitter").queue("lq-b").priority(0).creation(2)
                .pod_set(count=1, cpu="10").obj(),
            ]

        result = assert_differential(setup, workloads)
        assert set(result) == {"default/fitter"}

    def test_flavor_fungibility_borrow_policy(self):
        def setup(env):
            env.add_flavor("spot")
            env.add_flavor("on-demand")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .resource_group(flavor_quotas("spot", cpu="4"),
                                       flavor_quotas("on-demand", cpu="4")).obj(), "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("spot", cpu="4")).obj(), "lq-b")

        def workloads():
            # 6 cpu: borrows on spot (4+4 available) vs fits on on-demand?
            # on-demand has only a's 4 + nothing => borrow either way; the
            # default Borrow policy takes the first fitting flavor (spot).
            return [WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="6").obj()]

        result = assert_differential(setup, workloads)
        assert result["default/w"][0][0] == (("cpu", "spot"),)

    def test_try_next_flavor_avoids_borrowing(self):
        def setup(env):
            env.add_flavor("spot")
            env.add_flavor("on-demand")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .flavor_fungibility(when_can_borrow=api.TRY_NEXT_FLAVOR)
                       .resource_group(flavor_quotas("spot", cpu="4"),
                                       flavor_quotas("on-demand", cpu="8")).obj(), "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("spot", cpu="4")).obj(), "lq-b")

        def workloads():
            return [WorkloadWrapper("w").queue("lq-a").pod_set(count=1, cpu="6").obj()]

        result = assert_differential(setup, workloads)
        # avoids borrowing on spot; lands on on-demand which fits nominally
        assert result["default/w"][0][0] == (("cpu", "on-demand"),)

    def test_taints_and_selectors(self):
        def setup(env):
            env.add_flavor("tainted", taints=[Taint(key="gpu", value="y", effect="NoSchedule")])
            env.add_flavor("zone-a", labels={"zone": "a"})
            env.add_flavor("zone-b", labels={"zone": "b"})
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("tainted", cpu="10"),
                                       flavor_quotas("zone-a", cpu="10"),
                                       flavor_quotas("zone-b", cpu="10")).obj(), "lq")

        def workloads():
            return [
                WorkloadWrapper("plain").queue("lq").creation(1).pod_set(count=1, cpu="2").obj(),
                WorkloadWrapper("tolerates").queue("lq").creation(2)
                .pod_set(count=1, cpu="2").toleration("gpu", "y").obj(),
                WorkloadWrapper("pinned").queue("lq").creation(3)
                .pod_set(count=1, cpu="2").node_selector("zone", "b").obj(),
            ]

        result = assert_differential(setup, workloads, cycles=3)
        assert result["default/plain"][0][0] == (("cpu", "zone-a"),)
        assert result["default/tolerates"][0][0] == (("cpu", "tainted"),)
        assert result["default/pinned"][0][0] == (("cpu", "zone-b"),)

    def test_multi_podset_accumulation(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("default", cpu="10")).obj(), "lq")

        def workloads():
            w = (WorkloadWrapper("w").queue("lq")
                 .pod_set(name="driver", count=1, cpu="2")
                 .pod_set(name="workers", count=4, cpu="2").obj())
            return [w]

        result = assert_differential(setup, workloads)
        assert "default/w" in result

    def test_multi_resource_group_choice(self):
        def setup(env):
            env.add_flavor("cpu-flavor")
            env.add_flavor("gpu-flavor")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("cpu-flavor", cpu="10", memory="10Gi"))
                       .resource_group(flavor_quotas("gpu-flavor", **{"nvidia_com/gpu": "4"}))
                       .obj(), "lq")

        def workloads():
            w = (WorkloadWrapper("w").queue("lq").pod_set(count=1, cpu="2", memory="1Gi"))
            w.request("nvidia.com/gpu", 2)
            return [w.obj()]

        result = assert_differential(setup, workloads)
        flavors = dict(result["default/w"][0][0])
        assert flavors["cpu"] == "cpu-flavor"
        assert flavors["memory"] == "cpu-flavor"
        assert flavors["nvidia.com/gpu"] == "gpu-flavor"


class TestSolverFairSharing:
    """Device DRF share in the Phase B sort key (reference:
    dominantResourceShare clusterqueue.go:503-564 feeding
    entryOrdering.Less scheduler.go:643-672)."""

    @staticmethod
    def _three_cq_setup(weights=None):
        def setup(env):
            env.add_flavor("default")
            for name, nominal in (("a", "2"), ("b", "8"), ("c", "4")):
                w = ClusterQueueWrapper(name).cohort("team")
                if weights and name in weights:
                    w = w.fair_weight(weights[name])
                env.add_cq(w.resource_group(
                    flavor_quotas("default", cpu=nominal)).obj(), f"lq-{name}")
        return setup

    @staticmethod
    def _contending_workloads():
        # wa borrows 6/14 (share 428), wb borrows 4/14 (share 285);
        # fair sharing admits wb first despite wa's higher priority.
        return [
            WorkloadWrapper("wa").queue("lq-a").priority(10).creation(1)
            .pod_set(count=1, cpu="8").obj(),
            WorkloadWrapper("wb").queue("lq-b").priority(1).creation(2)
            .pod_set(count=1, cpu="12").obj(),
        ]

    def test_share_orders_before_priority(self):
        result = assert_differential(self._three_cq_setup(),
                                     self._contending_workloads,
                                     fair_sharing=True)
        assert set(result) == {"default/wb"}

    def test_without_fair_sharing_priority_wins(self):
        result = assert_differential(self._three_cq_setup(),
                                     self._contending_workloads,
                                     fair_sharing=False)
        assert set(result) == {"default/wa"}

    def test_fair_weight_scales_share(self):
        # a's weight 4000 divides its share to 107 < wb's 285: wa first.
        result = assert_differential(self._three_cq_setup({"a": 4000}),
                                     self._contending_workloads,
                                     fair_sharing=True)
        assert set(result) == {"default/wa"}

    def test_zero_weight_sorts_last(self):
        # weight 0 => infinite share: wb admits first even though wa
        # borrows less after c's quota shrinks.
        def setup(env):
            env.add_flavor("default")
            for name, nominal in (("a", "2"), ("b", "8"), ("c", "4")):
                w = ClusterQueueWrapper(name).cohort("team")
                if name == "a":
                    w = w.fair_weight(0)
                env.add_cq(w.resource_group(
                    flavor_quotas("default", cpu=nominal)).obj(), f"lq-{name}")

        def workloads():
            return [
                WorkloadWrapper("wa").queue("lq-a").priority(10).creation(1)
                .pod_set(count=1, cpu="3").obj(),
                WorkloadWrapper("wb").queue("lq-b").priority(1).creation(2)
                .pod_set(count=1, cpu="12").obj(),
            ]

        result = assert_differential(setup, workloads, fair_sharing=True)
        assert set(result) == {"default/wb"}

    def test_fair_sharing_random_differential(self):
        import random
        for seed in range(10):
            rng = random.Random(7000 + seed)
            n_cqs = rng.randint(2, 5)
            specs = [(f"cq{i}", rng.choice([2, 5, 8]),
                      rng.choice([500, 1000, 2000]))
                     for i in range(n_cqs)]

            def setup(env, specs=specs):
                env.add_flavor("default")
                for name, nominal, weight in specs:
                    env.add_cq(ClusterQueueWrapper(name).cohort("team")
                               .fair_weight(weight)
                               .resource_group(flavor_quotas(
                                   "default", cpu=str(nominal))).obj(),
                               f"lq-{name}")

            wl_specs = [(f"w{i}", f"lq-cq{rng.randrange(n_cqs)}",
                         rng.randint(0, 3), float(i),
                         str(rng.choice([1, 2, 4, 7, 12])))
                        for i in range(rng.randint(3, 10))]

            def workloads(wl_specs=wl_specs):
                return [WorkloadWrapper(n).queue(q).priority(p).creation(ts)
                        .pod_set(count=1, cpu=c).obj()
                        for n, q, p, ts, c in wl_specs]

            assert_differential(setup, workloads, fair_sharing=True)


class TestSolverFungibilityState:
    """Solver admissions must carry the same LastTriedFlavorIdx resume
    state as the CPU assigner (reference: flavorassigner.go:289-324)."""

    @staticmethod
    def _last_states(setup, workloads):
        """Returns (cpu last_state list, solver last_state list) for the
        nominated heads of one cycle."""
        from kueue_tpu.scheduler import flavorassigner as fa
        env = build_env(setup, solver=True)
        for w in workloads():
            env.submit(w)
        heads = env.queues.heads(timeout=0.01)
        snapshot = env.cache.snapshot()
        cpu_states, solver_states = [], []
        for info in heads:
            cq = snapshot.cluster_queues[info.cluster_queue]
            assigner = fa.FlavorAssigner(info, cq, snapshot.resource_flavors,
                                         False, lambda *a: False)
            cpu_states.append(assigner.assign().last_state)
        decisions = env.scheduler.solver.solve(snapshot, heads)
        for i in range(len(heads)):
            assignment, _ = decisions[i]
            solver_states.append(assignment.last_state)
        return cpu_states, solver_states

    def test_mid_list_fit_records_rank(self):
        def setup(env):
            env.add_flavor("f0")
            env.add_flavor("f1")
            env.add_flavor("f2")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("f0", cpu="0"),
                                       flavor_quotas("f1", cpu="8"),
                                       flavor_quotas("f2", cpu="8")).obj(), "lq")

        cpu, tpu = self._last_states(
            setup, lambda: [WorkloadWrapper("w").queue("lq")
                            .pod_set(count=1, cpu="4").obj()])
        assert cpu[0].last_tried_flavor_idx == tpu[0].last_tried_flavor_idx
        assert tpu[0].last_tried_flavor_idx == [{"cpu": 1}]

    def test_last_flavor_fit_records_minus_one(self):
        def setup(env):
            env.add_flavor("f0")
            env.add_flavor("f1")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("f0", cpu="0"),
                                       flavor_quotas("f1", cpu="8")).obj(), "lq")

        cpu, tpu = self._last_states(
            setup, lambda: [WorkloadWrapper("w").queue("lq")
                            .pod_set(count=1, cpu="4").obj()])
        assert cpu[0].last_tried_flavor_idx == tpu[0].last_tried_flavor_idx
        assert tpu[0].last_tried_flavor_idx == [{"cpu": -1}]

    def test_try_next_flavor_borrow_fit_exhausts_list(self):
        # TryNextFlavor + only borrowing fits anywhere: CPU scans the
        # whole list, stores -1, picks the first borrow fit.
        def setup(env):
            env.add_flavor("f0")
            env.add_flavor("f1")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .flavor_fungibility(when_can_borrow=api.TRY_NEXT_FLAVOR)
                       .resource_group(flavor_quotas("f0", cpu="2"),
                                       flavor_quotas("f1", cpu="2")).obj(), "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("f0", cpu="8"),
                                       flavor_quotas("f1", cpu="8")).obj(), "lq-b")

        cpu, tpu = self._last_states(
            setup, lambda: [WorkloadWrapper("w").queue("lq-a")
                            .pod_set(count=1, cpu="4").obj()])
        assert cpu[0].last_tried_flavor_idx == tpu[0].last_tried_flavor_idx
        assert tpu[0].last_tried_flavor_idx == [{"cpu": -1}]

    def test_resume_differential_across_cycles(self):
        """Intra-cycle skip records resume state; the next cycle must
        start from it identically on both paths."""
        def setup(env):
            env.add_flavor("f0")
            env.add_flavor("f1")
            env.add_cq(ClusterQueueWrapper("a").cohort("team")
                       .resource_group(flavor_quotas("f0", cpu="8")).obj(), "lq-a")
            env.add_cq(ClusterQueueWrapper("b").cohort("team")
                       .resource_group(flavor_quotas("f0", cpu="0"),
                                       flavor_quotas("f1", cpu="4")).obj(), "lq-b")

        def workloads():
            return [
                WorkloadWrapper("wa").queue("lq-a").priority(10).creation(1)
                .pod_set(count=1, cpu="8").obj(),
                WorkloadWrapper("wb").queue("lq-b").priority(1).creation(2)
                .pod_set(count=1, cpu="4").obj(),
            ]

        result = assert_differential(setup, workloads, cycles=3)
        assert set(result) == {"default/wa", "default/wb"}
        assert dict(result["default/wb"][0][0])["cpu"] == "f1"


class TestSolverRandomDifferential:
    @pytest.mark.parametrize("seed", range(12))
    def test_random_single_cycle(self, seed):
        rng = random.Random(seed)
        n_cohorts = rng.randint(1, 3)
        n_cqs = rng.randint(2, 6)
        flavors = [f"f{i}" for i in range(rng.randint(1, 3))]

        cq_specs = []
        for i in range(n_cqs):
            cohort = f"cohort-{rng.randrange(n_cohorts)}" if rng.random() < 0.8 else ""
            fqs = []
            for f in flavors:
                nominal = rng.choice(["2", "5", "10"])
                borrowing = rng.choice([None, "0", "5", None])
                lending = rng.choice([None, "1", None])
                fqs.append(flavor_quotas(f, cpu=(nominal, borrowing, lending)))
            cq_specs.append((f"cq{i}", cohort, fqs))

        def setup(env):
            for f in flavors:
                env.add_flavor(f)
            for name, cohort, fqs in cq_specs:
                w = ClusterQueueWrapper(name)
                if cohort:
                    w = w.cohort(cohort)
                env.add_cq(w.resource_group(*fqs).obj(), f"lq-{name}")

        wl_specs = []
        for i in range(rng.randint(3, 12)):
            cq = rng.randrange(n_cqs)
            wl_specs.append((f"w{i}", f"lq-cq{cq}", rng.randint(0, 3),
                            float(i), rng.choice(["1", "2", "4", "7", "12"])))

        def workloads():
            return [WorkloadWrapper(name).queue(q).priority(p).creation(ts)
                    .pod_set(count=1, cpu=cpu).obj()
                    for name, q, p, ts, cpu in wl_specs]

        assert_differential(setup, workloads)


class TestShardedSolve:
    def test_sharded_matches_single_device(self):
        import jax
        from kueue_tpu.parallel.mesh import make_mesh
        assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"

        def setup(env, mesh=None):
            env.add_flavor("default")
            for c in range(4):
                for i in range(2):
                    name = f"cq-{c}-{i}"
                    env.add_cq(ClusterQueueWrapper(name).cohort(f"cohort-{c}")
                               .resource_group(flavor_quotas("default", cpu="6")).obj(),
                               f"lq-{name}")

        def workloads():
            out = []
            for c in range(4):
                for i in range(2):
                    for j in range(2):
                        out.append(WorkloadWrapper(f"w-{c}-{i}-{j}")
                                   .queue(f"lq-cq-{c}-{i}").priority(j)
                                   .creation(c * 10 + i * 2 + j)
                                   .pod_set(count=1, cpu="4").obj())
            return out

        env_single = build_env(setup, solver=True)
        env_sharded = build_env(setup, solver=True)
        env_sharded.scheduler.solver.mesh = make_mesh()
        env_cpu = build_env(setup, solver=False)
        for env in (env_single, env_sharded, env_cpu):
            for w in workloads():
                env.submit(w)
            env.cycle()
        assert admitted_map(env_single) == admitted_map(env_sharded) == admitted_map(env_cpu)


class TestCohortParallelKernel:
    def test_matches_global_sequential_scan(self):
        """solve_cycle (global W-step scan), solve_cycle_cohort_parallel
        (host-gridded L-step scan) and solve_cycle_fused (single-dispatch
        device grid) must produce identical tensors."""
        import numpy as np
        import jax.numpy as jnp
        from kueue_tpu.solver.kernel import (
            max_rank_bound, solve_cycle, solve_cycle_cohort_parallel,
            solve_cycle_fused)
        from kueue_tpu.solver.synth import synth_solver_inputs

        for seed in range(6):
            topo, usage, cohort_usage, wl = synth_solver_inputs(
                num_cqs=24, num_cohorts=5, num_flavors=3, num_resources=2,
                num_workloads=64, seed=seed)

            class T:
                pass
            topo_np = T()
            for k, v in topo.items():
                setattr(topo_np, k, v)
            topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}
            args = (jnp.asarray(wl["requests"]), jnp.asarray(wl["podset_active"]),
                    jnp.asarray(wl["wl_cq"]), jnp.asarray(wl["priority"]),
                    jnp.asarray(wl["timestamp"]), jnp.asarray(wl["eligible"]),
                    jnp.asarray(wl["solvable"]))
            seq = solve_cycle(topo_dev, jnp.asarray(usage),
                              jnp.asarray(cohort_usage), *args, num_podsets=1)
            par = solve_cycle_cohort_parallel(
                topo_dev, topo_np, jnp.asarray(usage),
                jnp.asarray(cohort_usage), *args, num_podsets=1)
            fused = solve_cycle_fused(
                topo_dev, jnp.asarray(usage), jnp.asarray(cohort_usage),
                *args, num_podsets=1,
                max_rank=max_rank_bound(wl["wl_cq"], topo["cq_cohort"],
                                        topo["cohort_root"]))
            for key in ("admitted", "fit", "borrows"):
                for other in (par, fused):
                    assert np.array_equal(np.asarray(seq[key]),
                                          np.asarray(other[key])), (key, seed)
            for other in (par, fused):
                assert np.array_equal(np.asarray(seq["usage"]),
                                      np.asarray(other["usage"])), seed
                assert np.array_equal(np.asarray(seq["cohort_usage"]),
                                      np.asarray(other["cohort_usage"])), seed


class TestMixedCycleEquivalenceClass:
    """VERDICT r2 #5: pin the solver path's documented ordering deviation
    at its boundary (reference: scheduler.go:245-253).

    Scenario: cohort {cq-a, cq-b}. cq-a holds a BLOCKED high-priority
    preemptor P (preempt mode, zero targets — withinClusterQueue=Never)
    that the reference would process first (non-borrowing sorts before
    borrowing) and have reserve cq-a's unused nominal quota. cq-b holds a
    low-priority fit-mode workload F that only fits by borrowing that
    same unused quota.

    - CPU path (strict conformance): P's reservation starves F's borrow
      -> NEITHER admits this cycle.
    - Solver path (documented deviation, service.py): the device admits
      every fit-mode entry before blocked preemptors reserve, so F
      admits and P stays pending.
    """

    def _setup(self, env):
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq-a").cohort("team")
                   .preemption(within_cluster_queue=api.PREEMPTION_NEVER)
                   .resource_group(flavor_quotas("default", cpu=(10, 0)))
                   .obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("cq-b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu=10)).obj(),
                   "lq-b")

    def _drive(self, solver: bool):
        env = build_env(self._setup, solver=solver)
        # cq-a: 4 cpus admitted -> 6 unused nominal (the cohort's lendable)
        env.admit_existing(WorkloadWrapper("occupant").queue("lq-a")
                           .priority(200).pod_set(count=1, cpu=4)
                           .reserve("cq-a").obj())
        # P: preempt-mode (10 > 6 available, <= nominal, borrowingLimit 0),
        # no candidates -> blocked preemptor, reserves min(10, 10-4) = 6
        env.submit(WorkloadWrapper("preemptor").queue("lq-a").priority(100)
                   .creation(1).pod_set(count=1, cpu=10).obj())
        # F: fits only by borrowing 2 of cq-a's 6 unused
        env.submit(WorkloadWrapper("fitter").queue("lq-b").priority(0)
                   .creation(2).pod_set(count=1, cpu=12).obj())
        env.cycle()
        return admitted_map(env)

    def test_cpu_path_reserves_for_blocked_preemptor(self):
        admitted = self._drive(solver=False)
        assert "default/fitter" not in admitted
        assert "default/preemptor" not in admitted

    def test_solver_path_admits_fit_entries_first(self):
        admitted = self._drive(solver=True)
        assert "default/fitter" in admitted
        assert "default/preemptor" not in admitted


class TestDispatchGates:
    """VERDICT r2 #8: fallback boundaries of the dispatch gates.

    - solver_min_heads: cycles narrower than the head gate take the pure
      CPU path even with a solver configured (scheduler.py).
    - the preemption work gate routes small simulations to the CPU
      preemptor (no device dispatch at all when nothing fits), keyed on
      the measured sync floor; decisions are identical either way.
    """

    def _setup(self, env):
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu=4)).obj(),
                   "lq")

    def test_min_heads_gate_skips_solver(self):
        env = build_env(self._setup, solver=True)
        env.scheduler.solver_min_heads = 5  # 1 head < 5 -> CPU path
        calls = []
        orig = env.scheduler.solver.prepare
        env.scheduler.solver.prepare = lambda *a, **k: (
            calls.append(1) or orig(*a, **k))
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu=2).obj())
        env.cycle()
        assert not calls, "solver dispatched below the head gate"
        assert "default/w" in admitted_map(env)

    def test_min_heads_boundary_uses_solver(self):
        env = build_env(self._setup, solver=True)
        env.scheduler.solver_min_heads = 1  # 1 head >= 1 -> solver path
        calls = []
        orig = env.scheduler.solver.prepare
        env.scheduler.solver.prepare = lambda *a, **k: (
            calls.append(1) or orig(*a, **k))
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu=2).obj())
        env.cycle()
        assert calls, "solver not used at the head-gate boundary"
        assert "default/w" in admitted_map(env)

    def test_preempt_work_gate_routes_small_problems_to_cpu(self):
        """With a high sync floor and a 1-candidate problem, the gate
        resolves preemption on the CPU preemptor and skips the device
        dispatch entirely — without counting it as a fallback."""
        env = build_env(self._setup, solver=True)
        env.scheduler.solver_sync_floor_ms = 10_000.0  # tiny work never pays
        dispatches = []
        orig = env.scheduler.solver.solve_prepared
        env.scheduler.solver.solve_prepared = lambda *a, **k: (
            dispatches.append(1) or orig(*a, **k))
        env.admit_existing(WorkloadWrapper("victim").queue("lq").priority(0)
                           .pod_set(count=1, cpu=4).reserve("cq").obj())
        env.submit(WorkloadWrapper("preemptor").queue("lq").priority(10)
                   .pod_set(count=1, cpu=4).obj())
        env.cycle()
        assert not dispatches, "device dispatched despite the work gate"
        assert env.scheduler.preemption_fallbacks == 0
        assert "default/victim" in env.client.evicted


class TestResidentState:
    """Device-resident usage/cohort_usage across cycles: the cache journal
    reconciles it with sparse deltas; the host mirror must stay
    bit-identical to the device arrays (VERDICT r3 missing #2)."""

    @staticmethod
    def _setup(env):
        env.add_flavor("default")
        for i in range(3):
            env.add_cq(ClusterQueueWrapper(f"cq{i}").cohort("co")
                       .resource_group(flavor_quotas(
                           "default", cpu=("6", None, "4"))).obj(),
                       f"lq-cq{i}")

    def _assert_mirror_matches_device(self, solver):
        import numpy as np
        rs = solver._resident
        assert rs is not None, "residency not established"
        assert np.array_equal(np.asarray(rs.usage_dev), rs.mirror_usage)
        assert np.array_equal(np.asarray(rs.cohort_dev), rs.mirror_cohort)

    def test_mirror_tracks_device_across_cycles(self):
        env = build_env(self._setup, solver=True)
        for wave in range(3):
            for i in range(3):
                env.submit(WorkloadWrapper(f"w{wave}-{i}").queue(f"lq-cq{i}")
                           .creation(float(wave * 3 + i))
                           .pod_set(count=1, cpu="2").obj())
            env.cycle()
        assert len(env.client.applied) == 9
        self._assert_mirror_matches_device(env.scheduler.solver)

    def test_corrections_after_external_removal(self):
        """A workload finishing (cache removal) between cycles must reach
        the device as a sparse correction, and later cycles must admit
        into the freed capacity identically to the CPU path."""
        envs = [build_env(self._setup, solver=False),
                build_env(self._setup, solver=True)]
        finished = {}
        for env in envs:
            for i in range(3):
                env.submit(WorkloadWrapper(f"a{i}").queue(f"lq-cq{i}")
                           .creation(float(i)).pod_set(count=1, cpu="6").obj())
            env.cycle()
            # a0 finishes: its usage leaves the cache
            wl = env.client.applied["default/a0"]
            env.cache.delete_workload(wl)
            for i in range(3):
                env.submit(WorkloadWrapper(f"b{i}").queue(f"lq-cq{i}")
                           .creation(float(10 + i))
                           .pod_set(count=1, cpu="6").obj())
            env.cycle()
            finished[id(env)] = admitted_map(env)
        cpu, tpu = finished.values()
        assert cpu == tpu
        # b0 must have been admitted into a0's freed quota
        assert "default/b0" in cpu
        self._assert_mirror_matches_device(envs[1].scheduler.solver)

    def test_note_unapplied_reverts_device_add(self):
        """An admit failure after a device admission must revert the usage
        on both the mirror (now) and the device (next dispatch)."""
        env = build_env(self._setup, solver=True)
        fail_once = {"left": 1}
        orig_assume = env.cache.assume_workload

        def flaky_assume(wl, info=None):
            from kueue_tpu.core import workload as wlpkg
            if wlpkg.key(wl) == "default/w0" and fail_once["left"]:
                fail_once["left"] -= 1
                raise RuntimeError("injected assume failure")
            return orig_assume(wl, info=info)

        env.cache.assume_workload = flaky_assume
        for i in range(3):
            env.submit(WorkloadWrapper(f"w{i}").queue(f"lq-cq{i}")
                       .creation(float(i)).pod_set(count=1, cpu="6").obj())
        env.cycle()
        assert "default/w0" not in admitted_map(env)
        # w0 requeues; the next cycle must admit it into intact capacity
        env.cycle()
        assert "default/w0" in admitted_map(env)
        self._assert_mirror_matches_device(env.scheduler.solver)

    def test_topology_change_drops_residency(self):
        env = build_env(self._setup, solver=True)
        env.submit(WorkloadWrapper("w0").queue("lq-cq0")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        rs1 = env.scheduler.solver._resident
        assert rs1 is not None
        env.add_cq(ClusterQueueWrapper("cq-new").cohort("co")
                   .resource_group(flavor_quotas("default", cpu="6")).obj())
        env.submit(WorkloadWrapper("w1").queue("lq-cq-new")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        rs2 = env.scheduler.solver._resident
        assert rs2 is not None and rs2 is not rs1
        assert "default/w1" in admitted_map(env)
        self._assert_mirror_matches_device(env.scheduler.solver)


class TestPipelinedEquivalence:
    """Pipelined dispatch (cycle N+1 dispatched before cycle N's decisions
    are fetched) must converge to the same admitted set + usage as the
    sequential CPU scheduler; entries the device rejects fall back to a
    synchronous cycle (cooldown) for preempt-mode handling."""

    @staticmethod
    def _setup(env):
        env.add_flavor("default")
        for i in range(4):
            env.add_cq(ClusterQueueWrapper(f"cq{i}").cohort("co")
                       .resource_group(flavor_quotas("default", cpu="8")).obj(),
                       f"lq-cq{i}")

    def _run(self, solver, waves, cpu_per_wl="2", pipeline=False):
        env = build_env(self._setup, solver=solver)
        if pipeline:
            env.scheduler.pipeline_enabled = True
        n = 0
        for wave in range(waves):
            for i in range(4):
                env.submit(WorkloadWrapper(f"w{wave}-{i}").queue(f"lq-cq{i}")
                           .priority(n % 3).creation(float(n))
                           .pod_set(count=1, cpu=cpu_per_wl).obj())
                n += 1
        for _ in range(waves + 4):  # extra cycles drain the pipeline
            env.cycle()
        return env

    def test_all_fit_matches_cpu(self):
        cpu = self._run(False, waves=3)
        pipe = self._run(True, waves=3, pipeline=True)
        assert admitted_map(cpu) == admitted_map(pipe)
        for i in range(4):
            assert cpu.usage(f"cq{i}") == pipe.usage(f"cq{i}")
        solver = pipe.scheduler.solver
        assert solver._resident is not None

    def test_contention_skips_match_cpu(self):
        """Workloads oversubscribe the quota: some entries lose the
        intra-cycle race (device Phase B skip) and retry later; the final
        admitted SET must still match the CPU path (order of admission
        within the backlog may differ by the documented one-cycle shift)."""
        cpu = self._run(False, waves=4, cpu_per_wl="3")
        pipe = self._run(True, waves=4, cpu_per_wl="3", pipeline=True)
        assert set(admitted_map(cpu)) == set(admitted_map(pipe))
        for i in range(4):
            assert cpu.usage(f"cq{i}") == pipe.usage(f"cq{i}")

    def test_preempt_dominated_cycle_falls_back_to_sync(self):
        """A preempt-DOMINATED cycle (pend share > 1/4 of the batch)
        drains the pipeline and runs the synchronous mixed cycle — the
        pipelined-mixed machinery only pays off on fit-dominated
        batches. Evictions identical to the CPU path either way."""
        preemption = dict(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)

        def setup(env):
            env.add_flavor("default")
            for i in range(2):
                env.add_cq(ClusterQueueWrapper(f"cq{i}")
                           .preemption(**preemption)
                           .resource_group(flavor_quotas("default", cpu="4"))
                           .obj(), f"lq-cq{i}")

        outs = {}
        for pipeline in (False, True):
            env = build_env(setup, solver=pipeline)
            env.scheduler.pipeline_enabled = pipeline
            for i in range(2):
                env.admit_existing(
                    WorkloadWrapper(f"victim{i}").queue(f"lq-cq{i}")
                    .priority(0).pod_set(count=1, cpu="4")
                    .reserve(f"cq{i}").obj())
                env.submit(WorkloadWrapper(f"preemptor{i}")
                           .queue(f"lq-cq{i}").priority(10)
                           .creation(float(i)).pod_set(count=1, cpu="4").obj())
            for _ in range(4):
                env.cycle()
            outs[pipeline] = set(env.client.evicted)
            if pipeline:  # preempt share 100%: the gate forces sync
                assert "pipelined-preempt" not in env.scheduler.cycle_counts
        assert outs[False] == outs[True]
        assert outs[True] == {"default/victim0", "default/victim1"}


class TestBatchedPartialAdmission:
    """Batched partial admission (VERDICT r3 ask #9): all reducer probes
    for all eligible entries run as lockstep Phase A batches on the local
    CPU backend; decisions must equal the CPU scheduler's sequential
    PodSetReducer exactly (Never/Never CQs: the probe predicate is pure
    fit on both paths)."""

    @staticmethod
    def _setup(env):
        env.add_flavor("default")
        for i in range(3):
            env.add_cq(ClusterQueueWrapper(f"cq{i}")
                       .resource_group(flavor_quotas("default", cpu="6"))
                       .obj(), f"lq-cq{i}")

    def test_reduced_counts_match_cpu(self):
        def workloads():
            out = []
            for i in range(3):
                # 10 pods x 1 cpu vs quota 6 -> reduced to 6
                out.append(WorkloadWrapper(f"big{i}").queue(f"lq-cq{i}")
                           .creation(float(i))
                           .pod_set(count=10, min_count=2, cpu=1).obj())
            return out

        envs = []
        for solver in (False, True):
            env = build_env(self._setup, solver=solver)
            for w in workloads():
                env.submit(w)
            env.cycle()
            envs.append(env)
        cpu_map, dev_map = admitted_map(envs[0]), admitted_map(envs[1])
        assert cpu_map == dev_map and cpu_map
        # every workload actually got REDUCED (count 6, not 10)
        for key, psas in cpu_map.items():
            assert psas[0][1] == 6, (key, psas)

    def test_infeasible_and_mixed(self):
        """One entry reduces, one can't fit even at min_count, one fits
        outright — identical outcomes on both paths."""
        def workloads():
            return [
                WorkloadWrapper("reduce").queue("lq-cq0").creation(0.0)
                .pod_set(count=9, min_count=3, cpu=1).obj(),
                WorkloadWrapper("never").queue("lq-cq1").creation(1.0)
                .pod_set(count=20, min_count=8, cpu=1).obj(),
                WorkloadWrapper("fits").queue("lq-cq2").creation(2.0)
                .pod_set(count=4, min_count=2, cpu=1).obj(),
            ]

        envs = []
        for solver in (False, True):
            env = build_env(self._setup, solver=solver)
            for w in workloads():
                env.submit(w)
            env.cycle()
            envs.append(env)
        cpu_map, dev_map = admitted_map(envs[0]), admitted_map(envs[1])
        assert cpu_map == dev_map
        assert "default/reduce" in cpu_map and "default/fits" in cpu_map
        assert "default/never" not in cpu_map
        assert cpu_map["default/reduce"][0][1] == 6
        assert cpu_map["default/fits"][0][1] == 4


class TestResidencyRandomMultiCycle:
    """Randomized MULTI-CYCLE differential for the device-resident +
    pipelined stack: workloads arrive in waves, some admitted workloads
    complete (cache removal -> journal corrections), quotas force
    contention, and the pipelined solver must converge to the same final
    admitted set and per-CQ usage as the sequential CPU scheduler."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_waves_with_completions(self, seed):
        rng = random.Random(7000 + seed)
        n_cohorts = rng.randint(1, 3)
        n_cqs = rng.randint(3, 6)
        n_flavors = rng.randint(1, 3)
        quota = rng.choice(["4", "6", "8"])
        waves = rng.randint(3, 5)

        cq_specs = []
        for i in range(n_cqs):
            cohort = (f"co-{rng.randrange(n_cohorts)}"
                      if rng.random() < 0.7 else "")
            cq_specs.append((f"cq{i}", cohort))
        flavors = [f"f{k}" for k in range(n_flavors)]

        def setup(env):
            for f in flavors:
                env.add_flavor(f)
            for name, cohort in cq_specs:
                w = ClusterQueueWrapper(name)
                if cohort:
                    w = w.cohort(cohort)
                w = w.resource_group(*[flavor_quotas(f, cpu=quota)
                                       for f in flavors])
                env.add_cq(w.obj(), f"lq-{name}")

        plan = []  # (wave, name, cq idx, prio, cpu)
        n = 0
        for wave in range(waves):
            for _ in range(rng.randint(2, 2 * n_cqs)):
                plan.append((wave, f"w{n}", rng.randrange(n_cqs),
                             rng.randint(0, 3),
                             rng.choice(["1", "2", "3"])))
                n += 1
        # EVERY workload completes once admitted: capacity always frees
        # again, so both engines must converge to the full admitted set
        # (transient contention still forces parking/retries mid-run)
        complete_after = {p[1] for p in plan}

        all_cqs = {f"cq{i}" for i in range(n_cqs)}

        def run(pipeline):
            env = build_env(setup, solver=pipeline)
            if pipeline:
                env.scheduler.pipeline_enabled = True
            done = set(complete_after)

            def drain_completions():
                freed = False
                for key, wl in list(env.client.applied.items()):
                    if wl.metadata.name in done:
                        env.cache.delete_workload(wl)
                        done.discard(wl.metadata.name)
                        freed = True
                if freed:
                    # the workload controller's cohort flush (parked
                    # inadmissible entries retry on freed capacity)
                    env.queues.queue_inadmissible_workloads(all_cqs)

            for wave in range(waves):
                for (w_wave, name, qi, prio, cpu) in plan:
                    if w_wave != wave:
                        continue
                    env.submit(WorkloadWrapper(name).queue(f"lq-cq{qi}")
                               .priority(prio).creation(float(wave * 100))
                               .pod_set(count=1, cpu=cpu).obj())
                env.cycle()
                drain_completions()
            # settle until everything admitted (completions keep freeing
            # capacity; every workload fits a CQ alone, so both engines
            # must converge to the full set)
            for _ in range(40):
                if len(env.client.applied) >= n:
                    break
                env.cycle()
                drain_completions()
            for _ in range(3):  # drain the pipeline tail
                env.cycle()
                drain_completions()
            return env

        cpu_env = run(False)
        dev_env = run(True)
        cpu_map, dev_map = admitted_map(cpu_env), admitted_map(dev_env)
        # both engines eventually admit EVERY workload (admission ORDER
        # under completion-timing races may differ — the documented
        # pipeline deviation — so flavor choices for multi-flavor CQs can
        # legitimately differ too; the SET must not)
        assert set(cpu_map) == set(dev_map), (
            sorted(set(cpu_map) ^ set(dev_map)))
        assert len(cpu_map) == n, (len(cpu_map), n)
        # ...and every admission completed, so final usage is zero
        for name, _ in cq_specs:
            for f in flavors:
                assert cpu_env.usage(name, flavor=f) == 0, (name, f)
                assert dev_env.usage(name, flavor=f) == 0, (name, f)
        # residency stayed live and the mirror tracks the device exactly
        # (a non-empty backlog is legitimately un-dispatched state)
        rs = dev_env.scheduler.solver._resident
        assert rs is not None and rs.usage_dev is not None, \
            "residency was dropped during the run"
        if not rs.device_backlog:
            TestResidentState._assert_mirror_matches_device(
                TestResidentState(), dev_env.scheduler.solver)


class TestStarvationBound:
    """VERDICT r4 ask #7: the solver mixed-cycle deviation lets a
    sustained fit stream starve a blocked preemptor indefinitely
    (device fit admissions land before the blocked entry's
    resourcesToReserve — scheduler.go:443-462 semantics are per-cycle).
    After `strict_after_blocked_cycles` consecutive blocked cycles the
    scheduler pins the strict sequential path until the preemptor
    unblocks, so it admits exactly when the reference would."""

    def _setup(self, env):
        env.add_flavor("default")
        # reclaim != Never so the device-NoFit shortcut doesn't swallow
        # the preempt-mode nomination; the stream's priority 200 keeps
        # every candidate above the preemptor's threshold -> blocked.
        env.add_cq(ClusterQueueWrapper("cq-a").cohort("team")
                   .preemption(
                       within_cluster_queue=api.PREEMPTION_NEVER,
                       reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY)
                   .resource_group(flavor_quotas("default", cpu=(10, 0)))
                   .obj(), "lq-a")
        env.add_cq(ClusterQueueWrapper("cq-b").cohort("team")
                   .resource_group(flavor_quotas("default", cpu=10)).obj(),
                   "lq-b")

    def _drive(self, strict_after, cycles=16, with_counts=False):
        env = build_env(self._setup, solver=True)
        env.scheduler.strict_after_blocked_cycles = strict_after
        occupant = (WorkloadWrapper("occupant").queue("lq-a").priority(200)
                    .pod_set(count=1, cpu=4).reserve("cq-a").obj())
        env.admit_existing(occupant)
        # cq-b pinned at its nominal so every stream item borrows (and
        # none is a reclaim candidate at priority 200)
        env.admit_existing(WorkloadWrapper("base").queue("lq-b")
                           .priority(200).pod_set(count=1, cpu=10)
                           .reserve("cq-b").obj())
        # P wants cq-a's full nominal 10; part is lent out -> PREEMPT
        # mode, zero candidates -> blocked; the reference reserves.
        env.submit(WorkloadWrapper("preemptor").queue("lq-a").priority(100)
                   .creation(1).pod_set(count=1, cpu=10).obj())
        admitted_cycle = None
        occupant_done_at = None
        for i in range(cycles):
            # sustained overlapping stream: ~2 small borrowers
            # outstanding at any time, so free capacity never reaches
            # the preemptor's ask unless something reserves it
            prev = env.client.applied.pop(f"default/fitter{i-2}", None)
            if prev is not None:
                env.cache.delete_workload(prev)
            if i == 3:  # the occupant finishes mid-stream
                env.cache.delete_workload(occupant)
                occupant_done_at = i
            env.submit(WorkloadWrapper(f"fitter{i}").queue("lq-b")
                       .priority(200).creation(10.0 + i)
                       .pod_set(count=1, cpu=2).obj())
            env.queues.queue_inadmissible_workloads({"cq-a", "cq-b"})
            env.cycle()
            if "default/preemptor" in env.client.applied:
                admitted_cycle = i
                break
        if with_counts:
            return admitted_cycle, occupant_done_at, env.scheduler.cycle_counts
        return admitted_cycle, occupant_done_at

    def test_unbounded_deviation_starves(self):
        admitted_cycle, _ = self._drive(strict_after=0)
        assert admitted_cycle is None  # the documented worst case

    def test_strict_bound_admits_within_k(self):
        k = 3
        admitted_cycle, occupant_done_at, counts = self._drive(
            strict_after=k, with_counts=True)
        assert admitted_cycle is not None
        assert counts.get("cpu-strict", 0) > 0, counts  # bound engaged
        # blocked from cycle 0; strict mode engages after k blocked
        # cycles; one strict cycle reserves and the next admits
        assert admitted_cycle <= occupant_done_at + k + 2


class TestPipelinedMixedEquivalence:
    """Pipelined MIXED cycles (VERDICT r4 ask #4): fit admissions and
    preemption target selection ride one resident dispatch; evictions
    issue at collect time one cycle later. Over a multi-cycle contended
    stream the final admitted set, eviction set, and usage must match
    the sequential CPU scheduler (order may shift by the documented
    one-cycle lag)."""

    @staticmethod
    def _setup(env):
        env.add_flavor("default")
        # cq0/cq1 stand alone (no cohort): their preemptors can't borrow
        # their way in and must evict within-CQ victims; cq2-7 share a
        # cohort for the fit stream
        for i in range(8):
            w = ClusterQueueWrapper(f"cq{i}")
            if i >= 2:
                w = w.cohort("co")
            env.add_cq(
                w.preemption(
                    within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                .resource_group(flavor_quotas("default", cpu="8")).obj(),
                f"lq-cq{i}")

    def _run(self, pipeline):
        env = build_env(self._setup, solver=pipeline)
        env.scheduler.pipeline_enabled = pipeline
        # cq0/cq1 full of victims (the preemptors' targets); cq2-7 open
        # for the fit stream, keeping every cycle FIT-DOMINATED (pend
        # share <= 1/4) so the pipelined-mixed path engages
        for i in range(2):
            for v in range(2):
                env.admit_existing(
                    WorkloadWrapper(f"victim{i}-{v}").queue(f"lq-cq{i}")
                    .priority(0).creation(float(v))
                    .pod_set(count=1, cpu="4").reserve(f"cq{i}").obj())
        n = 0
        for wave in range(3):
            for i in range(2):
                env.submit(WorkloadWrapper(f"pre{wave}-{i}")
                           .queue(f"lq-cq{i}").priority(10)
                           .creation(100.0 + n)
                           .pod_set(count=1, cpu="4").obj())
                n += 1
            for i in range(2, 8):
                env.submit(WorkloadWrapper(f"fit{wave}-{i}")
                           .queue(f"lq-cq{i}").priority(1)
                           .creation(200.0 + n)
                           .pod_set(count=1, cpu="2").obj())
                n += 1
            for _ in range(3):
                env.cycle()
            # completions: each wave's evictions land as finished
            for key, wl in list(env.client.evicted.items()):
                env.cache.delete_workload(wl)
                env.client.evicted.pop(key)
                env.queues.queue_inadmissible_workloads(
                    {f"cq{j}" for j in range(8)})
            for _ in range(2):
                env.cycle()
        for _ in range(6):  # drain
            env.cycle()
        return env

    def test_mixed_stream_matches_cpu(self):
        cpu = self._run(False)
        pipe = self._run(True)
        assert set(admitted_map(cpu)) == set(admitted_map(pipe))
        for i in range(8):
            assert cpu.usage(f"cq{i}") == pipe.usage(f"cq{i}")
        # the pipelined path actually engaged its mixed form
        assert pipe.scheduler.cycle_counts.get("pipelined-preempt", 0) > 0, \
            pipe.scheduler.cycle_counts
        assert pipe.scheduler.preemption_fallbacks == 0


class TestPipelinedMixedRoutingSamples:
    def test_mixed_cycles_feed_the_router(self):
        """Mixed pipelined cycles must record device routing samples
        (drained admissions charged against the full cycle wall) — a
        sample-less mixed path would pin the adaptive router in
        mandatory sampling forever."""
        t = TestPipelinedMixedEquivalence()
        env = build_env(t._setup, solver=True)
        env.scheduler.pipeline_enabled = True
        env.scheduler.solver_routing = "adaptive"
        for i in range(2):
            for v in range(2):
                env.admit_existing(
                    WorkloadWrapper(f"victim{i}-{v}").queue(f"lq-cq{i}")
                    .priority(0).creation(float(v))
                    .pod_set(count=1, cpu="4").reserve(f"cq{i}").obj())
        for wave in range(3):
            for i in range(2):
                env.submit(WorkloadWrapper(f"pre{wave}-{i}")
                           .queue(f"lq-cq{i}").priority(10)
                           .creation(100.0 + wave * 8 + i)
                           .pod_set(count=1, cpu="4").obj())
            for i in range(2, 8):
                env.submit(WorkloadWrapper(f"fit{wave}-{i}")
                           .queue(f"lq-cq{i}").priority(1)
                           .creation(200.0 + wave * 8 + i)
                           .pod_set(count=1, cpu="2").obj())
            for _ in range(4):
                env.cycle()
        assert env.scheduler.cycle_counts.get("pipelined-preempt", 0) > 0
        device_samples = sum(
            len(v) for (eng, _r), v in env.scheduler._route_stats.items()
            if eng == "device")
        assert device_samples > 0, env.scheduler._route_stats


class TestPipelinedMixedRandom:
    """Randomized multi-cycle soak for pipelined MIXED cycles: two
    priority bands (victims low, preemptors high) keep the preemption
    structure deterministic while topology, quotas, counts, and arrival
    order randomize. Both engines must converge to the same admitted
    set, eviction set, and per-CQ usage."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_mixed_stream(self, seed):
        rng = random.Random(9100 + seed)
        n_pre_cqs = rng.randint(1, 2)      # stand-alone preemption CQs
        n_fit_cqs = rng.randint(4, 7)      # cohort fit-stream CQs
        quota = rng.choice([6, 8])
        victims_per_cq = rng.randint(1, 2)
        fit_waves = rng.randint(2, 3)

        def setup(env):
            env.add_flavor("default")
            for i in range(n_pre_cqs):
                env.add_cq(
                    ClusterQueueWrapper(f"p{i}")
                    .preemption(
                        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
                    .resource_group(flavor_quotas("default", cpu=quota))
                    .obj(), f"lq-p{i}")
            for i in range(n_fit_cqs):
                env.add_cq(
                    ClusterQueueWrapper(f"f{i}").cohort("co")
                    .resource_group(flavor_quotas("default", cpu=quota))
                    .obj(), f"lq-f{i}")

        victim_cpu = quota // victims_per_cq

        # one shared plan: the rng must NOT be consumed inside run(), or
        # the two engines would see different scenarios
        plan: list = []
        n = 0
        for wave in range(fit_waves):
            items = []
            for i in range(n_pre_cqs):
                items.append((f"pre{wave}-{i}", f"lq-p{i}", 10,
                              100.0 + n, quota))
                n += 1
            for i in range(n_fit_cqs):
                for _ in range(rng.randint(1, 2)):
                    items.append((f"fit{wave}-{i}-{n}", f"lq-f{i}",
                                  rng.randint(0, 3), 200.0 + n,
                                  rng.choice([1, 2])))
                    n += 1
            plan.append(items)

        def run(pipeline):
            env = build_env(setup, solver=pipeline)
            if pipeline:
                env.scheduler.pipeline_enabled = True
            processed: set = set()
            all_cqs = ({f"p{i}" for i in range(n_pre_cqs)}
                       | {f"f{i}" for i in range(n_fit_cqs)})

            def drain():
                # evicted victims finish AND every admitted workload
                # completes once: capacity always frees again, so both
                # engines must converge to the full admitted set
                freed = False
                for key, wl in list(env.client.evicted.items()):
                    if key not in processed:
                        processed.add(key)
                        env.cache.delete_workload(wl)
                        freed = True
                for key, wl in list(env.client.applied.items()):
                    if key not in processed:
                        processed.add(key)
                        env.cache.delete_workload(wl)
                        freed = True
                if freed:
                    env.queues.queue_inadmissible_workloads(all_cqs)

            for i in range(n_pre_cqs):
                for v in range(victims_per_cq):
                    env.admit_existing(
                        WorkloadWrapper(f"victim{i}-{v}").queue(f"lq-p{i}")
                        .priority(0).creation(float(v))
                        .pod_set(count=1, cpu=victim_cpu)
                        .reserve(f"p{i}").obj())
            for wave in range(fit_waves):
                for (name, lq, prio, ts, cpu) in plan[wave]:
                    env.submit(WorkloadWrapper(name).queue(lq)
                               .priority(prio).creation(ts)
                               .pod_set(count=1, cpu=cpu).obj())
                for _ in range(3):
                    env.cycle()
                drain()
                for _ in range(2):
                    env.cycle()
            for _ in range(12):  # settle: completions keep freeing
                env.cycle()
                drain()
            return env

        cpu_env = run(False)
        dev_env = run(True)
        assert set(admitted_map(cpu_env)) == set(admitted_map(dev_env))
        assert set(cpu_env.client.evicted) == set(dev_env.client.evicted)
        for i in range(n_pre_cqs):
            assert cpu_env.usage(f"p{i}") == dev_env.usage(f"p{i}")
        for i in range(n_fit_cqs):
            assert cpu_env.usage(f"f{i}") == dev_env.usage(f"f{i}")
        assert dev_env.scheduler.preemption_fallbacks == 0
