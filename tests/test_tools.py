"""Visibility API, debugger, kueuectl CLI, importer tests
(reference: pkg/visibility, pkg/debugger, cmd/kueuectl, cmd/importer)."""

import io
import json
import urllib.request

import pytest

from kueue_tpu.api import corev1, kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec
from kueue_tpu.api.meta import FakeClock, ObjectMeta
from kueue_tpu.cli import Kueuectl, main as cli_main
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.debugger import Dumper
from kueue_tpu.importer import Importer, MappingRule
from kueue_tpu.manager import KueueManager
from kueue_tpu.visibility import VisibilityAPI, VisibilityServer

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


@pytest.fixture
def clock():
    return FakeClock(1000.0)


@pytest.fixture
def mgr(clock):
    m = KueueManager(clock=clock)
    m.store.create(make_flavor("default"))
    m.store.create(ClusterQueueWrapper("cq").resource_group(
        flavor_quotas("default", cpu=1)).obj())
    m.store.create(make_local_queue("lq", "default", "cq"))
    m.run_until_idle()
    return m


def submit_n(mgr, n, prefix="w", prio=0):
    for i in range(n):
        mgr.store.create(WorkloadWrapper(f"{prefix}{i}").queue("lq")
                         .priority(prio).creation(100 + i)
                         .request("cpu", "1").obj())


class TestVisibility:
    def test_positions_and_pagination(self, mgr):
        submit_n(mgr, 5)
        mgr.schedule_until_settled()   # w0 admits; w1..w4 pending
        vis = VisibilityAPI(mgr.queues)
        summary = vis.pending_workloads_cq("cq")
        names = [pw.name for pw in summary.items]
        assert names == ["w1", "w2", "w3", "w4"]
        assert [pw.position_in_cluster_queue for pw in summary.items] == [0, 1, 2, 3]
        page = vis.pending_workloads_cq("cq", limit=2, offset=1)
        assert [pw.name for pw in page.items] == ["w2", "w3"]

    def test_priority_orders_view(self, mgr):
        # fill the queue first so nothing admits, then add a high-priority
        # workload: it must appear at the head of the pending view
        submit_n(mgr, 2, prefix="low", prio=0)
        mgr.schedule_until_settled()   # low0 admits (1-cpu quota)
        submit_n(mgr, 1, prefix="high", prio=100)
        mgr.run_until_idle()
        vis = VisibilityAPI(mgr.queues)
        names = [pw.name for pw in vis.pending_workloads_cq("cq").items]
        assert names == ["high0", "low1"]

    def test_local_queue_view(self, mgr):
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        vis = VisibilityAPI(mgr.queues)
        summary = vis.pending_workloads_lq("default", "lq")
        assert [pw.position_in_local_queue for pw in summary.items] == [0, 1]

    def test_local_queue_projection_and_pagination(self, mgr):
        # A second LQ on the same CQ: the LQ view must project without
        # materializing the other LQ's entries, and offset/limit apply
        # to LQ positions (not CQ positions).
        mgr.store.create(make_local_queue("lq2", "default", "cq"))
        mgr.run_until_idle()
        for i in range(4):
            mgr.store.create(WorkloadWrapper(f"a{i}").queue("lq")
                             .creation(200 + 2 * i)
                             .request("cpu", "2").obj())
            mgr.store.create(WorkloadWrapper(f"b{i}").queue("lq2")
                             .creation(201 + 2 * i)
                             .request("cpu", "2").obj())
        mgr.schedule_until_settled()   # nothing admits: 2-cpu vs 1-cpu quota
        vis = VisibilityAPI(mgr.queues)
        full = vis.pending_workloads_lq("default", "lq2")
        assert [pw.name for pw in full.items] == ["b0", "b1", "b2", "b3"]
        assert [pw.position_in_local_queue for pw in full.items] == [0, 1, 2, 3]
        # CQ positions are global (interleaved with lq's entries)
        cq_names = [pw.name for pw in
                    vis.pending_workloads_cq("cq").items]
        for pw in full.items:
            assert cq_names[pw.position_in_cluster_queue] == pw.name
        page = vis.pending_workloads_lq("default", "lq2", limit=2, offset=1)
        assert [pw.name for pw in page.items] == ["b1", "b2"]
        assert [pw.position_in_local_queue for pw in page.items] == [1, 2]
        # offset past the end / unknown LQ: empty, not an error
        assert vis.pending_workloads_lq("default", "lq2",
                                        offset=99).items == []
        assert vis.pending_workloads_lq("default", "nope").items == []

    def test_http_server(self, mgr):
        submit_n(mgr, 3)
        mgr.schedule_until_settled()
        server = VisibilityServer(VisibilityAPI(mgr.queues))
        port = server.start()
        try:
            url = (f"http://127.0.0.1:{port}/apis/visibility.kueue.x-k8s.io/"
                   f"v1alpha1/clusterqueues/cq/pendingworkloads?limit=1")
            body = json.loads(urllib.request.urlopen(url, timeout=5).read())
            assert len(body["items"]) == 1
            assert body["items"][0]["name"] == "w1"
        finally:
            server.stop()


def _get(port, path):
    """(status, body bytes) for a GET against the local server."""
    import urllib.error
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


class TestVisibilityHTTP:
    """The HTTP handler's edges + the /debug operator endpoints."""

    PW = "/apis/visibility.kueue.x-k8s.io/v1alpha1/clusterqueues/cq/pendingworkloads"

    @pytest.fixture
    def server(self, mgr):
        submit_n(mgr, 4)
        mgr.schedule_until_settled()   # w0 admits; w1..w3 pending
        server = mgr.serve_visibility()
        yield server
        server.stop()

    def test_pagination_edges(self, server):
        port = server.port
        status, body = _get(port, self.PW + "?offset=50")
        assert status == 200 and json.loads(body)["items"] == []
        status, body = _get(port, self.PW + "?limit=0")
        assert status == 200 and json.loads(body)["items"] == []
        status, body = _get(port, self.PW + "?limit=2&offset=1")
        assert status == 200
        assert [i["name"] for i in json.loads(body)["items"]] == ["w2", "w3"]

    def test_bad_params_400(self, server):
        assert _get(server.port, self.PW + "?limit=nope")[0] == 400
        assert _get(server.port, self.PW + "?offset=-1")[0] == 400
        assert _get(server.port, "/debug/cycles?slowest=abc")[0] == 400
        assert _get(server.port, "/debug/cycles?n=-2")[0] == 400

    def test_unknown_paths_404(self, server):
        assert _get(server.port, "/nope")[0] == 404
        assert _get(server.port, "/apis/visibility.kueue.x-k8s.io")[0] == 404
        assert _get(server.port, "/debug/nope")[0] == 404

    def test_metrics_endpoint(self, server):
        status, body = _get(server.port, "/metrics")
        assert status == 200
        text = body.decode()
        assert "kueue_admission_attempts_total" in text
        assert "kueue_cycle_phase_seconds" in text
        assert "kueue_solver_breaker_state" in text

    def test_debug_cycles(self, server, mgr):
        status, body = _get(server.port, "/debug/cycles")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] and payload["cycles"]
        cyc = payload["cycles"][-1]
        assert cyc["route"] and cyc["spans"]
        names = {s["name"] for s in cyc["spans"]}
        assert "snapshot" in names and "apply" in names
        # ?slowest=K returns K cycles, slowest first
        status, body = _get(server.port, "/debug/cycles?slowest=2")
        payload = json.loads(body)
        durs = [c["duration_ms"] for c in payload["cycles"]]
        assert len(durs) <= 2 and durs == sorted(durs, reverse=True)
        # reconcile with the histogram totals (acceptance criterion)
        all_traces = json.loads(_get(server.port,
                                     "/debug/cycles")[1])["cycles"]
        span_apply_ms = sum(s["dur_ms"] for c in all_traces
                            for s in c["spans"] if s["name"] == "apply")
        hist_apply_ms = sum(
            mgr.metrics.cycle_phase_seconds.sum(phase="apply", route=r)
            for r in ("cpu-forced", "cpu", "device")) * 1e3
        assert span_apply_ms == pytest.approx(hist_apply_ms, abs=0.01)

    def test_debug_breaker_router_arena(self, server):
        status, body = _get(server.port, "/debug/breaker")
        assert status == 200
        b = json.loads(body)
        assert b["state"] == "closed" and b["route"] == "device"
        assert "consecutive_faults" in b and "next_probe_in_s" in b
        status, body = _get(server.port, "/debug/router")
        assert status == 200
        assert "regimes" in json.loads(body)
        status, body = _get(server.port, "/debug/arena")
        assert status == 200
        assert json.loads(body)["bound"] is False  # no solver configured

    def test_debug_degrade(self, server):
        status, body = _get(server.port, "/debug/degrade")
        assert status == 200
        d = json.loads(body)
        assert d["state"] == "normal" and d["enabled"] is False
        assert d["cycles_shed"] == 0
        assert "shed_heads_requeued_total" in d
        assert "preempt_plans_deferred_total" in d

    def test_debug_404_without_wiring(self, mgr):
        # A bare VisibilityServer (no debug surface) keeps the old
        # behavior: /metrics and /debug/* are unknown paths.
        server = VisibilityServer(VisibilityAPI(mgr.queues))
        port = server.start()
        try:
            assert _get(port, "/metrics")[0] == 404
            assert _get(port, "/debug/cycles")[0] == 404
        finally:
            server.stop()

    def test_debug_journeys_edges(self, server, mgr):
        """ISSUE 14 satellite: /debug/journeys honors the
        DebugEndpoints contract — 400 on bad ?n=, 404 on an unknown
        workload, generation stamp on every payload."""
        status, body = _get(server.port, "/debug/journeys")
        assert status == 200
        payload = json.loads(body)
        assert payload["attached"] is True
        assert "generation" in payload           # staleness stamp
        assert payload["completed"] >= 1         # w0 admitted
        assert payload["slowest"], payload
        # every exemplar span is causally stamped
        for j in payload["slowest"]:
            for s in j["spans"]:
                assert isinstance(s["cycle"], int)
                assert s["generation"]
        # bad params -> 400
        assert _get(server.port, "/debug/journeys?n=abc")[0] == 400
        assert _get(server.port, "/debug/journeys?n=-1")[0] == 400
        # n=0 means ZERO exemplars, not all
        status, body = _get(server.port, "/debug/journeys?n=0")
        assert status == 200
        zero = json.loads(body)
        assert zero["slowest"] == [] and zero["violations"] == []
        # unknown workload -> 404
        assert _get(server.port, "/debug/journeys?wl=nope")[0] == 404
        # point query (full key AND bare name) -> the span timeline
        for ref in ("default/w1", "w1"):
            status, body = _get(server.port, f"/debug/journeys?wl={ref}")
            assert status == 200, ref
            j = json.loads(body)["journey"]
            assert j["workload"] == "default/w1"
            assert j["spans"][0]["kind"] == "queued"

    def test_debug_aging(self, server):
        status, body = _get(server.port, "/debug/aging")
        assert status == 200
        payload = json.loads(body)
        assert payload["attached"] is True
        assert "generation" in payload
        assert "live_handouts" in payload["monitors"]
        assert payload["samples_taken"] > 0
        assert payload["failing"] == []

    def test_trace_dump_journey(self, server, capsys):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "trace_dump", os.path.join(os.path.dirname(__file__),
                                       "..", "tools", "trace_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        url = f"http://127.0.0.1:{server.port}"
        assert mod.main([url, "--journey", "default/w0"]) == 0
        out = capsys.readouterr().out
        assert "journey default/w0" in out
        assert "queued" in out and "cycle=" in out and "gen=" in out

    def test_trace_dump_tool(self, server, tmp_path, capsys):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "trace_dump", os.path.join(os.path.dirname(__file__),
                                       "..", "tools", "trace_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([f"http://127.0.0.1:{server.port}"]) == 0
        out = capsys.readouterr().out
        assert "flight recorder:" in out
        assert "route=" in out and "snapshot" in out
        # file + --slowest paths
        payload = mod.fetch(f"http://127.0.0.1:{server.port}", slowest=1)
        assert len(payload["cycles"]) <= 1
        f = tmp_path / "traces.json"
        f.write_text(json.dumps(payload))
        assert mod.main([str(f)]) == 0


class TestTransportProbe:
    def test_probe_smoke_one_round_trip_per_cycle(self, capsys):
        """Tier-1 smoke for tools/transport_probe.py (chaos_run CLI
        contract): a tiny run must render the per-cycle transport
        table, report a parseable verdict, and find zero round-trip
        violations — the steady-state one-dispatch/one-collect
        contract."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "transport_probe",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "transport_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["3", "4"]) == 0
        captured = capsys.readouterr()
        assert "fetch_B" in captured.err      # the operator table
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["round_trip_violations"] == []
        assert verdict["dispatch_collect_balanced"] is True
        assert verdict["device_cycles"] >= 1
        assert verdict["fetch_bytes_per_cycle_p50"] is not None
        # decision-sized: the steady-state fetch is tens of bytes at
        # this shape, nowhere near the dense [W,...] tensors
        assert verdict["fetch_bytes_per_cycle_p50"] < 1000


class TestMeshProbe:
    def test_probe_smoke_identity_and_balance(self):
        """Tier-1 smoke for tools/mesh_probe.py (chaos_run CLI
        contract) AND the ISSUE 13 acceptance gate: ≥2 simulated hosts
        (forced host-platform device count — hence a subprocess; the
        flag must land before jax initializes) must produce
        bit-identical admitted sets vs the single-chip fused oracle on
        randomized traffic, with planner imbalance within the 1.5x
        gate. Exit status IS the verdict."""
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "mesh_probe.py"),
             "--hosts", "1,2", "--devices", "2", "--cqs-per-host", "16",
             "--wl-per-host", "32", "--cycles", "2", "--check-identity",
             "--json"],
            cwd=repo, capture_output=True, text=True, timeout=420,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr[-2000:]
        verdict = json.loads(out.stdout.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["identity_failures"] == []
        assert verdict["max_imbalance"] <= 1.5
        # conftest (or the caller's env) may force more devices than
        # the probe asked for — the probe uses the first N it needs
        assert verdict["total_devices"] >= 2
        rows = {r["hosts"]: r for r in verdict["rows"]}
        assert rows[2]["devices"] == 2 and not rows[2].get("skipped")
        # the weak-scaling curve is reported either way (judged only on
        # real multi-host devices — bench.multihost_scaling refuses and
        # records witness debt elsewhere)
        assert verdict["weak_scaling"] is not None


class TestVisibilityQueryPlaneHTTP:
    """The snapshot-backed read plane's HTTP behavior (ISSUE 12):
    stamped responses, warming 503s, the workload status route, and
    the read-side saturation metrics."""

    PW = ("/apis/visibility.kueue.x-k8s.io/v1alpha1/clusterqueues/cq/"
          "pendingworkloads")

    def test_responses_are_generation_stamped(self, mgr):
        submit_n(mgr, 4)
        mgr.schedule_until_settled()
        server = mgr.serve_visibility()
        try:
            status, body = _get(server.port, self.PW)
            assert status == 200
            payload = json.loads(body)
            assert [i["name"] for i in payload["items"]] == \
                ["w1", "w2", "w3"]
            # the staleness stamp (ISSUE 12): token + cycle + age
            assert payload["generation"] == \
                list(mgr.cache.generation_token())
            assert payload["cycle"] > 0 and payload["age_s"] >= 0
        finally:
            server.stop()

    def test_warming_returns_503_with_retry_after(self, mgr):
        # No admission cycle has sealed yet: the plane must answer 503
        # + Retry-After instead of blocking or serving unstamped data.
        server = mgr.serve_visibility()
        try:
            import urllib.error
            import urllib.request
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}{self.PW}", timeout=5)
                raise AssertionError("expected 503 while warming")
            except urllib.error.HTTPError as err:
                assert err.code == 503
                assert err.headers["Retry-After"] == "1"
            # one sealed cycle later the same route serves
            submit_n(mgr, 2)
            mgr.schedule_until_settled()
            assert _get(server.port, self.PW)[0] == 200
        finally:
            server.stop()

    def test_workload_status_route(self, mgr):
        submit_n(mgr, 3)
        mgr.schedule_until_settled()   # w0 admits; w1/w2 pending
        server = mgr.serve_visibility()
        try:
            base = "/apis/visibility.kueue.x-k8s.io/v1alpha1/namespaces"
            status, body = _get(server.port,
                                base + "/default/workloads/w1")
            assert status == 200
            st = json.loads(body)
            assert st["found"] and st["status"] == "pending"
            assert st["position_in_cluster_queue"] == 0
            assert st["cluster_queue"] == "cq"
            assert st["generation"] == list(mgr.cache.generation_token())
            status, body = _get(server.port,
                                base + "/default/workloads/w0")
            st = json.loads(body)
            assert st["found"] and st["status"] == "admitted"
            status, body = _get(server.port,
                                base + "/default/workloads/nope")
            assert json.loads(body)["found"] is False
        finally:
            server.stop()

    def test_read_side_metrics_feed_the_registry(self, mgr):
        submit_n(mgr, 2)
        mgr.schedule_until_settled()
        server = mgr.serve_visibility()
        try:
            _get(server.port, self.PW)
            _get(server.port, "/nope")            # 404s count too
            _get(server.port, self.PW + "?limit=bad")  # and 400s
            reqs = mgr.metrics.visibility_requests_total
            assert reqs.value(route="cq_pending", code="200") == 1
            assert reqs.value(route="unknown", code="404") == 1
            assert reqs.value(route="cq_pending", code="400") == 1
            assert mgr.metrics.visibility_request_seconds.count(
                route="cq_pending") == 2
            assert mgr.metrics.visibility_inflight_reads.value() == 0
            # the exposition carries the new families
            status, body = _get(server.port, "/metrics")
            text = body.decode()
            assert "kueue_visibility_requests_total" in text
            assert "kueue_visibility_snapshot_age_seconds" in text
        finally:
            server.stop()

    def test_debug_queryplane_endpoint(self, mgr):
        submit_n(mgr, 2)
        mgr.schedule_until_settled()
        server = mgr.serve_visibility()
        try:
            status, body = _get(server.port, "/debug/queryplane")
            assert status == 200
            st = json.loads(body)
            assert st["attached"] and not st["warming"]
            assert st["cycles_published"] > 0
            assert st["token_lag"] == 0
            assert st["holds_snapshot_handout"] is True
            # every /debug payload reports the token it rendered under
            status, body = _get(server.port, "/debug/breaker")
            assert json.loads(body)["generation"] == \
                list(mgr.cache.generation_token())
        finally:
            server.stop()

    def test_bare_server_keeps_live_reads(self, mgr):
        # no query plane wired: the live path still serves (no stamp,
        # no 503) — the conformance fallback
        submit_n(mgr, 2)
        mgr.schedule_until_settled()
        server = VisibilityServer(VisibilityAPI(mgr.queues))
        port = server.start()
        try:
            status, body = _get(port, self.PW)
            assert status == 200
            payload = json.loads(body)
            assert "generation" not in payload and payload["items"]
        finally:
            server.stop()


class TestVisibilityProbe:
    def test_probe_smoke_stamped_reads_no_leaks(self, capsys):
        """Tier-1 smoke for tools/visibility_probe.py (chaos_run CLI
        contract): a tiny run must render the operator table, report a
        parseable verdict, and find zero unstamped responses, bounded
        token lag, and zero leaked snapshot handouts."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "visibility_probe",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "visibility_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["3", "4"]) == 0
        captured = capsys.readouterr()
        assert "token_lag" in captured.err      # the operator table
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["errors"] == 0
        assert verdict["unstamped"] == 0
        assert verdict["max_token_lag"] <= 1
        assert verdict["cycles_published"] > 0
        assert verdict["live_handouts_after_shutdown"] == 0


class TestFailoverProbe:
    def test_probe_smoke_bounded_lag_fencing_holds(self, capsys):
        """Tier-1 smoke for tools/failover_probe.py (chaos_run CLI
        contract): a tiny run must render the replication table,
        report a parseable verdict, and find zero unbounded-lag polls,
        every deposed-leader write fenced, zero deposed admissions,
        and a promoted replica admitting within the cycle bound."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "failover_probe",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "failover_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["3", "4"]) == 0
        captured = capsys.readouterr()
        assert "lag_pre" in captured.err        # the operator table
        assert "promotion:" in captured.err
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["unbounded_lag_polls"] == 0
        assert verdict["leaked_writes"] == 0
        assert verdict["fenced_writes"] == 2
        assert verdict["deposed_admissions"] == 0
        assert verdict["fencing_epoch"] == 2
        assert verdict["cycles_to_first_admission"] <= 3
        assert verdict["usage_consistent"] is True
        assert verdict["live_handouts_after_shutdown"] == 0


class TestShardProbe:
    def test_probe_smoke_exactly_once_fenced_bounded_resume(self, capsys):
        """Tier-1 smoke for tools/shard_probe.py (chaos_run CLI
        contract): a tiny sharded run must render the per-wave table,
        report a parseable verdict, and find exactly-once admission at
        every wave, the zombie shard's post-promotion write fenced, a
        bounded promote-to-resume lag, a clean rebalance handoff, and
        zero leaked snapshot handouts."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "shard_probe",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "shard_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["3", "2", "4"]) == 0
        captured = capsys.readouterr()
        assert "per-shard" in captured.err      # the operator table
        assert "rebalance:" in captured.err
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["consistency_failures"] == 0
        assert verdict["dead_shard_admissions"] == 0
        assert verdict["survivor_admitted_during_outage"] > 0
        assert verdict["leaked_writes"] == 0
        assert verdict["fenced_writes"] == 1
        assert verdict["cycles_to_resume"] <= mod.MAX_CYCLES_TO_RESUME
        assert verdict["rebalance_old_owner_admitted"] == 0
        assert verdict["final_exactly_once"] is True
        assert verdict["live_handouts_after_shutdown"] == 0


class TestJourneyProbe:
    def test_probe_smoke_complete_timelines_no_leaks(self, capsys):
        """Tier-1 smoke for tools/journey_probe.py (chaos_run CLI
        contract): a tiny run must render the per-class TTA table +
        slowest-exemplar timeline + aging verdicts, report a parseable
        verdict, and find zero ledger leaks, zero unstamped spans, and
        a complete slowest timeline."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "journey_probe",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "journey_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["2", "3"]) == 0
        captured = capsys.readouterr()
        assert "time-to-admission" in captured.err   # the operator table
        assert "aging verdicts" in captured.err
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["retained_after_shutdown"] == 0
        assert verdict["unstamped_spans"] == 0
        assert verdict["timeline_ok"] is True
        assert verdict["journeys"]["completed"] > 0
        assert verdict["aging_failing"] == []


class TestSoakRun:
    @pytest.fixture
    def mod(self):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "soak_run", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "soak_run.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_smoke_composed_soak_green(self, mod, capsys):
        """Tier-1 smoke for tools/soak_run.py (chaos_run CLI contract):
        the smoke-scale composed soak — all six phases, crash AND
        failover included — must pass the soak gate, print the result
        JSON line to stderr and a parseable verdict to stdout."""
        assert mod.main(["--seed", "0", "--scale", "smoke"]) == 0
        captured = capsys.readouterr()
        json.loads(captured.err.strip().splitlines()[-1])  # result line
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["tool"] == "soak_run"
        assert verdict["ok"] is True
        assert verdict["violations"] == []
        assert verdict["restarts"] >= 1 and verdict["promotions"] >= 1
        assert verdict["phase_transitions"] >= 4
        assert verdict["aging_ok"] is True

    def test_shapes_mode_prints_ladder_feed(self, mod, capsys):
        """--shapes is pure shape arithmetic (no soak runs): the
        warm-ladder feed must be parseable and carry the (B, rank)
        bucket keys plus the current ladder's own rungs."""
        assert mod.main(["--shapes", "--seed", "1",
                         "--samples", "8"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["samples"] == 8
        assert report["keys"] and all("x" in k for k in report["keys"])
        assert report["ladder_keys"]
        assert set(report["suggested_rungs"]) == set(report["off_ladder"])


class TestDumper:
    def test_dump_contains_state(self, mgr):
        submit_n(mgr, 2)
        mgr.schedule_until_settled()
        buf = io.StringIO()
        Dumper(mgr.cache, mgr.queues, out=buf).write()
        text = buf.getvalue()
        assert "cq cq" in text
        assert "workload default/w0" in text
        assert "pending default/w1" in text


class TestKueuectl:
    def test_create_list_stop_resume(self, mgr):
        out = io.StringIO()
        ctl = Kueuectl(mgr, out=out)
        ctl.create_resource_flavor("gpu")
        ctl.create_cluster_queue("cq2", nominal_quota={"cpu": 8000}, flavor="gpu")
        ctl.create_local_queue("lq2", "default", "cq2")
        mgr.run_until_idle()
        cqs = ctl.list_cluster_queues()
        assert {c.metadata.name for c in cqs} == {"cq", "cq2"}

        submit_n(mgr, 1)
        mgr.schedule_until_settled()
        assert wlpkg.is_admitted(mgr.store.get("Workload", "default", "w0"))
        ctl.stop_workload("default", "w0")
        mgr.run_until_idle()
        assert not mgr.store.get("Workload", "default", "w0").spec.active
        ctl.resume_workload("default", "w0")
        mgr.run_until_idle()
        assert mgr.store.get("Workload", "default", "w0").spec.active

        ctl.stop_cluster_queue("cq")
        mgr.run_until_idle()
        assert mgr.store.get("ClusterQueue", "", "cq").spec.stop_policy == \
            api.HOLD_AND_DRAIN
        ctl.resume_cluster_queue("cq")
        mgr.run_until_idle()
        assert mgr.store.get("ClusterQueue", "", "cq").spec.stop_policy == \
            api.STOP_POLICY_NONE

    def test_argparse_entry(self, mgr, capsys):
        assert cli_main(["version"], manager=mgr) == 0
        assert cli_main(["create", "resourceflavor", "cli-flavor"],
                        manager=mgr) == 0
        assert mgr.store.try_get("ResourceFlavor", "", "cli-flavor") is not None
        assert cli_main(["list", "workload"], manager=mgr) == 0

    def test_passthrough_get_describe(self, mgr):
        """Pass-through verbs resolve aliases and cluster scope
        (reference: app/passthrough/passthrough.go:33-39)."""
        out = io.StringIO()
        ctl = Kueuectl(mgr, out=out)
        submit_n(mgr, 1)
        mgr.schedule_until_settled()
        data = ctl.get("cq", "cq")
        assert data["metadata"]["name"] == "cq"
        assert data["spec"]["resource_groups"]
        spec = ctl.describe("workload", "w0", namespace="default")
        assert spec["queue_name"] == "lq"
        assert "Condition:\tQuotaReserved=True" in out.getvalue()

    def test_passthrough_patch_edit_delete(self, mgr):
        out = io.StringIO()
        ctl = Kueuectl(mgr, out=out)
        submit_n(mgr, 1)
        mgr.schedule_until_settled()
        # patch: deactivate the workload via a JSON merge patch
        ctl.patch("wl", "w0", '{"spec": {"active": false}}')
        assert not mgr.store.get("Workload", "default", "w0").spec.active
        # edit: merge patch from a stream (non-interactive kubectl edit)
        ctl.edit("wl", "w0", stream=io.StringIO('{"spec": {"active": true}}'))
        assert mgr.store.get("Workload", "default", "w0").spec.active
        # delete
        ctl.delete("workload", "w0", namespace="default")
        mgr.run_until_idle()
        assert mgr.store.try_get("Workload", "default", "w0") is None

    def test_passthrough_cli_entry(self, mgr, capsys):
        submit_n(mgr, 1)
        mgr.schedule_until_settled()
        assert cli_main(["get", "wl", "w0"], manager=mgr) == 0
        assert cli_main(["describe", "cq", "cq"], manager=mgr) == 0
        assert cli_main(["patch", "wl", "w0", "-p",
                         '{"spec": {"active": false}}'], manager=mgr) == 0
        assert not mgr.store.get("Workload", "default", "w0").spec.active
        assert cli_main(["get", "wl", "missing"], manager=mgr) == 1

    def test_list_pods_for(self, mgr):
        from kueue_tpu.api import corev1
        from kueue_tpu.api.meta import OwnerReference
        out = io.StringIO()
        ctl = Kueuectl(mgr, out=out)
        for i in range(2):
            pod = corev1.Pod(metadata=ObjectMeta(
                name=f"j-pod-{i}", namespace="default",
                owner_references=[OwnerReference(kind="Job", name="my-job",
                                                 uid="j1")]))
            mgr.store.create(pod)
        for i in range(2):
            pod = corev1.Pod(metadata=ObjectMeta(
                name=f"g-pod-{i}", namespace="default",
                labels={"kueue.x-k8s.io/pod-group-name": "grp"}))
            mgr.store.create(pod)
        pods = ctl.list_pods_for("job/my-job")
        assert {p.metadata.name for p in pods} == {"j-pod-0", "j-pod-1"}
        pods = ctl.list_pods_for("pod/g-pod-0")
        assert {p.metadata.name for p in pods} == {"g-pod-0", "g-pod-1"}
        assert cli_main(["list", "pods", "--for", "job/my-job"],
                        manager=mgr) == 0


class TestImporter:
    def make_running_pod(self, name, namespace="default", cpu=500, labels=None):
        pod = corev1.Pod(metadata=ObjectMeta(
            name=name, namespace=namespace, labels=dict(labels or {})))
        pod.spec = PodSpec(containers=[Container(name="c",
                                                 requests={"cpu": cpu})])
        pod.status.phase = corev1.POD_RUNNING
        return pod

    def test_check_rejects_missing_queue(self, mgr):
        mgr.store.create(self.make_running_pod("p1"))
        imp = Importer(mgr, [MappingRule(namespace="default", queue_name="nope")])
        result = imp.check()
        assert result.errors and "not found" in result.errors[0]

    def test_import_creates_admitted_workloads(self, mgr):
        mgr.store.create(self.make_running_pod("p1"))
        mgr.store.create(self.make_running_pod("p2"))
        # a pod outside the mapping is ignored
        mgr.store.create(self.make_running_pod("other", namespace="kube-system"))
        imp = Importer(mgr, [MappingRule(namespace="default", queue_name="lq")])
        result = imp.import_pods()
        assert result.imported == 2 and not result.errors
        mgr.run_until_idle()
        wl = mgr.store.get("Workload", "default", "pod-p1")
        assert wlpkg.is_admitted(wl)
        # the cache accounts for the imported usage: 2x500m of the 1-cpu
        # quota; a new 1-cpu workload no longer fits
        mgr.store.create(WorkloadWrapper("newbie").queue("lq")
                         .request("cpu", "1").obj())
        mgr.schedule_until_settled()
        assert not wlpkg.has_quota_reservation(
            mgr.store.get("Workload", "default", "newbie"))

    def test_label_scoped_rule(self, mgr):
        mgr.store.create(self.make_running_pod("tagged", labels={"team": "a"}))
        mgr.store.create(self.make_running_pod("untagged"))
        imp = Importer(mgr, [MappingRule(namespace="default", queue_name="lq",
                                         match_labels={"team": "a"})])
        result = imp.import_pods()
        assert result.imported == 1
        assert mgr.store.try_get("Workload", "default", "pod-tagged") is not None
        assert mgr.store.try_get("Workload", "default", "pod-untagged") is None


class TestVLog:
    def test_cycle_logging_levels(self, caplog):
        import logging
        from kueue_tpu.utils import vlog
        from tests.test_scheduler import simple_env
        from tests.wrappers import WorkloadWrapper
        vlog.set_verbosity(6)
        try:
            env = simple_env()
            env.submit(WorkloadWrapper("w").queue("lq")
                       .pod_set(count=1, cpu="2").obj())
            with caplog.at_level(logging.DEBUG, logger="kueue_tpu"):
                env.cycle()
        finally:
            vlog.set_verbosity(0)
        text = caplog.text
        assert "cycle" in text and "admitted=1" in text          # V2
        assert "attempt" in text and "workload=default/w" in text  # V5
        assert "snapshot.clusterQueue" in text and "name=cq" in text  # V6

    def test_disabled_by_default(self, caplog):
        import logging
        from tests.test_scheduler import simple_env
        from tests.wrappers import WorkloadWrapper
        env = simple_env()
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        with caplog.at_level(logging.DEBUG, logger="kueue_tpu"):
            env.cycle()
        assert "snapshot.clusterQueue" not in caplog.text
        assert "attempt" not in caplog.text
