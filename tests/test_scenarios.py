"""Production-realism scenario suite (sim/scenarios.py, sim/traces.py,
perf.checker SLO layer; catalog in sim/SCENARIOS.md).

Tier-1 runs every scenario at ``smoke`` scale — seeded, virtual-time,
each well under a second — plus the trace-generator determinism
contract, the SLOSpec gate units, and the bounded EventRecorder ring.
The ``slow`` sweep re-runs the catalog at ``full`` scale (the bench
``scenario_slo`` row independently pins the two SURVEY §5 failure
scenarios every round).
"""

import importlib.util
import json
import os

import pytest

from kueue_tpu.api.meta import ObjectMeta
from kueue_tpu.perf.checker import SLOSpec, check_slo, refuse_cross_backend
from kueue_tpu.sim.runtime import EventRecorder
from kueue_tpu.sim.scenarios import (ScenarioResult, list_scenarios,
                                     run_scenario)
from kueue_tpu.sim.traces import (TraceArrival, burst_trace, diurnal_trace,
                                  steady_trace, storm_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# traces: seeded determinism and shape
# ----------------------------------------------------------------------

class TestTraces:
    GENERATORS = [
        lambda s: diurnal_trace(s, duration_s=300.0, tenants=4,
                                base_rate=0.3),
        lambda s: steady_trace(s, 300.0, 4, interval_s=20.0),
        lambda s: storm_trace(s, 300.0, 4, storm_count=30),
        lambda s: burst_trace(s, tenants=3, per_tenant=5),
    ]

    def test_same_seed_same_trace_different_seed_different(self):
        for gen in self.GENERATORS:
            assert gen(7) == gen(7)
            assert gen(7) != gen(8)

    def test_arrivals_sorted_and_in_window(self):
        for gen in self.GENERATORS:
            arrivals = gen(3)
            assert arrivals
            ats = [a.at_s for a in arrivals]
            assert ats == sorted(ats)
            assert all(a.tenant in range(4) or isinstance(a, TraceArrival)
                       for a in arrivals)

    def test_diurnal_wave_modulates_rate(self):
        # amplitude 1 zeroes the trough: the crest quarter-period must
        # carry far more arrivals than the trough quarter-period
        arrivals = diurnal_trace(11, duration_s=1000.0, tenants=4,
                                 base_rate=0.5, amplitude=1.0,
                                 period_s=1000.0, bursts=[])
        crest = sum(1 for a in arrivals if 125.0 <= a.at_s < 375.0)
        trough = sum(1 for a in arrivals if 625.0 <= a.at_s < 875.0)
        assert crest > 4 * max(1, trough), (crest, trough)

    def test_storm_trace_floods_one_tenant(self):
        arrivals = storm_trace(5, 300.0, 4, storm_tenant=2,
                               storm_at_s=60.0, storm_count=50,
                               storm_width_s=10.0)
        flood = [a for a in arrivals
                 if a.tenant == 2 and 60.0 <= a.at_s <= 70.0]
        assert len(flood) >= 50
        # the other tenants still trickle
        assert any(a.tenant != 2 for a in arrivals)

    def test_burst_trace_synchronized_wave(self):
        arrivals = burst_trace(9, tenants=3, per_tenant=4, width_s=5.0)
        assert len(arrivals) == 12
        assert all(0.0 <= a.at_s <= 5.0 for a in arrivals)
        assert {a.tenant for a in arrivals} == {0, 1, 2}


# ----------------------------------------------------------------------
# SLO gate units (perf/checker.py check_slo)
# ----------------------------------------------------------------------

def make_result(**kw) -> ScenarioResult:
    res = ScenarioResult(name="unit", seed=0, scale="smoke")
    res.admitted = kw.pop("admitted", 10)
    res.admissions = kw.pop("admissions", 10)
    res.evictions = kw.pop("evictions", 0)
    res.class_p99_tta_s = kw.pop("class_p99_tta_s", {"standard": 10.0})
    for k, v in kw.items():
        setattr(res, k, v)
    return res


class TestSLOGates:
    def test_all_green(self):
        res = make_result(requeue_amplification=1.0)
        spec = SLOSpec(min_admitted=10,
                       class_max_p99_tta_s={"standard": 60.0},
                       max_ladder_recovery_cycles=5,
                       max_requeue_amplification=2.0, max_evictions=0)
        assert check_slo(res, spec) == []

    def test_min_admitted(self):
        v = check_slo(make_result(admitted=3), SLOSpec(min_admitted=10))
        assert any("below minimum" in s for s in v)

    def test_class_p99_bound_and_missing_class(self):
        res = make_result(class_p99_tta_s={"standard": 120.0})
        spec = SLOSpec(class_max_p99_tta_s={"standard": 60.0,
                                            "prod": 30.0})
        v = check_slo(res, spec)
        assert any("exceeds" in s and "standard" in s for s in v)
        assert any("no admissions recorded" in s and "prod" in s for s in v)

    def test_zero_starvation(self):
        res = make_result(starved=["default/w1", "default/w2"])
        v = check_slo(res, SLOSpec())
        assert any("starved" in s for s in v)
        assert check_slo(res, SLOSpec(zero_starvation=False)) == []

    def test_ladder_recovery(self):
        spec = SLOSpec(max_ladder_recovery_cycles=5)
        assert check_slo(
            make_result(ladder_recovery_cycles=5), spec) == []
        v = check_slo(make_result(ladder_recovery_cycles=9), spec)
        assert any("ladder recovery took 9" in s for s in v)
        v = check_slo(make_result(ladder_recovery_cycles=None), spec)
        assert any("never recovered" in s for s in v)

    def test_requeue_amplification_and_evictions(self):
        res = make_result(requeue_amplification=3.5, evictions=7)
        v = check_slo(res, SLOSpec(max_requeue_amplification=2.0,
                                   max_evictions=5))
        assert any("amplification" in s for s in v)
        assert any("evictions exceed" in s for s in v)
        assert check_slo(res, SLOSpec()) == []  # both gates off by default

    def test_slospec_backend_honesty(self):
        # Same contract as RangeSpec: a wall-calibrated spec refuses
        # cross-backend comparison instead of producing a dishonest gate.
        spec = SLOSpec(backend="tpu")
        assert refuse_cross_backend(
            spec, {"backend": "cpu", "cpu_fallback": False}) is not None
        assert refuse_cross_backend(
            spec, {"backend": "tpu", "cpu_fallback": False}) is None
        # virtual-time specs (no backend) compare anywhere
        assert refuse_cross_backend(
            SLOSpec(), {"backend": "cpu", "cpu_fallback": False}) is None


# ----------------------------------------------------------------------
# bounded EventRecorder ring (sim/runtime.py)
# ----------------------------------------------------------------------

class _Obj:
    def __init__(self, name):
        self.metadata = ObjectMeta(name=name, namespace="default")


class TestEventRecorderRing:
    def test_window_bounded_counters_exact(self):
        rec = EventRecorder(capacity=10)
        for i in range(25):
            rec.event(_Obj(f"w{i}"), "Normal", "Admitted", "ok")
        assert len(rec.events) == 10
        assert rec.total_events == 25
        assert rec.reason_counts["Admitted"] == 25
        # the retained window is the most recent 10
        assert [e.object_key for e in rec.events] == \
            [f"default/w{i}" for i in range(15, 25)]

    def test_by_reason_on_window_prefix_on_lifetime(self):
        rec = EventRecorder(capacity=5)
        for i in range(8):
            rec.event(_Obj(f"w{i}"), "Warning", "EvictedDueToPodsReadyTimeout",
                      "timeout")
        rec.system_event("Warning", "EvictedDueToPreemption", "bumped")
        assert len(rec.by_reason("EvictedDueToPodsReadyTimeout")) == 4
        assert rec.count_by_reason_prefix("EvictedDueTo") == 9
        assert rec.count_by_reason_prefix("Admitted") == 0

    def test_system_events_share_the_ring(self):
        rec = EventRecorder(capacity=3)
        rec.system_event("Warning", "DeviceFault", "site=device_dispatch")
        assert rec.events[-1].kind == "Scheduler"
        assert rec.reason_counts["DeviceFault"] == 1

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            EventRecorder(capacity=0)


# ----------------------------------------------------------------------
# the scenario catalog at smoke scale (tier-1)
# ----------------------------------------------------------------------

class TestScenarioSmoke:
    def test_catalog_lists_all_builtins(self):
        assert list_scenarios() == ["cluster_loss", "cluster_rebalance",
                                    "diurnal", "failover",
                                    "flavor_churn", "mixed_jobs",
                                    "requeue_flood", "restart_storm",
                                    "shard_rebalance", "shard_storm",
                                    "soak", "tenant_storm",
                                    "visibility_storm"]

    def test_unknown_scenario_and_scale_rejected(self):
        with pytest.raises(KeyError):
            run_scenario("nope")
        with pytest.raises(ValueError):
            run_scenario("diurnal", scale="medium")

    def test_diurnal_green(self):
        res = run_scenario("diurnal", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.admitted == res.submitted
        assert not res.starved

    def test_diurnal_deterministic_per_seed(self):
        a = run_scenario("diurnal", seed=3, scale="smoke").to_dict()
        b = run_scenario("diurnal", seed=3, scale="smoke").to_dict()
        assert a == b
        c = run_scenario("diurnal", seed=4, scale="smoke").to_dict()
        assert a != c

    def test_restart_storm_survives_kills(self):
        res = run_scenario("restart_storm", seed=3, scale="smoke")
        assert res.ok, res.violations
        assert res.restarts >= 1
        # every restart re-admitted within the SLO bound, in virtual s
        assert len(res.recovery_to_first_admission_s) == res.restarts
        assert res.admitted == res.submitted and not res.starved
        # the store never re-admits what it already settled
        assert res.requeue_amplification == 1.0

    def test_restart_storm_deterministic_per_seed(self):
        a = run_scenario("restart_storm", seed=5, scale="smoke").to_dict()
        b = run_scenario("restart_storm", seed=5, scale="smoke").to_dict()
        assert a == b

    def test_failover_promotes_warm_standby(self):
        """Scenario (j): leader killed mid-storm, hot standby promotes
        — no cold restore, promotion-to-first-admission gated at a
        THIRD of restart_storm's cold budget, zero double admission
        (store-vs-cache cross-check), fencing epoch advanced once per
        leadership change."""
        res = run_scenario("failover", seed=3, scale="smoke")
        assert res.ok, res.violations
        assert res.promotions >= 1
        assert res.restarts == 0  # warm failover never cold-restores
        assert len(res.promotion_to_first_admission_s) == res.promotions
        bound = res.slo.max_promotion_to_first_admission_s
        assert max(res.promotion_to_first_admission_s) <= bound
        # decisively under the cold-restore scenario's 6-cycle budget
        assert bound < 6 * 5.0
        assert res.admitted == res.submitted and not res.starved
        assert res.requeue_amplification == 1.0
        assert res.counters["fencing_epoch"] == 1 + res.promotions
        assert res.counters["standby"]["resyncs"] == 0

    def test_failover_deterministic_per_seed(self):
        a = run_scenario("failover", seed=5, scale="smoke").to_dict()
        b = run_scenario("failover", seed=5, scale="smoke").to_dict()
        assert a == b

    def test_tenant_storm_no_cross_tenant_starvation(self):
        res = run_scenario("tenant_storm", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.counters["tta_scope"].startswith("non-storm")
        # the storm tenant queues behind its own flood; everyone else's
        # gated p99 stays bounded (it is the SLO population)
        assert res.counters["storm_tenant_p99_tta_s"] is not None
        # ISSUE 14 journey gate ran and held: the slowest workload's
        # /debug/journeys timeline explained its admission (a gate
        # failure would be in res.violations and fail the ok assert),
        # and the ledger's evidence landed on the result.
        assert res.counters["journey_slowest"]["spans"] >= 2
        assert res.counters["journey_slowest"]["tta_s"] is not None
        assert res.counters["journeys"]["completed"] > 0
        # burn rates were priced against THIS scenario's SLOSpec
        # objectives (set_objectives wiring)
        assert res.counters["journeys"]["burn_rates"]

    def test_flavor_churn_takes_partial_rebuild_path(self):
        res = run_scenario("flavor_churn", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.counters["quota_edits"] > 0
        assert res.counters["partial_rebuilds"] > 0
        # single-CQ quota edits must not devolve into per-edit full
        # rebuilds (the scenario adds a violation if partials stay 0)
        assert res.counters["full_rebuilds"] <= 1 + res.counters["partial_rebuilds"]

    def test_requeue_flood_jitter_desync_and_ladder_recovery(self):
        res = run_scenario("requeue_flood", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.evictions > 0
        assert res.counters["requeue_ats"] > 0
        # seeded backoff jitter de-synchronizes the retry storm
        assert res.counters["requeue_at_distinct"] \
            >= 0.7 * res.counters["requeue_ats"]
        assert res.counters["requeue_at_spread_s"] > 0
        # the ladder engaged during the storm and recovered on budget
        assert res.ladder_recovery_cycles is not None
        assert 0 < res.ladder_recovery_cycles <= 8

    def test_cluster_loss_replacement_gc_no_double_dispatch(self):
        res = run_scenario("cluster_loss", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.counters["lost_with_reservation"] > 0
        assert res.counters["relocated"] == res.counters["lost_with_reservation"]
        assert res.counters["double_dispatched"] == 0
        assert res.counters["unplaced_admitted"] == 0
        assert res.counters["orphan_collected"] is True
        assert not res.starved

    def test_cluster_rebalance_batched_columns_bounded_replacement(self):
        # scenario (i), ISSUE 13: loss/rejoin mid-storm on the
        # batched-column placement path — zero double-dispatch, bounded
        # re-placement latency, and the planned single-mirror execution
        # actually engaged (no mirror-everywhere race, no expiries).
        res = run_scenario("cluster_rebalance", seed=0, scale="smoke")
        assert res.ok, res.violations
        assert res.counters["survivors_at_loss"] > 0
        assert res.replacement_latency_s is not None
        assert res.replacement_latency_s <= 90.0
        assert res.counters["double_dispatched"] == 0
        assert res.counters["unplaced_admitted"] == 0
        assert res.counters["placements_planned"] > 0
        assert res.counters["placements_executed"] > 0
        assert res.counters["placements_expired"] == 0
        assert not res.starved

    def test_mixed_jobs_admission_and_eviction_parity(self):
        res = run_scenario("mixed_jobs", seed=0, scale="smoke")
        assert res.ok, res.violations
        submitted = res.counters["submitted_by_kind"]
        admitted = res.counters["admitted_by_kind"]
        assert set(submitted) == {"workload", "job", "jobset",
                                  "pytorch", "ray"}
        for kind, n in submitted.items():
            assert admitted.get(kind, 0) == n, (kind, admitted)
        # one admitted object of every kind went through the eviction
        # lap (deactivate -> evict -> reactivate -> re-admit)
        assert set(res.counters["eviction_lap"]) == \
            {"workload", "Job", "JobSet", "PyTorchJob", "RayJob"}

    def test_visibility_storm_reads_consistent_and_bounded_stale(self):
        res = run_scenario("visibility_storm", seed=0, scale="smoke")
        assert res.ok, res.violations
        # the reader storm actually read, against a plane that kept
        # publishing sealed views through the traffic
        assert res.reads >= 50
        assert res.counters["cycles_published"] > 0
        assert res.counters["tables_built"] > 0
        # structural churn happened AND every stamped response stayed
        # within one generation of the live cache
        assert res.counters["quota_edits"] > 0
        assert res.read_staleness_generations is not None
        assert res.read_staleness_generations <= 1

    def test_results_backend_stamped(self):
        res = run_scenario("diurnal", seed=0, scale="smoke")
        assert "backend" in res.backend
        d = res.to_dict()
        assert d["backend"] == res.backend
        json.dumps(d)  # artifact-serializable


# ----------------------------------------------------------------------
# the driver CLI (tools/scenario_run.py)
# ----------------------------------------------------------------------

def _load_scenario_run():
    spec = importlib.util.spec_from_file_location(
        "scenario_run", os.path.join(REPO, "tools", "scenario_run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestScenarioRunCLI:
    def test_list(self, capsys):
        mod = _load_scenario_run()
        assert mod.main(["--list"]) == 0
        out = capsys.readouterr().out.split()
        assert out == list_scenarios()

    def test_unknown_scenario_is_an_argparse_error(self):
        mod = _load_scenario_run()
        with pytest.raises(SystemExit) as exc:
            mod.main(["no-such-scenario"])
        assert exc.value.code == 2

    def test_solver_flag_forwards_and_rejects_ungated(self, monkeypatch):
        """--solver reaches the scenario callable (the ROADMAP-item-2
        tenant-storm route gate is operator-runnable, not just a slow
        test), and asking for it on a scenario without a solver mode
        is a loud error, not a silent no-op."""
        from kueue_tpu.sim import scenarios as sc
        seen = {}

        def fake_storm(seed=0, scale="full", solver=False):
            seen.update(seed=seed, scale=scale, solver=solver)
            return sc.ScenarioResult("tenant_storm", seed, scale)

        monkeypatch.setitem(sc.SCENARIOS, "tenant_storm", fake_storm)
        res = sc.run_scenario("tenant_storm", seed=3, scale="smoke",
                              solver=True)
        assert res.name == "tenant_storm"
        assert seen == {"seed": 3, "scale": "smoke", "solver": True}
        with pytest.raises(ValueError, match="no solver mode"):
            sc.run_scenario("requeue_flood", scale="smoke", solver=True)

    def test_single_scenario_with_json_artifact(self, tmp_path, capsys):
        mod = _load_scenario_run()
        rc = mod.main(["requeue_flood", "--seed", "0",
                       "--scale", "smoke", "--json", str(tmp_path)])
        captured = capsys.readouterr()
        assert rc == 0
        verdict = json.loads(captured.out.strip().splitlines()[-1])
        assert verdict["ok"] is True
        assert verdict["scenarios"] == 1
        artifact = json.loads((tmp_path / "requeue_flood.json").read_text())
        assert artifact["scenario"] == "requeue_flood"
        assert artifact["ok"] is True
        assert artifact["counters"]["requeue_at_distinct"] > 0


# ----------------------------------------------------------------------
# full-scale sweep (slow)
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestFullSweep:
    @pytest.mark.parametrize("name", ["cluster_loss", "cluster_rebalance",
                                      "diurnal", "failover",
                                      "flavor_churn", "mixed_jobs",
                                      "requeue_flood", "restart_storm",
                                      "tenant_storm"])
    def test_full_scale_green(self, name):
        res = run_scenario(name, seed=0, scale="full")
        assert res.ok, (name, res.violations)
        assert not res.starved

    @pytest.mark.parametrize("seed", [1, 2])
    def test_failure_scenarios_hold_across_seeds(self, seed):
        for name in ("requeue_flood", "cluster_loss", "cluster_rebalance",
                     "restart_storm", "failover"):
            res = run_scenario(name, seed=seed, scale="full")
            assert res.ok, (name, seed, res.violations)
