"""Device-fault containment (kueue_tpu/resilience): the breaker state
machine, watchdog deadline derivation, the injection layer, and their
scheduler integration — device faults fall back to the CPU oracle with
identical decisions, N consecutive faults pin cycles to the distinct
"cpu-breaker" route (excluded from router samples), and a backed-off
half-open probe restores the device path. See RESILIENCE.md.
"""

import pytest

from kueue_tpu.metrics import Registry
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.breaker import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker)
from kueue_tpu.resilience.faultinject import (
    DeviceFault, FaultInjector, InjectedFault, SITE_COLLECT, SITE_DISPATCH,
    SITE_REPLAY, SITE_SCATTER)
from kueue_tpu.resilience.supervisor import SupervisedWorker
from kueue_tpu.resilience.watchdog import DispatchTimeout, DispatchWatchdog
from kueue_tpu.solver import BatchSolver
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faultinject.uninstall()


class TestFaultInjector:
    def test_disabled_is_identity(self):
        assert faultinject.active() is None
        payload = {"x": 1}
        assert faultinject.site(SITE_DISPATCH) is None
        assert faultinject.site(SITE_COLLECT, payload) is payload

    def test_scripted_schedules_are_seed_deterministic(self):
        a = FaultInjector.scripted(42, delay_s=0.01)
        b = FaultInjector.scripted(42, delay_s=0.01)
        c = FaultInjector.scripted(43, delay_s=0.01)
        assert a.schedule == b.schedule
        assert a.schedule != c.schedule

    def test_actions_fire_per_hit_index(self):
        inj = FaultInjector({SITE_DISPATCH: {1: faultinject.RAISE},
                             SITE_COLLECT: {0: faultinject.CORRUPT}})
        with faultinject.installed(inj):
            faultinject.site(SITE_DISPATCH)  # hit 0: clean
            with pytest.raises(InjectedFault) as exc:
                faultinject.site(SITE_DISPATCH)  # hit 1: fires
            assert exc.value.site == SITE_DISPATCH and exc.value.hit == 1
            out = faultinject.site(SITE_COLLECT, {"v": 1},
                                   corrupt=lambda p: {"v": -p["v"]})
            assert out == {"v": -1}
            # corrupt with no corruptor at the call site: pass-through
            p = object()
            assert faultinject.site(SITE_COLLECT, p) is p \
                or inj.schedule[SITE_COLLECT].get(1) is None
        assert inj.fired[SITE_DISPATCH] == 1
        assert inj.total_fired >= 2
        assert faultinject.active() is None  # context manager uninstalled

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError):
            FaultInjector({"not_a_site": {0: faultinject.RAISE}})

    def test_injected_fault_is_a_device_fault(self):
        assert issubclass(InjectedFault, DeviceFault)
        assert issubclass(DispatchTimeout, DeviceFault)


class TestCircuitBreaker:
    def test_trips_after_consecutive_faults_only(self):
        b = CircuitBreaker(threshold=3, backoff_base_s=2.0)
        assert b.allow_device(0)
        b.record_fault(0)
        b.record_fault(0)
        b.record_success(0)  # success resets the consecutive count
        b.record_fault(1)
        b.record_fault(1)
        assert b.state == CLOSED and b.trips == 0
        assert b.record_fault(1) is True  # third consecutive: trips
        assert b.state == OPEN and b.trips == 1

    def test_backoff_gates_the_probe_then_success_closes(self):
        b = CircuitBreaker(threshold=1, backoff_base_s=2.0, jitter=0.0)
        b.record_fault(10.0)
        assert b.state == OPEN
        assert not b.allow_device(11.0)   # within backoff
        assert b.allow_device(12.0)       # backoff elapsed: the probe
        assert b.state == HALF_OPEN
        assert not b.allow_device(12.0)   # one probe at a time
        assert b.record_success(12.0) is True
        assert b.state == CLOSED and b.recoveries == 1
        # blocked cycle at t=11 + the probe itself
        assert b.last_recovery_cycles == 3

    def test_failed_probe_doubles_backoff_to_the_cap(self):
        b = CircuitBreaker(threshold=1, backoff_base_s=1.0,
                           backoff_max_s=3.0, jitter=0.0)
        b.record_fault(0.0)
        assert b.allow_device(1.0)
        b.record_fault(1.0)               # failed probe: backoff 2s
        assert not b.allow_device(2.5)
        assert b.allow_device(3.0)
        b.record_fault(3.0)               # failed probe: backoff 3s (cap)
        assert not b.allow_device(5.5)
        assert b.allow_device(6.0)
        b.record_success(6.0)
        assert b.state == CLOSED
        # recovery resets the backoff to base
        b.record_fault(7.0)
        assert b.allow_device(8.0)

    def test_jitter_is_seed_deterministic(self):
        def retry_at(seed):
            b = CircuitBreaker(threshold=1, backoff_base_s=1.0,
                               jitter=0.5, seed=seed)
            b.record_fault(0.0)
            return b._retry_at
        assert retry_at(7) == retry_at(7)
        assert 1.0 <= retry_at(7) <= 1.5

    def test_failed_probe_counts_as_a_trip(self):
        # HALF_OPEN -> OPEN is a trip: self.trips must agree with the
        # breaker_trips_total metric the scheduler increments on every
        # tripped=True record_fault.
        b = CircuitBreaker(threshold=1, backoff_base_s=1.0, jitter=0.0)
        b.record_fault(0.0)
        assert b.trips == 1
        assert b.allow_device(1.5)
        assert b.record_fault(1.5) is True  # failed probe
        assert b.trips == 2

    def test_inconclusive_probe_rearms(self):
        b = CircuitBreaker(threshold=1, backoff_base_s=1.0, jitter=0.0)
        b.record_fault(0.0)
        assert b.allow_device(1.5)
        b.probe_inconclusive(1.5)  # the cycle never touched the device
        assert b.state == OPEN
        assert b.allow_device(1.5)  # probe immediately re-armed
        b.record_success(1.5)
        assert b.state == CLOSED


class TestSupervisedWorker:
    def test_inline_without_deadline(self):
        w = SupervisedWorker()
        assert w.run(lambda a, b: a + b, 1, 2) == 3
        assert w.status()["alive"] is False  # no thread was ever spawned

    def test_result_and_exception_relay(self):
        w = SupervisedWorker()
        assert w.run(lambda: 42, deadline_s=5.0) == 42
        with pytest.raises(ValueError, match="boom"):
            w.run(lambda: (_ for _ in ()).throw(ValueError("boom")),
                  deadline_s=5.0)
        w.stop()

    def test_worker_thread_is_reused(self):
        import threading
        w = SupervisedWorker()
        tids = set()
        for _ in range(3):
            tids.add(w.run(lambda: threading.get_ident(), deadline_s=5.0))
        assert len(tids) == 1  # persistent worker, not per-call threads
        assert w.calls == 3
        w.stop()

    def test_timeout_abandons_and_respawns(self):
        import threading
        release = threading.Event()

        def wedge():
            release.wait(10.0)
            return "late"

        w = SupervisedWorker()
        with pytest.raises(DispatchTimeout):
            w.run(wedge, deadline_s=0.05)
        assert w.timeouts == 1 and w.orphaned == 1
        # the next call is NOT queued behind the wedged one
        assert w.run(lambda: "fresh", deadline_s=5.0) == "fresh"
        release.set()  # let the orphan drain and exit its loop
        w.stop()

    def test_orphan_result_is_discarded(self):
        import threading
        release = threading.Event()
        out = []

        def wedge():
            release.wait(10.0)
            out.append("orphan-finished")
            return "late"

        w = SupervisedWorker()
        with pytest.raises(DispatchTimeout):
            w.run(wedge, deadline_s=0.05)
        release.set()
        # the orphan finishes eventually; its result reaches nobody
        for _ in range(100):
            if out:
                break
            import time
            time.sleep(0.01)
        assert out == ["orphan-finished"]
        assert w.run(lambda: "next", deadline_s=5.0) == "next"
        w.stop()


class TestWatchdog:
    def test_deadline_derivation(self):
        w = DispatchWatchdog(safety_factor=10.0, min_deadline_s=0.5,
                             max_deadline_s=30.0)
        assert w.deadline_s(None) == 30.0       # no estimate: cold max
        assert w.deadline_s(0.001) == 0.5       # clamped to the floor
        assert w.deadline_s(0.1) == 1.0
        assert w.deadline_s(100.0) == 30.0      # clamped to the cap

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            DispatchWatchdog(safety_factor=0)


def _fault_env(setup=None, threshold=3, min_heads=0):
    """Solver-enabled Env with a tight, deterministic breaker."""
    def default_setup(env):
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq")
                   .resource_group(flavor_quotas("default", cpu="100"))
                   .obj(), "lq")
    env = build_env(setup or default_setup, solver=True)
    env.scheduler.solver_min_heads = min_heads
    env.scheduler.breaker = CircuitBreaker(threshold=threshold,
                                           backoff_base_s=2.0, jitter=0.0)
    env.scheduler.metrics = Registry()
    return env


class TestSchedulerFaultContainment:
    def test_dispatch_fault_falls_back_to_cpu_same_decisions(self):
        env = _fault_env()
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        inj = faultinject.install(
            FaultInjector({SITE_DISPATCH: {0: faultinject.RAISE}}))
        env.cycle()
        faultinject.uninstall()
        # The CPU fallback admitted the head in the SAME cycle.
        assert "default/w" in admitted_map(env)
        assert inj.fired[SITE_DISPATCH] == 1
        s = env.scheduler
        assert s.solver_faults == 1
        assert s.breaker.consecutive_faults == 1
        assert s.breaker.state == CLOSED  # below threshold
        assert s.metrics.device_faults_total.value(site="solve") == 1

    def test_replay_fault_reestablishes_residency(self):
        env = _fault_env()
        env.submit(WorkloadWrapper("w0").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()  # establishes residency
        assert env.scheduler.solver._resident is not None
        env.submit(WorkloadWrapper("w1").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        faultinject.install(
            FaultInjector({SITE_REPLAY: {0: faultinject.RAISE}}))
        env.cycle()  # replay fault -> prepare fails -> CPU fallback
        faultinject.uninstall()
        assert "default/w1" in admitted_map(env)
        assert env.scheduler.solver_faults == 1
        env.submit(WorkloadWrapper("w2").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()  # residency re-establishes from a fresh snapshot
        assert "default/w2" in admitted_map(env)
        assert env.scheduler.solver._resident is not None

    def test_corrupted_collect_is_detected_not_admitted(self):
        env = _fault_env()
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        faultinject.install(
            FaultInjector({SITE_COLLECT: {0: faultinject.CORRUPT}}))
        env.cycle()
        faultinject.uninstall()
        s = env.scheduler
        assert s.solver.counters["validation_faults"] == 1
        assert s.solver_faults == 1
        # garbage decisions never became admissions; the head retries
        # and admits on fresh state
        env.cycle()
        assert "default/w" in admitted_map(env)

    def test_watchdog_timeout_abandons_the_collect(self):
        env = _fault_env()
        s = env.scheduler
        # Collect-watchdog test: the tiny cold clamp must not also
        # abort the (legitimately compiling) supervised dispatch.
        s.solver.supervise_dispatch = False
        s.watchdog = DispatchWatchdog(safety_factor=1.0,
                                      min_deadline_s=0.05,
                                      max_deadline_s=0.1)
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        faultinject.install(FaultInjector(
            {SITE_COLLECT: {0: (faultinject.DELAY, 0.3)}}))
        env.cycle()  # the hang outlives the 0.1s deadline
        faultinject.uninstall()
        assert s.solver.counters["dispatch_timeouts"] == 1
        assert s.solver_faults == 1
        assert s.metrics.dispatch_timeouts_total.value() == 1
        # a collect-side watchdog timeout (surfacing via the "solve"
        # site on the sync path) is NOT a supervised-dispatch timeout
        assert s.metrics.dispatch_supervised_timeouts_total.value() == 0
        assert s.solver.counters["supervised_timeouts"] == 0
        assert s.solver._resident is None  # residency invalidated
        # the abandoned cycle's heads re-heap and admit on retry
        env.cycle()
        assert "default/w" in admitted_map(env)

    def test_breaker_trips_routes_cpu_breaker_and_recovers(self):
        env = _fault_env(threshold=2)
        s = env.scheduler
        faultinject.install(FaultInjector(
            {SITE_DISPATCH: {0: faultinject.RAISE, 1: faultinject.RAISE}}))
        for i in range(2):
            env.submit(WorkloadWrapper(f"w{i}").queue("lq")
                       .creation(float(i)).pod_set(count=1, cpu="2").obj())
            env.cycle()
            assert f"default/w{i}" in admitted_map(env)  # CPU fallback
        assert s.breaker.state == OPEN and s.breaker.trips == 1
        assert s.metrics.breaker_trips_total.value() == 1
        # Open breaker: cycles pinned to the cpu-breaker route (clock
        # has not advanced past the backoff).
        env.submit(WorkloadWrapper("w2").queue("lq").creation(2.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert "default/w2" in admitted_map(env)
        assert s.cycle_counts.get("cpu-breaker") == 1
        # cpu-breaker cycles are containment, not economics: no router
        # sample may land under either engine for them
        assert not s._route_stats
        # Backoff elapses -> half-open probe on the device route (the
        # injector's schedule is exhausted, so the probe succeeds).
        env.clock.advance(10.0)
        env.submit(WorkloadWrapper("w3").queue("lq").creation(3.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        faultinject.uninstall()
        assert "default/w3" in admitted_map(env)
        assert s.breaker.state == CLOSED
        assert s.breaker.recoveries == 1
        assert s.metrics.fault_recovery_cycles.value() \
            == s.breaker.last_recovery_cycles > 0

    def test_failed_probe_reopens_with_longer_backoff(self):
        env = _fault_env(threshold=1)
        s = env.scheduler
        faultinject.install(FaultInjector(
            {SITE_DISPATCH: {0: faultinject.RAISE, 1: faultinject.RAISE}}))
        env.submit(WorkloadWrapper("w0").queue("lq").creation(0.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()  # fault 0: trips (threshold 1)
        assert s.breaker.state == OPEN
        env.clock.advance(3.0)  # past base backoff: probe admitted
        env.submit(WorkloadWrapper("w1").queue("lq").creation(1.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()  # probe faults too (hit 1): reopen, doubled backoff
        faultinject.uninstall()
        assert s.breaker.state == OPEN
        assert "default/w1" in admitted_map(env)  # still admitted via CPU
        env.clock.advance(3.0)  # 3 < doubled 4s backoff: still blocked
        env.submit(WorkloadWrapper("w2").queue("lq").creation(2.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert s.cycle_counts.get("cpu-breaker", 0) >= 1
        assert s.breaker.state == OPEN
        env.clock.advance(2.0)  # now past it: clean probe closes
        env.submit(WorkloadWrapper("w3").queue("lq").creation(3.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert s.breaker.state == CLOSED

    def test_strict_bound_does_not_consume_the_probe(self):
        # The starvation bound and the breaker can engage together
        # (blocked preemptors accumulate during an outage). A cycle the
        # strict gate routes off-device must NOT consume the half-open
        # probe: allow_device() transitioning OPEN->HALF_OPEN with no
        # device cycle to record an outcome would wedge the breaker in
        # HALF_OPEN forever (every later allow_device returns False).
        env = _fault_env(threshold=1)
        s = env.scheduler
        faultinject.install(
            FaultInjector({SITE_DISPATCH: {0: faultinject.RAISE}}))
        env.submit(WorkloadWrapper("w0").queue("lq").creation(0.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()  # trips (threshold 1)
        faultinject.uninstall()
        assert s.breaker.state == OPEN
        env.clock.advance(10.0)  # past the backoff: a probe is due
        # starvation bound engaged: the strict gate claims the cycle
        s.strict_after_blocked_cycles = 2
        s._blocked_preempt_streak = 2
        env.submit(WorkloadWrapper("w1").queue("lq").creation(1.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert s.cycle_counts.get("cpu-strict") == 1
        assert s.breaker.state == OPEN  # probe NOT consumed
        # bound released: the probe runs on the device and recovers
        s._blocked_preempt_streak = 0
        env.submit(WorkloadWrapper("w2").queue("lq").creation(2.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert s.breaker.state == CLOSED
        assert s.breaker.recoveries == 1

    def test_dispatch_hang_is_supervised_not_a_freeze(self):
        # ISSUE 5 tentpole: a DELAY (hang) at the device_dispatch site
        # used to sleep INLINE on the scheduler thread — the watchdog
        # only bounded collect, so an indefinite hang froze the
        # scheduler forever. Supervised dispatch abandons it within the
        # watchdog's cold clamp and the cycle completes via the CPU
        # path.
        import time as _t
        env = _fault_env()
        s = env.scheduler
        # Two warm cycles compile every shape bucket the hang cycle
        # will hit (the establishing dispatch AND the delta-prologue
        # variant) — so the tight clamp set below cannot be blown by a
        # legitimate compile, only by the injected hang.
        for i, name in enumerate(("warm-a", "warm-b")):
            env.submit(WorkloadWrapper(name).queue("lq")
                       .creation(float(i)).pod_set(count=1, cpu="2").obj())
            env.cycle()
        assert "default/warm-b" in admitted_map(env)
        s.watchdog = DispatchWatchdog(safety_factor=1.0,
                                      min_deadline_s=0.05,
                                      max_deadline_s=0.3)
        env.submit(WorkloadWrapper("w").queue("lq").creation(2.0)
                   .pod_set(count=1, cpu="2").obj())
        faultinject.install(FaultInjector(
            {SITE_DISPATCH: {0: (faultinject.DELAY, 2.0)}}))
        t0 = _t.perf_counter()
        env.cycle()  # 2s hang vs the 0.3s cold clamp: abandoned
        waited = _t.perf_counter() - t0
        faultinject.uninstall()
        assert waited < 2.0  # did NOT sit out the hang inline
        assert s.solver.counters["supervised_timeouts"] == 1
        assert s.solver._supervisor.orphaned == 1
        assert s.solver_faults == 1
        assert s.solver._resident is None  # residency invalidated
        assert s.metrics.dispatch_supervised_timeouts_total.value() == 1
        # the CPU fallback admitted the head in the SAME cycle
        assert "default/w" in admitted_map(env)
        # next device cycle re-establishes on a FRESH worker (the
        # orphan is still sleeping — raise the clamp back over the
        # re-establish dispatch, which is jit-cached but not free)
        s.watchdog = DispatchWatchdog()
        env.submit(WorkloadWrapper("w2").queue("lq").creation(3.0)
                   .pod_set(count=1, cpu="2").obj())
        env.cycle()
        assert "default/w2" in admitted_map(env)

    def test_supervision_disabled_runs_inline(self):
        env = _fault_env()
        s = env.scheduler
        s.solver.supervise_dispatch = False
        s.watchdog = DispatchWatchdog(safety_factor=1.0,
                                      min_deadline_s=0.05,
                                      max_deadline_s=0.1)
        env.submit(WorkloadWrapper("w").queue("lq")
                   .pod_set(count=1, cpu="2").obj())
        faultinject.install(FaultInjector(
            {SITE_DISPATCH: {0: (faultinject.DELAY, 0.2)}}))
        env.cycle()  # inline: the delay is sat out, no dispatch fault
        faultinject.uninstall()
        assert s.solver.counters["supervised_timeouts"] == 0
        assert "default/w" in admitted_map(env)

    def test_pipelined_collect_timeout_requeues_heads(self):
        def setup(env):
            env.add_flavor("default")
            env.add_cq(ClusterQueueWrapper("cq")
                       .resource_group(flavor_quotas("default", cpu="100"))
                       .obj(), "lq")
        env = _fault_env(setup)
        s = env.scheduler
        s.pipeline_enabled = True
        # This test exercises the COLLECT watchdog; its deliberately
        # tiny cold clamp would also abort legitimate compiles inside
        # supervised dispatch, so run dispatch inline (PR 3 semantics).
        s.solver.supervise_dispatch = False
        s.watchdog = DispatchWatchdog(safety_factor=1.0,
                                      min_deadline_s=0.05,
                                      max_deadline_s=0.1)
        for i in range(3):
            env.submit(WorkloadWrapper(f"w{i}").queue("lq")
                       .creation(float(i)).pod_set(count=1, cpu="2").obj())
        faultinject.install(FaultInjector(
            {SITE_COLLECT: {0: (faultinject.DELAY, 0.3)}}))
        for _ in range(8):  # dispatch, hung collect, recovery cycles
            env.cycle()
        faultinject.uninstall()
        assert s.solver.counters["dispatch_timeouts"] >= 1
        # no deadlock, nothing lost: every head admitted eventually
        assert {f"default/w{i}" for i in range(3)} <= set(admitted_map(env))


class TestBackendProbeNarrowing:
    """ISSUE 3 satellite: the blanket except-Exception backend probes
    must classify — expected backend-unavailable errors stay quiet,
    anything else lands in the fault counter (and vlog) instead of
    being silently swallowed."""

    def test_expected_backend_error_stays_quiet(self, monkeypatch):
        solver = BatchSolver()
        import jax

        def boom(*a, **k):
            raise RuntimeError("Backend 'cpu' failed to initialize")
        monkeypatch.setattr(jax, "devices", boom)
        assert solver._route(None, None, None, None) is None
        assert solver.counters["backend_probe_faults"] == 0

    def test_unexpected_probe_error_is_counted(self, monkeypatch):
        solver = BatchSolver()
        import jax

        def boom(*a, **k):
            raise ValueError("boom")
        monkeypatch.setattr(jax, "devices", boom)
        assert solver._route(None, None, None, None) is None
        assert solver.counters["backend_probe_faults"] == 1

    def test_calibration_failure_returns_default_and_counts(self,
                                                            monkeypatch):
        solver = BatchSolver()
        monkeypatch.setattr(
            BatchSolver, "_calibrate_floor",
            staticmethod(lambda: (_ for _ in ()).throw(ValueError("x"))))
        assert solver.estimated_sync_ms(default=77.0) == 77.0
        assert solver.counters["backend_probe_faults"] == 1
