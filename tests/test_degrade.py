"""Bounded-cycle admission (ISSUE 5): the degradation-ladder state
machine (transitions, hysteresis, N-healthy-cycle recovery), its
scheduler integration (head caps, deferred preempt planning, the
cpu-survival route, starvation-bound interplay), and the operator
surface (degraded_state gauge, cycles_shed_total, /debug/degrade,
flight-recorder annotations — all fed from the same producers).
"""

import pytest

from kueue_tpu.metrics import Registry
from kueue_tpu.resilience.degrade import (
    NORMAL, SHED, SURVIVAL, DegradationLadder)
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas


def make_ladder(**kw):
    kw.setdefault("budget_s", 0.1)
    kw.setdefault("escalate_after", 2)
    kw.setdefault("recovery_cycles", 2)
    kw.setdefault("ewma_alpha", 1.0)  # EWMA == last cycle: exact tests
    return DegradationLadder(**kw)


class TestLadderStateMachine:
    def test_disabled_ladder_never_moves(self):
        lad = DegradationLadder(budget_s=0.0)
        assert not lad.enabled
        for _ in range(10):
            assert lad.observe_cycle(10.0, backlog=100) is False
        assert lad.state == NORMAL and lad.cycles_observed == 0

    def test_escalates_after_consecutive_overloaded_cycles(self):
        lad = make_ladder()
        assert lad.observe_cycle(0.2) is False   # 1st overloaded
        assert lad.state == NORMAL
        assert lad.observe_cycle(0.2) is True    # 2nd: normal -> shed
        assert lad.state == SHED
        assert lad.observe_cycle(0.2) is False
        assert lad.observe_cycle(0.2) is True    # shed -> survival
        assert lad.state == SURVIVAL
        # survival is the floor: more overload cannot escalate further
        assert lad.observe_cycle(0.2) is False
        assert lad.observe_cycle(0.2) is False
        assert lad.state == SURVIVAL
        assert lad.escalations == 2

    def test_one_overloaded_cycle_is_not_enough(self):
        lad = make_ladder()
        lad.observe_cycle(0.2)
        lad.observe_cycle(0.01)  # healthy cycle resets the streak
        lad.observe_cycle(0.2)
        assert lad.state == NORMAL

    def test_recovery_needs_consecutive_healthy_cycles(self):
        lad = make_ladder()
        for _ in range(4):
            lad.observe_cycle(0.2)
        assert lad.state == SURVIVAL
        lad.observe_cycle(0.01)
        lad.observe_cycle(0.2)   # overload interrupts the healthy streak
        lad.observe_cycle(0.01)
        assert lad.state == SURVIVAL
        lad.observe_cycle(0.01)  # 2 consecutive healthy: down one rung
        assert lad.state == SHED
        lad.observe_cycle(0.01)
        lad.observe_cycle(0.01)
        assert lad.state == NORMAL
        assert lad.recoveries == 2

    def test_hysteresis_band_holds_the_rung(self):
        # exit 0.7 x budget < cycle < enter 1.0 x budget: neither streak
        # may accumulate — a borderline load can't flap the ladder.
        lad = make_ladder()
        lad.observe_cycle(0.2)
        lad.observe_cycle(0.2)
        assert lad.state == SHED
        for _ in range(20):
            assert lad.observe_cycle(0.085) is False  # inside the band
        assert lad.state == SHED
        assert lad._over == 0 and lad._healthy == 0

    def test_backlog_growth_escalates_on_raw_cycle_overrun(self):
        # EWMA still under budget, but the raw cycle blew it while the
        # backlog grew: storm onset counts as overloaded immediately.
        lad = make_ladder(ewma_alpha=0.01)  # EWMA barely moves
        lad.observe_cycle(0.01, backlog=10)
        assert lad.observe_cycle(0.5, backlog=20) is False
        assert lad.observe_cycle(0.5, backlog=30) is True
        assert lad.state == SHED

    def test_backlog_not_growing_allows_recovery(self):
        lad = make_ladder()
        lad.observe_cycle(0.2, backlog=10)
        lad.observe_cycle(0.2, backlog=10)
        assert lad.state == SHED
        # healthy cycle times but GROWING backlog: not healthy
        lad.observe_cycle(0.01, backlog=20)
        lad.observe_cycle(0.01, backlog=30)
        assert lad.state == SHED
        lad.observe_cycle(0.01, backlog=25)
        lad.observe_cycle(0.01, backlog=20)
        assert lad.state == NORMAL

    def test_head_cap_and_flags_per_state(self):
        lad = make_ladder(shed_heads=100, survival_heads=10)
        assert lad.head_cap() is None
        assert not lad.defer_preemption and not lad.pin_cpu
        lad.state = SHED
        assert lad.head_cap() == 100
        assert lad.defer_preemption and not lad.pin_cpu
        lad.state = SURVIVAL
        assert lad.head_cap() == 10
        assert lad.defer_preemption and lad.pin_cpu

    def test_cycles_shed_counts_degraded_cycles(self):
        lad = make_ladder()
        lad.observe_cycle(0.2)
        lad.observe_cycle(0.2)  # transition happens at THIS cycle's end
        assert lad.cycles_shed == 0  # both ran under normal
        lad.observe_cycle(0.2)
        assert lad.cycles_shed == 1

    def test_idle_cycles_rung_down_while_quiescent(self):
        # PR-5 follow-up: a degraded ladder with an empty queue held its
        # rung until traffic resumed. Idle ticks now count toward the
        # healthy-cycle streak.
        lad = make_ladder()
        for _ in range(4):
            lad.observe_cycle(0.2)
        assert lad.state == SURVIVAL
        assert lad.observe_idle() is False
        assert lad.observe_idle() is True   # 2 idle ticks: down a rung
        assert lad.state == SHED
        lad.observe_idle()
        assert lad.observe_idle() is True
        assert lad.state == NORMAL
        assert lad.recoveries == 2 and lad.idle_cycles == 4

    def test_idle_recovery_drops_the_stale_storm_ewma(self):
        # The storm's EWMA must not survive an idle recovery: left in
        # place, the first healthy cycles after traffic resumes would
        # inherit it and spuriously re-escalate.
        lad = make_ladder(ewma_alpha=0.3)
        for _ in range(4):
            lad.observe_cycle(0.3)
        assert lad.state == SURVIVAL and lad.ewma_s > lad.budget_s
        while lad.state != NORMAL:
            lad.observe_idle()
        assert lad.ewma_s is None
        # resumed healthy traffic stays normal
        for _ in range(4):
            lad.observe_cycle(0.02)
        assert lad.state == NORMAL and lad._over == 0

    def test_idle_ticks_mix_with_healthy_cycles(self):
        # a trickle cycle between idle ticks keeps accumulating the SAME
        # healthy streak; an overloaded cycle resets it
        lad = make_ladder()
        lad.observe_cycle(0.2)
        lad.observe_cycle(0.2)
        assert lad.state == SHED
        lad.observe_idle()
        lad.observe_cycle(0.2)      # overload resets the streak
        lad.observe_idle()
        assert lad.state == SHED
        assert lad.observe_idle() is True
        assert lad.state == NORMAL

    def test_idle_is_noop_when_normal_or_disabled(self):
        lad = make_ladder()
        assert lad.observe_idle() is False
        assert lad.idle_cycles == 0 and lad._healthy == 0
        off = DegradationLadder(budget_s=0.0)
        off.state = SHED
        assert off.observe_idle() is False
        assert off.state == SHED

    def test_allow_pipeline_per_state(self):
        lad = make_ladder()
        assert lad.allow_pipeline
        lad.state = SHED
        assert lad.allow_pipeline   # bounded allowance (ISSUE 6)
        lad.state = SURVIVAL
        assert not lad.allow_pipeline

    def test_status_payload(self):
        lad = make_ladder()
        lad.observe_cycle(0.2, backlog=7)
        st = lad.status()
        assert st["state"] == NORMAL and st["enabled"]
        assert st["budget_ms"] == 100.0
        assert st["ewma_ms"] == 200.0
        assert st["last_backlog"] == 7
        assert st["cycles_observed"] == 1

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            DegradationLadder(budget_s=-1)
        with pytest.raises(ValueError):
            DegradationLadder(shed_heads=0)
        with pytest.raises(ValueError):
            DegradationLadder(exit_factor=1.5, enter_factor=1.0)
        with pytest.raises(ValueError):
            DegradationLadder(recovery_cycles=0)
        with pytest.raises(ValueError):
            DegradationLadder(ewma_alpha=0)


def _env(n_cqs=4, cpu="100", preemption=False, solver=False):
    def setup(env):
        env.add_flavor("default")
        for i in range(n_cqs):
            cq = ClusterQueueWrapper(f"cq{i}").cohort("co")
            if preemption:
                from kueue_tpu.api import kueue as api
                cq = cq.preemption(
                    within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
            env.add_cq(cq.resource_group(
                flavor_quotas("default", cpu=cpu)).obj(), f"lq-cq{i}")
    env = build_env(setup, solver=solver)
    env.scheduler.metrics = Registry()
    return env


def _submit(env, n_per_cq=1, n_cqs=4, cpu="2", priority=0, start=0):
    n = start
    for w in range(n_per_cq):
        for i in range(n_cqs):
            env.submit(WorkloadWrapper(f"w{n}").queue(f"lq-cq{i}")
                       .priority(priority).creation(float(n))
                       .pod_set(count=1, cpu=cpu).obj())
            n += 1
    return n


class TestSchedulerShedding:
    def test_shed_caps_heads_and_requeues_extras_fifo(self):
        env = _env()
        s = env.scheduler
        s.ladder = make_ladder()
        s.ladder.state = SHED
        s.ladder.shed_heads = 2
        _submit(env)  # 4 heads, one per CQ
        env.cycle()
        # only the 2 oldest heads were processed; extras re-heaped
        assert set(admitted_map(env)) == {"default/w0", "default/w1"}
        assert s.shed_heads_requeued == 2
        # the shed heads were NOT patched (no Pending churn) and retry
        env.cycle()
        assert set(admitted_map(env)) == {f"default/w{i}" for i in range(4)}
        # trace carries the rung + the shed annotation
        traces = s.recorder.traces()
        assert traces[0].degraded == SHED
        kinds = [a["kind"] for a in traces[0].annotations]
        assert "shed" in kinds

    def test_shed_cap_keeps_high_priority_over_older_heads(self):
        # Timestamp-only capping would shed a high-priority mid-storm
        # arrival every cycle behind older low-priority heads — the cap
        # must mirror the admission order's priority-then-FIFO prefix.
        env = _env()
        s = env.scheduler
        s.ladder = make_ladder()
        s.ladder.state = SHED
        s.ladder.shed_heads = 1
        _submit(env)  # w0..w3, priority 0, oldest timestamps
        env.submit(WorkloadWrapper("hot").queue("lq-cq0").priority(100)
                   .creation(99.0).pod_set(count=1, cpu="2").obj())
        env.cycle()
        # cq0's heap is priority-ordered, so "hot" pops as its head
        # despite the young timestamp; the cap (1 of 4 heads) must then
        # keep it ahead of the older priority-0 heads from cq1..cq3.
        assert "default/hot" in admitted_map(env)

    def test_survival_pins_cpu_survival_route(self):
        env = _env(solver=True)
        s = env.scheduler
        s.ladder = make_ladder(survival_heads=2)
        s.ladder.state = SURVIVAL
        _submit(env)
        env.cycle()
        assert s.cycle_counts.get("cpu-survival") == 1
        # an intervention, not an economics signal
        assert not s._route_stats
        assert len(admitted_map(env)) == 2  # top-k only
        assert env.scheduler.metrics.degraded_state.value() == 2

    def test_survival_does_not_consume_half_open_probe(self):
        from kueue_tpu.resilience.breaker import OPEN, CircuitBreaker
        env = _env(solver=True)
        s = env.scheduler
        s.breaker = CircuitBreaker(threshold=1, backoff_base_s=1.0,
                                   jitter=0.0)
        s.breaker.record_fault(env.clock.now())
        assert s.breaker.state == OPEN
        env.clock.advance(10.0)  # a probe is due
        s.ladder = make_ladder()
        s.ladder.state = SURVIVAL
        _submit(env)
        env.cycle()
        assert s.cycle_counts.get("cpu-survival") == 1
        assert s.breaker.state == OPEN  # probe NOT consumed (no wedge)

    def test_shed_defers_preempt_planning(self):
        env = _env(n_cqs=1, cpu="8", preemption=True)
        s = env.scheduler
        # victims occupy the full quota; a high-priority preemptor needs
        # target selection to make progress
        env.admit_existing(
            WorkloadWrapper("victim").queue("lq-cq0").priority(0)
            .pod_set(count=1, cpu="8").reserve("cq0").obj())
        env.submit(WorkloadWrapper("pre").queue("lq-cq0").priority(10)
                   .creation(1.0).pod_set(count=1, cpu="8").obj())
        s.ladder = make_ladder()
        s.ladder.state = SHED
        env.cycle()
        # deferred: no eviction issued, plan counted, streak NOT ratcheted
        assert not env.client.evicted
        assert s.preempt_plans_deferred == 1
        assert s._blocked_preempt_streak == 0
        # ladder recovers -> the preemptor plans and evicts normally
        s.ladder.state = NORMAL
        env.cycle()
        assert "default/victim" in env.client.evicted

    def test_shed_defers_device_preempt_batch(self):
        env = _env(n_cqs=1, cpu="8", preemption=True, solver=True)
        s = env.scheduler
        env.admit_existing(
            WorkloadWrapper("victim").queue("lq-cq0").priority(0)
            .pod_set(count=1, cpu="8").reserve("cq0").obj())
        env.submit(WorkloadWrapper("pre").queue("lq-cq0").priority(10)
                   .creation(1.0).pod_set(count=1, cpu="8").obj())
        s.ladder = make_ladder()
        s.ladder.state = SHED
        env.cycle()
        assert not env.client.evicted
        assert s.preempt_plans_deferred == 1
        s.ladder.state = NORMAL
        env.cycle()
        assert "default/victim" in env.client.evicted

    def test_budget_transitions_fire_annotations_events_and_metrics(self):
        env = _env()
        s = env.scheduler
        events = []
        s.on_fault = lambda kind, msg: events.append((kind, msg))
        # Budget of -inf effectively: every real cycle overloads it.
        s.ladder = DegradationLadder(budget_s=1e-9, escalate_after=1,
                                     recovery_cycles=1, ewma_alpha=1.0)
        # a head per cycle: the ladder only observes cycles that popped
        # heads (a headless scheduler has nothing to bound)
        n = _submit(env)
        env.cycle()  # overloaded -> normal->shed at cycle end
        assert s.ladder.state == SHED
        assert env.scheduler.metrics.degraded_state.value() == 1
        assert events and events[0][0] == "degrade"
        tr = s.recorder.traces()[-1]
        assert any(a["kind"] == "degrade" for a in tr.annotations)
        n = _submit(env, start=n)
        env.cycle()  # shed cycle runs -> counted, escalates again
        assert s.ladder.state == SURVIVAL
        assert env.scheduler.metrics.cycles_shed_total.value(
            state="shed") == 1
        _submit(env, start=n)
        env.cycle()
        assert env.scheduler.metrics.cycles_shed_total.value(
            state="survival") == 1

    def test_ladder_recovers_end_to_end_with_real_budget(self):
        env = _env()
        s = env.scheduler
        # generous budget: real tiny cycles are healthy
        s.ladder = DegradationLadder(budget_s=60.0, escalate_after=1,
                                     recovery_cycles=2, ewma_alpha=1.0)
        s.ladder.state = SURVIVAL  # as if a storm just ended
        n = 0
        for _ in range(5):
            # trickled arrivals: the ladder only observes cycles that
            # popped heads
            n = _submit(env, start=n)
            env.cycle()
        assert s.ladder.state == NORMAL
        assert len(admitted_map(env)) == 20  # nothing lost on the way

    def test_pipeline_bounded_under_shed_gated_off_in_survival(self):
        # ISSUE 6: shed allows BOUNDED pipelining (the head cap ran
        # before routing; preempt-planning cycles bail to sync), while
        # survival still gates it off (the cycle is CPU-pinned anyway
        # and the in-flight queue must drain, not grow).
        env = _env(solver=True)
        s = env.scheduler
        s.pipeline_enabled = True
        s.ladder = make_ladder()
        s.ladder.state = SHED
        assert s.ladder.allow_pipeline
        s.ladder.state = SURVIVAL
        assert not s.ladder.allow_pipeline
        assert not s._pipeline_ok([object()] * 100)
        s.ladder.state = NORMAL
        assert s.ladder.allow_pipeline


class TestDegradeStatusSurface:
    def test_debug_degrade_payload(self):
        from kueue_tpu.obs import DebugEndpoints, degrade_status
        env = _env()
        s = env.scheduler
        s.ladder = make_ladder()
        s.ladder.state = SHED
        s.ladder.shed_heads = 2
        _submit(env)
        env.cycle()
        st = degrade_status(s)
        assert st["state"] == SHED
        assert st["shed_heads_requeued_total"] == 2
        assert "budget_ms" in st and "ewma_ms" in st
        ep = DebugEndpoints(s, env.scheduler.metrics)
        payload = ep.handle("/debug/degrade", {})
        # the endpoint additionally stamps the generation token it
        # rendered under (ISSUE 12 satellite)
        assert payload.pop("generation") == \
            list(s.cache.generation_token())
        assert payload == degrade_status(s)

    def test_metrics_exposition_includes_degrade_series(self):
        env = _env()
        s = env.scheduler
        s.ladder = DegradationLadder(budget_s=1e-9, escalate_after=1)
        _submit(env)
        env.cycle()
        env.cycle()
        text = env.scheduler.metrics.dump()
        assert "kueue_scheduler_degraded_state" in text
        assert "kueue_scheduler_cycles_shed_total" in text
        assert "kueue_solver_dispatch_supervised_timeouts_total" in text


class TestConfigWiring:
    def test_manager_wires_ladder_and_supervision(self):
        from kueue_tpu import config as cfgpkg
        from kueue_tpu.manager import KueueManager
        from kueue_tpu.solver import BatchSolver
        cfg = cfgpkg.Configuration()
        cfg.scheduler.cycle_budget_s = 0.5
        cfg.scheduler.shed_heads = 33
        cfg.scheduler.survival_heads = 7
        cfg.solver.supervise_dispatch = False
        solver = BatchSolver()
        mgr = KueueManager(cfg=cfg, solver=solver)
        lad = mgr.scheduler.ladder
        assert lad.enabled and lad.budget_s == 0.5
        assert lad.shed_heads == 33 and lad.survival_heads == 7
        assert solver.supervise_dispatch is False

    def test_config_load_and_validation(self):
        from kueue_tpu import config as cfgpkg
        cfg = cfgpkg.load({"scheduler": {"cycleBudget": 0.25,
                                         "shedHeads": 128,
                                         "survivalHeads": 16,
                                         "recoveryCycles": 5}})
        assert cfg.scheduler.cycle_budget_s == 0.25
        assert cfg.scheduler.shed_heads == 128
        assert cfg.scheduler.recovery_cycles == 5
        with pytest.raises(ValueError):
            cfgpkg.load({"scheduler": {"cycleBudget": -1}})
        with pytest.raises(ValueError):
            cfgpkg.load({"scheduler": {"shedHeads": 0}})
        with pytest.raises(ValueError):
            cfgpkg.load({"scheduler": {"overloadExitFactor": 2.0}})
        cfg = cfgpkg.load({"solver": {"superviseDispatch": False}})
        assert cfg.solver.supervise_dispatch is False

    def test_reconcile_seconds_fed_by_runtime(self):
        from kueue_tpu.manager import KueueManager
        from tests.wrappers import make_flavor, make_local_queue
        mgr = KueueManager()
        mgr.store.create(make_flavor("default"))
        mgr.store.create(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=8)).obj())
        mgr.store.create(make_local_queue("lq", "default", "cq"))
        mgr.run_until_idle()
        h = mgr.metrics.reconcile_seconds
        assert h.count(controller="clusterqueue") > 0
        assert h.count(controller="localqueue") > 0
