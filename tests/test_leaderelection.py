"""Leader election / HA (reference: cmd/kueue leader election wiring,
pkg/scheduler/scheduler.go:144 NeedLeaderElection,
pkg/controller/core/leader_aware_reconciler.go:89)."""

from kueue_tpu import config as cfgpkg
from kueue_tpu.api.meta import FakeClock
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.sim.store import Store
from kueue_tpu.utils.leaderelection import (
    LeaderAwareReconciler,
    LeaderElector,
)

from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)


class TestLeaderElector:
    def test_acquires_fresh_lease(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        e = LeaderElector(store, "rep-a", clock=clock)
        assert e.tick() is True
        assert e.is_leader()
        assert e.leader_identity() == "rep-a"

    def test_second_replica_waits_then_takes_over_on_expiry(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        a = LeaderElector(store, "rep-a", lease_duration=15.0, clock=clock)
        b = LeaderElector(store, "rep-b", lease_duration=15.0, clock=clock)
        assert a.tick()
        assert not b.tick()
        # a renews within the lease: b still locked out
        clock.advance(10.0)
        assert a.tick()
        clock.advance(10.0)
        assert not b.tick()
        # a dies (stops renewing); lease expires; b takes over
        clock.advance(15.0)
        assert b.tick()
        assert b.is_leader()
        lease = store.get("Lease", "kueue-system", a.lease_name)
        assert lease.spec.holder_identity == "rep-b"
        assert lease.spec.lease_transitions == 1
        # a notices it lost on its next tick
        assert not a.tick()
        assert not a.is_leader()

    def test_release_hands_over_immediately(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        stopped = []
        a = LeaderElector(store, "rep-a", clock=clock,
                          on_stopped_leading=lambda: stopped.append(1))
        b = LeaderElector(store, "rep-b", clock=clock)
        assert a.tick()
        a.release()
        assert stopped == [1]
        assert not a.is_leader()
        assert b.tick()  # no need to wait out the lease duration

    def test_transition_callbacks_fire_once(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        started = []
        a = LeaderElector(store, "rep-a", clock=clock,
                          on_started_leading=lambda: started.append(1))
        assert a.tick()
        assert a.tick()  # renewal: no second callback
        assert started == [1]

    def test_concurrent_renew_conflict_loses(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        a = LeaderElector(store, "rep-a", lease_duration=15.0, clock=clock)
        b = LeaderElector(store, "rep-b", lease_duration=15.0, clock=clock)
        assert a.tick()
        clock.advance(20.0)  # expired: both replicas race for it
        assert b.tick()      # b wins the store update first
        assert not a.tick()  # a's expect_rv update conflicts


class TestLeaderAwareReconciler:
    def test_non_leader_requeues_leader_delegates(self):
        clock = FakeClock(100.0)
        store = Store(clock)
        e = LeaderElector(store, "rep-a", retry_period=2.0, clock=clock)
        calls = []

        class Inner:
            def reconcile(self, key):
                calls.append(key)
                return None

        r = LeaderAwareReconciler(Inner(), e)
        assert r.reconcile("k") == 2.0  # delayed, not executed
        assert calls == []
        e.tick()
        assert r.reconcile("k") is None
        assert calls == ["k"]


def _ha_manager(store, clock, identity):
    cfg = cfgpkg.Configuration()
    cfg.leader_election.leader_elect = True
    return KueueManager(cfg=cfg, clock=clock, store=store, identity=identity)


class TestManagerHA:
    def test_only_leader_schedules_and_failover_works(self):
        clock = FakeClock(1000.0)
        store = Store(clock)
        m1 = _ha_manager(store, clock, "rep-1")
        m2 = _ha_manager(store, clock, "rep-2")
        # m1 registered its elector controller first: it wins the lease
        m1.run_until_idle()
        m2.run_until_idle()
        assert m1.elector.is_leader()
        assert not m2.elector.is_leader()

        store.create(make_flavor("default"))
        store.create(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=10)).obj())
        store.create(make_local_queue("lq", "default", "cq"))
        m1.run_until_idle()
        m2.run_until_idle()
        store.create(WorkloadWrapper("w1").queue("lq")
                     .request("cpu", "1").obj())
        m1.run_until_idle()
        m2.run_until_idle()

        # non-leader's scheduler is gated; leader admits
        m2.schedule_once()
        assert not wlpkg.has_quota_reservation(
            store.get("Workload", "default", "w1"))
        m1.schedule_once()
        assert wlpkg.has_quota_reservation(
            store.get("Workload", "default", "w1"))

        # failover: m1 stops renewing (crashed); after the lease expires
        # m2's next tick takes over and its scheduler un-gates
        store.create(WorkloadWrapper("w2").queue("lq")
                     .request("cpu", "1").obj())
        m2.run_until_idle()
        clock.advance(20.0)
        m2.advance(0.0)  # release m2's due renewal timer
        assert m2.elector.is_leader()
        m2.schedule_once()
        assert wlpkg.has_quota_reservation(
            store.get("Workload", "default", "w2"))


class TestPipelineAbandonOnLeadershipLoss:
    def test_inflight_cycle_abandoned_not_admitted(self):
        """Losing the lease with a pipelined cycle in flight must NOT
        admit its device decisions (another replica may admit the same
        heads); the heads requeue and residency is invalidated."""
        from kueue_tpu.solver import BatchSolver
        from tests.test_scheduler import Env
        from tests.wrappers import ClusterQueueWrapper, flavor_quotas

        env = Env()
        env.scheduler.solver = BatchSolver()
        env.scheduler.solver_min_heads = 0
        env.scheduler.pipeline_enabled = True
        env.add_flavor("default")
        env.add_cq(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu="8")).obj(), "lq")
        env.submit(WorkloadWrapper("w0").queue("lq")
                   .pod_set(count=1, cpu="1").obj())
        leading = [True]
        env.scheduler.leader_check = lambda: leading[0]
        env.scheduler.schedule(timeout=0)  # dispatch-only cycle
        assert env.scheduler._inflight is not None
        leading[0] = False
        env.scheduler.schedule(timeout=0)
        assert env.scheduler._inflight is None
        assert env.client.applied == {}  # decisions dropped, not applied
        assert env.scheduler.solver._resident is None  # residency reset
        # re-acquire: the requeued head admits through a fresh cycle
        leading[0] = True
        for _ in range(3):
            env.scheduler.schedule(timeout=0)
        assert "default/w0" in env.client.applied
