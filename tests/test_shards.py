"""Sharded admission control plane (parallel/shards.py, RESILIENCE.md
§9): named leases on one durable log, the planner-owned unit layout,
kill/promote fault isolation, rebalance handoff, scoped fault
injection, and the exactly-once cross-checks the probes gate on."""

import pytest

from kueue_tpu.api.meta import FakeClock
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.parallel.shards import (SHARD_ACTIVE, SHARD_KILLED,
                                       ShardedControlPlane, plan_shards,
                                       shard_units)
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import CRASH, FaultInjector
from kueue_tpu.sim.durable import DurableLog, Fenced
from kueue_tpu.sim.shardstorm import _admitted, _objects, _workload


def _build(n_shards=2, num_cqs=4, quota=50_000):
    clock = FakeClock(1000.0)
    scp = ShardedControlPlane(n_shards, clock=clock)
    for obj in _objects(num_cqs, quota):
        scp.plane.store.create(obj)
    scp.plane.run_until_idle(max_iterations=1_000_000)
    scp.replan()
    return scp, clock


def _wave(scp, wave, num_cqs, n0):
    for i in range(num_cqs):
        scp.plane.store.create(_workload(wave, i, n0 + i))
    scp.plane.run_until_idle(max_iterations=1_000_000)
    return n0 + num_cqs


# ----------------------------------------------------------------------
# named leases on one durable log
# ----------------------------------------------------------------------

class TestNamedLeases:
    def test_leases_are_independent(self):
        log = DurableLog()
        e0 = log.acquire_lease("a", now=0.0, name="shard-0")
        e1 = log.acquire_lease("b", now=0.0, name="shard-1")
        assert e0 == 1 and e1 == 1  # separate epoch sequences
        # Each identity is valid only against its OWN lease name.
        log.check_epoch("a", 1, name="shard-0")
        log.check_epoch("b", 1, name="shard-1")
        with pytest.raises(Fenced):
            log.check_epoch("a", 1, name="shard-1")

    def test_holder_change_bumps_only_that_lease(self):
        log = DurableLog()
        log.acquire_lease("a", now=0.0, name="shard-0")
        log.acquire_lease("b", now=0.0, name="shard-1")
        e = log.acquire_lease("a2", now=0.0, force=True, name="shard-0")
        assert e == 2
        with pytest.raises(Fenced):
            log.check_epoch("a", 1, name="shard-0")  # deposed
        log.check_epoch("b", 1, name="shard-1")      # untouched

    def test_legacy_unnamed_lease_back_compat(self):
        log = DurableLog()
        e = log.acquire_lease("leader", now=0.0)
        assert e == 1 and log.fencing_epoch == 1
        log.acquire_lease("s", now=0.0, name="shard-0")
        assert log.fencing_epoch == 1  # shard lease is a different row
        table = log.lease_table(now=0.0)
        assert set(table) == {"", "shard-0"}

    def test_unleased_name_is_open_regime(self):
        # A name that never had a holder doesn't fence anything —
        # standalone durability keeps working without leases.
        DurableLog().check_epoch("anyone", 0, name="shard-9")


# ----------------------------------------------------------------------
# the planner-owned layout
# ----------------------------------------------------------------------

class TestShardPlan:
    def test_plan_deterministic_and_fingerprinted(self):
        units = {f"cq{i}": f"cohort:c{i % 3}" for i in range(9)}
        w = {f"cq{i}": i + 1 for i in range(9)}
        p1 = plan_shards(units, w, 2)
        p2 = plan_shards(dict(reversed(list(units.items()))), w, 2)
        assert p1.fingerprint == p2.fingerprint
        assert p1.shard_of_unit == p2.shard_of_unit
        assert len(p1.fingerprint) == 16

    def test_whole_cohorts_move_together(self):
        scp, _ = _build(n_shards=2, num_cqs=4)
        units = shard_units(scp.plane.cache)
        # _objects puts cq{i} in cohort-{i%2}: cohort-mates share a unit.
        assert units["cq0"] == units["cq2"] == "cohort:cohort-0"
        assert units["cq1"] == units["cq3"] == "cohort:cohort-1"
        for cq, unit in units.items():
            assert scp.plan.cq_shard[cq] == scp.plan.shard_of_unit[unit]
        scp.shutdown()

    def test_unmapped_cq_defaults_to_shard_zero(self):
        scp, _ = _build(n_shards=2, num_cqs=4)
        owns0 = scp.shards[0].scheduler.cq_filter
        owns1 = scp.shards[1].scheduler.cq_filter
        assert owns0("brand-new-cq") is True
        assert owns1("brand-new-cq") is False
        scp.shutdown()

    def test_every_shard_owns_some_unit_here(self):
        scp, _ = _build(n_shards=2, num_cqs=4)
        assert all(scp.plan.units_of(i) for i in range(2))
        scp.shutdown()


# ----------------------------------------------------------------------
# kill / promote
# ----------------------------------------------------------------------

class TestKillPromote:
    def test_shards_admit_only_owned_cqs(self):
        scp, clock = _build()
        n = _wave(scp, 0, 4, 0)
        scp.cycle()
        assert _admitted(scp.plane) == n
        own0 = set(scp.plan.cqs_of(0))
        for wl in scp.plane.store.list("Workload", copy_objects=False):
            if not wlpkg.has_quota_reservation(wl):
                continue
            cq = wl.status.admission.cluster_queue
            # cq{i} drains through lq{i} -> cq{i}; ownership is by plan.
            expected = 0 if cq in own0 else 1
            assert scp.plan.cq_shard[cq] == expected
        scp.shutdown()

    def test_survivor_keeps_admitting_and_dead_admits_nothing(self):
        scp, clock = _build()
        n = _wave(scp, 0, 4, 0)
        scp.cycle()
        scp.kill_shard(0)
        before = [s.admitted_total for s in scp.shards]
        n = _wave(scp, 1, 4, n)
        scp.cycle()
        assert scp.shards[0].admitted_total == before[0]
        assert scp.shards[1].admitted_total > before[1]
        scp.shutdown()

    def test_promote_bumps_epoch_and_fences_zombie(self):
        scp, clock = _build()
        _wave(scp, 0, 4, 0)
        scp.cycle()
        zombie = scp.shards[0].token
        scp.kill_shard(0)
        promoted = scp.promote_shard(0)
        assert promoted.epoch == zombie.epoch + 1
        assert promoted.state == SHARD_ACTIVE
        assert not zombie.valid()
        saved = scp.store.fencing
        scp.store.fencing = zombie
        try:
            with pytest.raises(Fenced):
                scp.plane.store.create(_workload(99, 0, 999))
        finally:
            scp.store.fencing = saved
        scp.shutdown()

    def test_admitted_total_watermark_survives_promotion(self):
        scp, clock = _build()
        n = _wave(scp, 0, 4, 0)
        scp.cycle()
        total_before = scp.shards[0].admitted_total
        assert total_before > 0
        scp.kill_shard(0)
        # While killed, the counter neither doubles nor resets.
        assert scp.shards[0].admitted_total == total_before
        scp.promote_shard(0)
        assert scp.shards[0].admitted_total == total_before
        n = _wave(scp, 1, 4, n)
        scp.cycle()
        assert scp.shards[0].admitted_total > total_before
        # Exactly-once: counters sum to the store's admitted count
        # (valid for clean kills — no mid-cycle tear here).
        total = sum(s.admitted_total for s in scp.shards)
        assert total == _admitted(scp.plane)
        scp.shutdown()


# ----------------------------------------------------------------------
# scoped fault injection (satellite: per-manager arming)
# ----------------------------------------------------------------------

class TestScopedFaults:
    def test_crash_in_one_scope_spares_the_sibling(self):
        scp, clock = _build()
        n = _wave(scp, 0, 4, 0)
        faultinject.install(
            FaultInjector({faultinject.SITE_APPLY: {0: CRASH}}),
            scope="shard-0")
        try:
            before1 = scp.shards[1].admitted_total
            scp.cycle()
            assert scp.shards[0].state == SHARD_KILLED
            assert scp.shards[1].state == SHARD_ACTIVE
            assert scp.shards[1].admitted_total > before1
        finally:
            faultinject.uninstall(scope="shard-0")
        # Promote + resync: the mid-apply tear heals against the store
        # and everything still pending eventually admits exactly once.
        scp.promote_shard(0)
        for cycle in range(4):
            scp.cycle()
            clock.advance(1.0)
            scp.renew_leases()
        assert _admitted(scp.plane) == n
        from kueue_tpu.sim.scenarios import _usage_consistent
        ok, msg = _usage_consistent(scp.plane)
        assert ok, msg
        scp.shutdown()
        assert scp.plane.cache.live_handouts == 0

    def test_scoped_injector_never_fires_unscoped(self):
        inj = FaultInjector({faultinject.SITE_APPLY: {0: CRASH}})
        faultinject.install(inj, scope="shard-7")
        try:
            faultinject.site(faultinject.SITE_APPLY)  # no scope: no-op
            with pytest.raises(faultinject.InjectedCrash):
                with faultinject.scope("shard-7"):
                    faultinject.site(faultinject.SITE_APPLY)
        finally:
            faultinject.uninstall(scope="shard-7")


# ----------------------------------------------------------------------
# rebalance
# ----------------------------------------------------------------------

class TestRebalance:
    def test_move_fences_old_owner_and_new_owner_admits(self):
        scp, clock = _build()
        n = _wave(scp, 0, 4, 0)
        scp.cycle()
        unit = scp.plan.units_of(0)[0]
        old_epoch = scp.shards[0].epoch
        old_fp = scp.plan.fingerprint
        rep = scp.rebalance(unit, 1)
        assert rep["moved"] is True
        assert scp.shards[0].epoch == old_epoch + 1  # fenced + re-armed
        assert scp.plan.fingerprint != old_fp
        assert scp.plan.shard_of_unit[unit] == 1
        assert scp.rebalances == 1
        before = [s.admitted_total for s in scp.shards]
        n = _wave(scp, 1, 4, n)
        scp.cycle()
        moved_cqs = {cq for cq, u in
                     shard_units(scp.plane.cache).items() if u == unit}
        admitted_by_1 = scp.shards[1].admitted_total - before[1]
        # New owner picked up the moved cohort's traffic (its own plus
        # the moved unit's wave = one per owned CQ).
        assert admitted_by_1 == len(scp.plan.cqs_of(1))
        assert moved_cqs <= set(scp.plan.cqs_of(1))
        scp.shutdown()

    def test_noop_move_and_bad_args(self):
        scp, _ = _build()
        unit = scp.plan.units_of(0)[0]
        assert scp.rebalance(unit, 0)["moved"] is False
        with pytest.raises(ValueError):
            scp.rebalance("cohort:nope", 1)
        with pytest.raises(ValueError):
            scp.rebalance(unit, 9)
        scp.shutdown()


# ----------------------------------------------------------------------
# the catalog scenarios (tier-1 at smoke scale, seeds 0-2)
# ----------------------------------------------------------------------

class TestShardScenarios:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_shard_rebalance_smoke(self, seed):
        from kueue_tpu.sim.scenarios import run_scenario
        r = run_scenario("shard_rebalance", seed=seed, scale="smoke")
        assert r.ok, r.violations
        assert r.admitted == r.submitted
        assert r.counters["moves"]
        for mv in r.counters["moves"]:
            assert mv["ttfa_cycles"] is not None

    def test_shard_storm_smoke(self):
        from kueue_tpu.sim.scenarios import run_scenario
        r = run_scenario("shard_storm", seed=0, scale="smoke")
        assert r.ok, r.violations
        assert r.promotions > 0
        assert r.admitted == r.submitted

    @pytest.mark.slow
    @pytest.mark.parametrize("name", ["shard_storm", "shard_rebalance"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_full_scale(self, name, seed):
        from kueue_tpu.sim.scenarios import run_scenario
        r = run_scenario(name, seed=seed, scale="full")
        assert r.ok, r.violations
