"""Unit tests for the core model layer (resources, workload Info, heap,
hierarchy, podset, limitrange).

Mirrors the reference's colocated unit suites for pkg/resources,
pkg/workload, pkg/util/heap, pkg/hierarchy.
"""

import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import (
    Taint, Toleration, find_untolerated_taint, parse_quantity,
)
from kueue_tpu.api.meta import Condition, LabelSelector, LabelSelectorRequirement, set_condition
from kueue_tpu.core import limitrange, podset
from kueue_tpu.core import workload as wl
from kueue_tpu.core.hierarchy import Manager
from kueue_tpu.core.resources import FlavorResource, pod_effective_requests
from kueue_tpu.utils.heap import Heap
from tests.wrappers import WorkloadWrapper, make_flavor


class TestQuantity:
    def test_cpu_milli(self):
        assert parse_quantity("500m", "cpu") == 500
        assert parse_quantity("2", "cpu") == 2000
        assert parse_quantity(1.5, "cpu") == 1500

    def test_memory(self):
        assert parse_quantity("2Gi", "memory") == 2 * 1024**3
        assert parse_quantity("100M", "memory") == 100 * 10**6
        assert parse_quantity("1024", "memory") == 1024

    def test_count(self):
        assert parse_quantity(3, "pods") == 3
        assert parse_quantity("4", "nvidia.com/gpu") == 4


class TestRequests:
    def test_pod_effective_requests_max_of_init(self):
        w = (WorkloadWrapper("w").pod_set(count=1, cpu="1", memory="1Gi")).obj()
        spec = w.spec.pod_sets[0].template.spec
        from kueue_tpu.api.corev1 import Container
        spec.init_containers.append(Container(name="i", requests={"cpu": 3000}))
        reqs = pod_effective_requests(spec)
        assert reqs["cpu"] == 3000  # init max dominates the 1000m container sum
        assert reqs["memory"] == 1024**3

    def test_total_requests_scaled_by_count(self):
        w = WorkloadWrapper("w").pod_set(count=3, cpu="1").obj()
        info = wl.Info(w)
        assert info.total_requests[0].requests["cpu"] == 3000
        assert info.total_requests[0].count == 3

    def test_reclaimable_pods_reduce_count(self):
        w = WorkloadWrapper("w").pod_set(count=5, cpu="1").obj()
        w.status.reclaimable_pods.append(api.ReclaimablePod(name="main", count=2))
        info = wl.Info(w)
        assert info.total_requests[0].count == 3
        assert info.total_requests[0].requests["cpu"] == 3000

    def test_scaled_to(self):
        w = WorkloadWrapper("w").pod_set(count=4, cpu="500m").obj()
        info = wl.Info(w)
        scaled = info.total_requests[0].scaled_to(2)
        assert scaled.requests["cpu"] == 1000
        assert scaled.count == 2

    def test_requests_from_admission(self):
        w = WorkloadWrapper("w").pod_set(count=2, cpu="1").reserve("cq-a", "spot").obj()
        info = wl.Info(w)
        assert info.cluster_queue == "cq-a"
        assert info.total_requests[0].flavors["cpu"] == "spot"
        assert info.flavor_resource_usage()[FlavorResource("spot", "cpu")] == 2000

    def test_can_be_partially_admitted(self):
        w1 = WorkloadWrapper("w").pod_set(count=4, min_count=2, cpu="1").obj()
        assert wl.Info(w1).can_be_partially_admitted()
        w2 = WorkloadWrapper("w").pod_set(count=4, cpu="1").obj()
        assert not wl.Info(w2).can_be_partially_admitted()


class TestConditions:
    def test_quota_reservation_lifecycle(self):
        w = WorkloadWrapper("w").pod_set(count=1, cpu="1").obj()
        adm = api.Admission(cluster_queue="cq")
        wl.set_quota_reservation(w, adm, now=10.0)
        assert wl.has_quota_reservation(w)
        assert w.status.admission is adm
        assert wl.sync_admitted_condition(w, now=11.0)
        assert wl.is_admitted(w)
        changed = wl.unset_quota_reservation_with_condition(w, "Pending", "requeued", now=12.0)
        assert changed
        assert not wl.has_quota_reservation(w)
        assert not wl.is_admitted(w)
        assert w.status.admission is None

    def test_eviction_resets_on_new_reservation(self):
        w = WorkloadWrapper("w").pod_set(count=1, cpu="1").obj()
        wl.set_evicted_condition(w, api.EVICTED_BY_PREEMPTION, "bye", now=5.0)
        assert wl.is_evicted(w)
        wl.set_quota_reservation(w, api.Admission(cluster_queue="cq"), now=6.0)
        assert not wl.is_evicted(w)

    def test_admitted_requires_checks_ready(self):
        w = WorkloadWrapper("w").pod_set(count=1, cpu="1").obj()
        wl.set_quota_reservation(w, api.Admission(cluster_queue="cq"), now=1.0)
        w.status.admission_checks.append(api.AdmissionCheckState(name="prov", state=api.CHECK_STATE_PENDING))
        wl.sync_admitted_condition(w, now=2.0)
        assert not wl.is_admitted(w)
        w.status.admission_checks[0].state = api.CHECK_STATE_READY
        wl.sync_admitted_condition(w, now=3.0)
        assert wl.is_admitted(w)

    def test_ordering_eviction_timestamp(self):
        w = WorkloadWrapper("w").creation(100.0).pod_set(count=1, cpu="1").obj()
        ordering = wl.Ordering()
        assert ordering.queue_order_timestamp(w) == 100.0
        set_condition(w.status.conditions, Condition(
            type=api.WORKLOAD_EVICTED, status="True",
            reason=api.EVICTED_BY_PODS_READY_TIMEOUT), now=250.0)
        assert ordering.queue_order_timestamp(w) == 250.0
        assert wl.Ordering(pods_ready_requeuing_timestamp="Creation").queue_order_timestamp(w) == 100.0


class TestAdmissionCheckResolution:
    def test_per_flavor_strategy(self):
        w = WorkloadWrapper("w").pod_set(count=1, cpu="1").reserve("cq", flavor="spot").obj()
        checks = {"always": set(), "spot-only": {"spot"}, "ondemand-only": {"on-demand"}}
        assert wl.admission_checks_for_workload(w, checks) == {"always", "spot-only"}


class TestHeap:
    def test_ordering_and_update(self):
        h = Heap(key_func=lambda x: x[0], less_func=lambda a, b: a[1] < b[1])
        assert h.push_if_not_present(("a", 3))
        assert h.push_if_not_present(("b", 1))
        assert not h.push_if_not_present(("a", 0))  # present
        h.push_or_update(("c", 2))
        assert h.peek() == ("b", 1)
        h.push_or_update(("b", 10))  # reorder
        assert h.pop() == ("c", 2)
        assert h.delete("a")
        assert h.pop() == ("b", 10)
        assert h.pop() is None
        assert len(h) == 0


class TestHierarchy:
    def test_implicit_cohort_lifecycle(self):
        m = Manager(cohort_factory=lambda name: {"name": name})
        m.add_cluster_queue("cq1", "CQ1")
        m.add_cluster_queue("cq2", "CQ2")
        m.update_cluster_queue_edge("cq1", "team")
        m.update_cluster_queue_edge("cq2", "team")
        assert set(m.cohorts["team"].child_cqs) == {"cq1", "cq2"}
        m.update_cluster_queue_edge("cq1", "")
        assert "team" in m.cohorts
        m.delete_cluster_queue("cq2")
        assert "team" not in m.cohorts  # gc'd

    def test_explicit_cohort_tree(self):
        m = Manager(cohort_factory=lambda name: {})
        m.add_cohort("root")
        m.add_cohort("left")
        m.update_cohort_edge("left", "root")
        m.add_cluster_queue("cq", "CQ")
        m.update_cluster_queue_edge("cq", "left")
        assert m.root(m.cohort_of("cq")).name == "root"
        with pytest.raises(ValueError):
            m.update_cohort_edge("root", "left")  # cycle

    def test_cohort_survives_while_explicit(self):
        m = Manager(cohort_factory=lambda name: {})
        m.add_cohort("solo")
        assert "solo" in m.cohorts
        m.delete_cohort("solo")
        assert "solo" not in m.cohorts


class TestTaints:
    def test_untolerated(self):
        taints = [Taint(key="gpu", value="true", effect="NoSchedule")]
        assert find_untolerated_taint(taints, []) is not None
        tol = [Toleration(key="gpu", value="true", effect="NoSchedule")]
        assert find_untolerated_taint(taints, tol) is None
        tol_exists = [Toleration(key="gpu", operator="Exists")]
        assert find_untolerated_taint(taints, tol_exists) is None
        # PreferNoSchedule isn't blocking
        assert find_untolerated_taint([Taint(key="x", effect="PreferNoSchedule")], []) is None


class TestLabelSelector:
    def test_match(self):
        sel = LabelSelector(match_labels={"team": "a"},
                            match_expressions=[LabelSelectorRequirement(key="env", operator="In", values=["prod"])])
        assert sel.matches({"team": "a", "env": "prod"})
        assert not sel.matches({"team": "a", "env": "dev"})
        assert LabelSelector().matches({"anything": "x"})


class TestPodSet:
    def test_from_assignment_and_merge_restore(self):
        flavors = {"spot": make_flavor("spot", node_labels={"cloud/zone": "z1"})}
        psa = api.PodSetAssignment(name="main", flavors={"cpu": "spot"}, count=2)
        info = podset.from_assignment(psa, flavors, default_count=2)
        assert info.node_selector == {"cloud/zone": "z1"}

        w = WorkloadWrapper("w").pod_set(count=2, cpu="1").obj()
        tpl = w.spec.pod_sets[0].template
        original = podset.snapshot_template("main", 2, tpl)
        podset.merge_into_template(tpl, info)
        assert tpl.spec.node_selector == {"cloud/zone": "z1"}
        assert podset.restore_template(tpl, original)
        assert tpl.spec.node_selector == {}

    def test_merge_conflict_is_permanent(self):
        flavors = {"a": make_flavor("a", node_labels={"k": "1"}),
                   "b": make_flavor("b", node_labels={"k": "2"})}
        psa = api.PodSetAssignment(name="main", flavors={"cpu": "a", "memory": "b"}, count=1)
        with pytest.raises(podset.PermanentError):
            podset.from_assignment(psa, flavors, default_count=1)


class TestLimitRange:
    def test_summarize_and_validate(self):
        lr = limitrange.LimitRange(namespace="ns", name="lr", limits=[
            limitrange.LimitRangeItem(type="Container", min={"cpu": 100}, max={"cpu": 2000})])
        summary = limitrange.summarize(lr)
        ok = WorkloadWrapper("w").pod_set(count=1, cpu="1").obj()
        assert limitrange.validate_pod_spec(ok.spec.pod_sets[0].template.spec, summary) == []
        bad = WorkloadWrapper("w").pod_set(count=1, cpu="3").obj()
        assert limitrange.validate_pod_spec(bad.spec.pod_sets[0].template.spec, summary) != []

    def test_defaults_applied(self):
        lr = limitrange.LimitRange(limits=[
            limitrange.LimitRangeItem(type="Container", default_request={"cpu": 250})])
        w = api.Workload()
        from kueue_tpu.api.corev1 import Container, PodSpec
        spec = PodSpec(containers=[Container(name="c")])
        limitrange.apply_defaults(spec, limitrange.summarize(lr))
        assert spec.containers[0].requests["cpu"] == 250


class TestCloneWorkload:
    def test_matches_deepcopy_on_maximal_object(self):
        import copy
        from kueue_tpu.api import kueue as api
        from kueue_tpu.api.corev1 import (
            Affinity, Container, NodeAffinity, NodeSelector,
            NodeSelectorRequirement, NodeSelectorTerm, PodSpec,
            PodTemplateSpec, Toleration)
        from kueue_tpu.api.meta import Condition, ObjectMeta, OwnerReference

        wl = api.Workload(metadata=ObjectMeta(
            name="w", namespace="ns", uid="u1", generation=3,
            resource_version=17, creation_timestamp=1.5,
            deletion_timestamp=9.0, labels={"a": "b"},
            annotations={"c": "d"}, finalizers=["f1"],
            owner_references=[OwnerReference(api_version="v1", kind="Job",
                                             name="j", uid="ju",
                                             controller=True)]))
        spec = PodSpec(
            containers=[Container(name="c", requests={"cpu": 100},
                                  limits={"cpu": 200})],
            init_containers=[Container(name="i", requests={"mem": 5})],
            node_selector={"zone": "a"},
            tolerations=[Toleration(key="k", operator="Exists", value="v",
                                    effect="NoSchedule")],
            affinity=Affinity(node_affinity=NodeAffinity(
                required=NodeSelector(node_selector_terms=[
                    NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(key="x", operator="In",
                                                values=["1", "2"])])]))),
            priority_class_name="pc", priority=7,
            scheduling_gates=["g"], restart_policy="Always",
            overhead={"cpu": 1})
        wl.spec.pod_sets = [api.PodSet(
            name="main", count=4, min_count=2,
            template=PodTemplateSpec(labels={"l": "1"},
                                     annotations={"an": "2"}, spec=spec))]
        wl.spec.queue_name = "q"
        wl.spec.priority = 5
        wl.spec.priority_class_name = "wpc"
        wl.spec.priority_class_source = "kueue.x-k8s.io/workloadpriorityclass"
        wl.spec.active = False
        wl.status.conditions = [Condition(type="QuotaReserved", status="True",
                                          reason="r", message="m",
                                          last_transition_time=2.0,
                                          observed_generation=3)]
        wl.status.admission = api.Admission(
            cluster_queue="cq",
            pod_set_assignments=[api.PodSetAssignment(
                name="main", flavors={"cpu": "f0"},
                resource_usage={"cpu": 400}, count=4)])
        wl.status.requeue_state = api.RequeueState(count=2, requeue_at=8.0)
        wl.status.reclaimable_pods = [api.ReclaimablePod(name="main", count=1)]
        wl.status.admission_checks = [api.AdmissionCheckState(
            name="chk", state=api.CHECK_STATE_READY, message="ok",
            last_transition_time=3.0,
            pod_set_updates=[api.PodSetUpdate(
                name="main", labels={"x": "y"}, annotations={"p": "q"},
                node_selector={"n": "s"},
                tolerations=[Toleration(key="t")])])]

        clone = api.clone_workload(wl)
        assert clone == copy.deepcopy(wl)
        assert clone is not wl

        # no aliasing anywhere: mutate every mutable corner of the clone
        clone.metadata.labels["a"] = "zz"
        clone.spec.pod_sets[0].template.spec.containers[0].requests["cpu"] = 1
        clone.spec.pod_sets[0].template.spec.tolerations[0].key = "zz"
        clone.spec.pod_sets[0].template.spec.affinity.node_affinity.required \
            .node_selector_terms[0].match_expressions[0].values.append("3")
        clone.status.conditions[0].status = "False"
        clone.status.admission.pod_set_assignments[0].flavors["cpu"] = "f9"
        clone.status.admission_checks[0].pod_set_updates[0].labels["x"] = "n"
        clone.status.requeue_state.count = 99
        assert wl.metadata.labels["a"] == "b"
        assert wl.spec.pod_sets[0].template.spec.containers[0].requests["cpu"] == 100
        assert wl.status.conditions[0].status == "True"
        assert wl.status.admission.pod_set_assignments[0].flavors["cpu"] == "f0"

    def test_cq_and_lq_clones_match_deepcopy(self):
        import copy
        from kueue_tpu.api import kueue as api
        from kueue_tpu.api.meta import (Condition, LabelSelector,
                                        LabelSelectorRequirement, ObjectMeta)
        cq = api.ClusterQueue(metadata=ObjectMeta(name="cq", uid="u",
                                                  labels={"a": "b"}))
        cq.spec.cohort = "co"
        cq.spec.queueing_strategy = api.STRICT_FIFO
        cq.spec.namespace_selector = LabelSelector(
            match_labels={"t": "x"},
            match_expressions=[LabelSelectorRequirement(
                key="k", operator="In", values=["v1"])])
        cq.spec.preemption = api.ClusterQueuePreemption(
            reclaim_within_cohort=api.PREEMPTION_ANY,
            borrow_within_cohort=api.BorrowWithinCohort(
                policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY,
                max_priority_threshold=4),
            within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        cq.spec.resource_groups = [api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=5,
                                  borrowing_limit=2, lending_limit=1)])])]
        cq.spec.admission_checks = ["chk"]
        cq.spec.admission_checks_strategy = [
            api.AdmissionCheckStrategyRule(name="s", on_flavors=["f0"])]
        cq.spec.fair_sharing = api.FairSharing(weight=500)
        cq.status.conditions = [Condition(type="Active", status="True")]
        cq.status.flavors_reservation = [api.FlavorUsage(
            name="f0", resources=[api.ResourceUsage(name="cpu", total=3,
                                                    borrowed=1)])]
        cq.status.pending_workloads = 7
        clone = api.clone_cluster_queue(cq)
        assert clone == copy.deepcopy(cq)
        clone.spec.resource_groups[0].flavors[0].resources[0].nominal_quota = 9
        clone.status.flavors_reservation[0].resources[0].total = 0
        clone.spec.namespace_selector.match_labels["t"] = "z"
        assert cq.spec.resource_groups[0].flavors[0].resources[0].nominal_quota == 5
        assert cq.status.flavors_reservation[0].resources[0].total == 3
        assert cq.spec.namespace_selector.match_labels["t"] == "x"

        lq = api.LocalQueue(metadata=ObjectMeta(name="lq", namespace="ns"))
        lq.spec.cluster_queue = "cq"
        lq.status.conditions = [Condition(type="Active", status="True")]
        lq.status.flavors_usage = [api.FlavorUsage(
            name="f0", resources=[api.ResourceUsage(name="cpu", total=2)])]
        lclone = api.clone_local_queue(lq)
        assert lclone == copy.deepcopy(lq)
        lclone.status.flavors_usage[0].resources[0].total = 9
        assert lq.status.flavors_usage[0].resources[0].total == 2


class TestParallelize:
    """reference: pkg/util/parallelize/parallelize.go:17-40."""

    def test_runs_every_index_parallel_and_sequential(self):
        from kueue_tpu.utils import parallelize
        for workers in (1, 8):
            seen = set()
            lock = __import__("threading").Lock()

            def fn(i):
                with lock:
                    seen.add(i)

            parallelize.until(100, fn, workers=workers)
            assert seen == set(range(100))

    def test_first_error_reraised_after_all_items_attempted(self):
        from kueue_tpu.utils import parallelize
        attempted = []
        lock = __import__("threading").Lock()

        def fn(i):
            with lock:
                attempted.append(i)
            if i % 3 == 0:
                raise ValueError(i)

        with pytest.raises(ValueError):
            parallelize.until(30, fn, workers=8)
        assert len(attempted) == 30

    def test_nested_until_inside_workers_does_not_deadlock(self):
        # ADVICE r5 low: a nested until(workers>1) from inside a shared-
        # pool worker could exhaust the bounded 8-thread pool (every
        # thread blocked on futures with no free thread to run them).
        # The re-entrancy guard degrades nested calls to the sequential
        # path; run under a watchdog so a regression fails instead of
        # hanging the suite.
        import threading

        from kueue_tpu.utils import parallelize
        inner_runs = []
        lock = threading.Lock()

        def outer(i):
            def inner(j):
                with lock:
                    inner_runs.append((i, j))
            parallelize.until(4, inner, workers=4)

        done = threading.Event()
        failure = []

        def drive():
            try:
                parallelize.until(16, outer, workers=8)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                failure.append(exc)
            finally:
                done.set()

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        assert done.wait(timeout=30), "nested until() deadlocked"
        assert not failure, failure
        assert len(inner_runs) == 16 * 4
