"""Preemption target-selection depth suite.

Transliteration of the reference's pkg/scheduler/preemption/preemption_test.go
table cases (TestPreemption:284-1438, TestFairPreemptions:1479-1987,
TestCandidatesOrdering:1993-2040) driving Preemptor.get_targets_internal
directly against a cache snapshot, exactly as the reference drives
GetTargets with a fixed flavor assignment.
"""

import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import parse_quantity
from kueue_tpu.api.meta import Condition, FakeClock, set_condition
from kueue_tpu.cache import Cache
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler.preemption import Preemptor, parse_strategies
from tests.wrappers import (
    ClusterQueueWrapper,
    WorkloadWrapper,
    flavor_quotas,
    make_flavor,
)

NOW = 1000.0
CPU = "cpu"
MEM = "memory"

IN_CQ = api.IN_CLUSTER_QUEUE_REASON
RECLAIM = api.IN_COHORT_RECLAMATION_REASON
FAIR = api.IN_COHORT_FAIR_SHARING_REASON
WHILE_BORROWING = api.IN_COHORT_RECLAIM_WHILE_BORROWING_REASON


def bwc(threshold=0):
    return api.BorrowWithinCohort(
        policy=api.BORROW_WITHIN_COHORT_LOWER_PRIORITY,
        max_priority_threshold=threshold)


def preemption_fixture_cqs():
    """The reference's ClusterQueue fixture list (preemption_test.go:71-277)."""
    return [
        ClusterQueueWrapper("standalone")
        .resource_group(flavor_quotas("default", cpu="6"))
        .resource_group(flavor_quotas("alpha", memory="3Gi"),
                        flavor_quotas("beta", memory="3Gi"))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("c1").cohort("cohort")
        .resource_group(flavor_quotas("default", cpu=("6", "6"),
                                      memory=("3Gi", "3Gi")))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("c2").cohort("cohort")
        .resource_group(flavor_quotas("default", cpu=("6", "6"),
                                      memory=("3Gi", "3Gi")))
        .preemption(within_cluster_queue=api.PREEMPTION_NEVER,
                    reclaim_within_cohort=api.PREEMPTION_ANY).obj(),

        ClusterQueueWrapper("d1").cohort("cohort-no-limits")
        .resource_group(flavor_quotas("default", cpu="6", memory="3Gi"))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("d2").cohort("cohort-no-limits")
        .resource_group(flavor_quotas("default", cpu="6", memory="3Gi"))
        .preemption(within_cluster_queue=api.PREEMPTION_NEVER,
                    reclaim_within_cohort=api.PREEMPTION_ANY).obj(),

        ClusterQueueWrapper("l1").cohort("legion")
        .resource_group(flavor_quotas("default", cpu=("6", "12"),
                                      memory=("3Gi", "6Gi")))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("preventStarvation")
        .resource_group(flavor_quotas("default", cpu="6"))
        .preemption(
            within_cluster_queue=api.PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY)
        .obj(),

        ClusterQueueWrapper("a_standard").cohort("with_shared_cq")
        .resource_group(flavor_quotas("default", cpu=("1", "12")))
        .preemption(within_cluster_queue=api.PREEMPTION_NEVER,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                    borrow_within_cohort=bwc(0)).obj(),

        ClusterQueueWrapper("b_standard").cohort("with_shared_cq")
        .resource_group(flavor_quotas("default", cpu=("1", "12")))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_ANY,
                    borrow_within_cohort=bwc(0)).obj(),

        ClusterQueueWrapper("a_best_effort").cohort("with_shared_cq")
        .resource_group(flavor_quotas("default", cpu=("1", "12")))
        .preemption(within_cluster_queue=api.PREEMPTION_NEVER,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                    borrow_within_cohort=bwc(0)).obj(),

        ClusterQueueWrapper("b_best_effort").cohort("with_shared_cq")
        .resource_group(flavor_quotas("default", cpu=("0", "13")))
        .preemption(within_cluster_queue=api.PREEMPTION_NEVER,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY,
                    borrow_within_cohort=bwc(0)).obj(),

        ClusterQueueWrapper("shared").cohort("with_shared_cq")
        .resource_group(flavor_quotas("default", cpu="10")).obj(),

        ClusterQueueWrapper("lend1").cohort("cohort-lend")
        .resource_group(flavor_quotas("default", cpu=("6", None, "4")))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("lend2").cohort("cohort-lend")
        .resource_group(flavor_quotas("default", cpu=("6", None, "2")))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_LOWER_PRIORITY).obj(),

        ClusterQueueWrapper("a").cohort("cohort-three")
        .resource_group(flavor_quotas("default", cpu="2", memory="2"))
        .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                    reclaim_within_cohort=api.PREEMPTION_ANY).obj(),

        ClusterQueueWrapper("b").cohort("cohort-three")
        .resource_group(flavor_quotas("default", cpu="2", memory="2")).obj(),

        ClusterQueueWrapper("c").cohort("cohort-three")
        .resource_group(flavor_quotas("default", cpu="2", memory="2")).obj(),
    ]


def admitted(name, cq, priority=0, reserved_at=NOW, creation=NOW,
             flavor="default", **requests):
    w = (WorkloadWrapper(name).priority(priority).creation(creation))
    w.pod_set(count=1, **requests)
    w.reserve(cq, flavor=flavor, now=reserved_at)
    w.wl.metadata.uid = name  # predictable candidate ordering, as the reference
    return w.obj()


def run_targets(cqs, admitted_wls, incoming, target_cq, assignment,
                fair=False, strategies=None):
    """assignment: resource -> (flavor, mode) with mode in {"fit","preempt"};
    requests come from the incoming workload's podset totals, mirroring
    assignment.TotalRequestsFor (reference: flavorassigner.go:101-107)."""
    cache = Cache()
    for f in ("default", "alpha", "beta"):
        cache.add_or_update_resource_flavor(make_flavor(f))
    for cq in cqs:
        cache.add_cluster_queue(cq)
    for wl in admitted_wls:
        cache.add_or_update_workload(wl)
    snapshot = cache.snapshot()

    info = wlpkg.Info(incoming, cluster_queue=target_cq)
    requests = {}
    for psr in info.total_requests:
        for res, qty in psr.requests.items():
            flavor = assignment[res][0] if res in assignment else "default"
            fr = FlavorResource(flavor, res)
            requests[fr] = requests.get(fr, 0) + qty
    frs_need_preemption = {FlavorResource(flv, res)
                           for res, (flv, mode) in assignment.items()
                           if mode == "preempt"}

    preemptor = Preemptor(clock=FakeClock(NOW), enable_fair_sharing=fair,
                          fs_strategies=parse_strategies(strategies))
    targets = preemptor.get_targets_internal(
        info, requests, frs_need_preemption, snapshot)
    return {(t.workload_info.obj.metadata.name, t.reason) for t in targets}


def incoming_wl(name="in", priority=0, creation=NOW, pod_sets=None, **requests):
    w = WorkloadWrapper(name).priority(priority).creation(creation)
    if pod_sets:
        for ps_name, count, reqs in pod_sets:
            w.pod_set(name=ps_name, count=count, **reqs)
    else:
        w.pod_set(count=1, **requests)
    return w.obj()


P = {CPU: ("default", "preempt")}


class TestPreemptionTargets:
    """preemption_test.go TestPreemption:284-1438."""

    def test_preempt_lowest_priority(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="2"),
             admitted("mid", "standalone", priority=0, cpu="2"),
             admitted("high", "standalone", priority=1, cpu="2")],
            incoming_wl(priority=1, cpu="2"), "standalone", P)
        assert got == {("low", IN_CQ)}

    def test_preempt_multiple(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="2"),
             admitted("mid", "standalone", priority=0, cpu="2"),
             admitted("high", "standalone", priority=1, cpu="2")],
            incoming_wl(priority=1, cpu="3"), "standalone", P)
        assert got == {("low", IN_CQ), ("mid", IN_CQ)}

    def test_no_preemption_for_low_priority(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="3"),
             admitted("mid", "standalone", priority=0, cpu="3")],
            incoming_wl(priority=-1, cpu="1"), "standalone", P)
        assert got == set()

    def test_not_enough_low_priority_workloads(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="3"),
             admitted("mid", "standalone", priority=0, cpu="3")],
            incoming_wl(priority=0, cpu="4"), "standalone", P)
        assert got == set()

    def test_some_free_quota_preempt_low_priority(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="1"),
             admitted("mid", "standalone", priority=0, cpu="1"),
             admitted("high", "standalone", priority=1, cpu="3")],
            incoming_wl(priority=1, cpu="2"), "standalone", P)
        assert got == {("low", IN_CQ)}

    def test_minimal_set_excludes_low_priority(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, cpu="1"),
             admitted("mid", "standalone", priority=0, cpu="2"),
             admitted("high", "standalone", priority=1, cpu="3")],
            incoming_wl(priority=1, cpu="2"), "standalone", P)
        assert got == {("mid", IN_CQ)}

    def test_only_preempt_workloads_using_chosen_flavor(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("low", "standalone", priority=-1, flavor="alpha",
                      memory="2Gi"),
             admitted("mid", "standalone", priority=0, flavor="beta",
                      memory="1Gi"),
             admitted("high", "standalone", priority=1, flavor="beta",
                      memory="1Gi")],
            incoming_wl(priority=1, cpu="1", memory="2Gi"), "standalone",
            {CPU: ("default", "fit"), MEM: ("beta", "preempt")})
        assert got == {("mid", IN_CQ)}

    def test_reclaim_quota_from_borrower(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-low", "c1", priority=-1, cpu="3"),
             admitted("c2-mid", "c2", priority=0, cpu="3"),
             admitted("c2-high", "c2", priority=1, cpu="6")],
            incoming_wl(priority=1, cpu="3"), "c1", P)
        assert got == {("c2-mid", RECLAIM)}

    def test_reclaim_with_zero_request_for_resource_at_nominal(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-low", "c1", priority=-1, cpu="3", memory="3Gi"),
             admitted("c2-mid", "c2", priority=0, cpu="3"),
             admitted("c2-high", "c2", priority=1, cpu="6")],
            incoming_wl(priority=1, cpu="3", memory="0"), "c1",
            {CPU: ("default", "preempt"), MEM: ("default", "fit")})
        assert got == {("c2-mid", RECLAIM)}

    def test_no_workloads_borrowing(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-high", "c1", priority=1, cpu="4"),
             admitted("c2-low-1", "c2", priority=-1, cpu="4")],
            incoming_wl(priority=1, cpu="4"), "c1", P)
        assert got == set()

    def test_not_enough_workloads_borrowing(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-high", "c1", priority=1, cpu="4"),
             admitted("c2-low-1", "c2", priority=-1, cpu="4"),
             admitted("c2-low-2", "c2", priority=-1, cpu="4")],
            incoming_wl(priority=1, cpu="4"), "c1", P)
        assert got == set()

    def test_preempt_locally_borrow_other_resources_no_cohort_candidates(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-low", "c1", priority=-1, cpu="4"),
             admitted("c2-low-1", "c2", priority=-1, cpu="4"),
             admitted("c2-high-2", "c2", priority=1, cpu="4")],
            incoming_wl(priority=1, cpu="4", memory="5Gi"), "c1",
            {CPU: ("default", "preempt"), MEM: ("default", "preempt")})
        assert got == {("c1-low", IN_CQ)}

    def test_preempt_locally_and_borrow_same_resource_in_cohort(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-med", "c1", priority=0, cpu="4"),
             admitted("c1-low", "c1", priority=-1, cpu="4"),
             admitted("c2-low-1", "c2", priority=-1, cpu="4")],
            incoming_wl(priority=1, cpu="4"), "c1", P)
        assert got == {("c1-low", IN_CQ)}

    def test_preempt_locally_borrow_same_resource_no_borrowing_limit(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("d1-med", "d1", priority=0, cpu="4"),
             admitted("d1-low", "d1", priority=-1, cpu="4"),
             admitted("d2-low-1", "d2", priority=-1, cpu="4")],
            incoming_wl(priority=1, cpu="4"), "d1", P)
        assert got == {("d1-low", IN_CQ)}

    def test_preempt_locally_borrow_other_resources_with_cohort_candidates(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-med", "c1", priority=0, cpu="4"),
             admitted("c2-low-1", "c2", priority=-1, cpu="5"),
             admitted("c2-low-2", "c2", priority=-1, cpu="1"),
             admitted("c2-low-3", "c2", priority=-1, cpu="1")],
            incoming_wl(priority=1, cpu="2", memory="5Gi"), "c1",
            {CPU: ("default", "preempt"), MEM: ("default", "preempt")})
        assert got == {("c1-med", IN_CQ)}

    def test_preempt_locally_not_borrowing_in_single_queue_cohort(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("l1-med", "l1", priority=0, cpu="4"),
             admitted("l1-low", "l1", priority=-1, cpu="2")],
            incoming_wl(priority=1, cpu="4"), "l1", P)
        assert got == {("l1-med", IN_CQ)}

    def test_no_reclaim_same_priority_with_lower_priority_policy(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1w", "c1", priority=0, cpu="2"),
             admitted("c2-1", "c2", priority=0, cpu="4"),
             admitted("c2-2", "c2", priority=0, cpu="4")],
            incoming_wl(priority=0, cpu="4"), "c1", P)
        assert got == set()

    def test_reclaim_same_priority_with_any_policy(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-1", "c1", priority=0, cpu="4"),
             admitted("c1-2", "c1", priority=1, cpu="4"),
             admitted("c2w", "c2", priority=0, cpu="2")],
            incoming_wl(priority=0, cpu="4"), "c2", P)
        assert got == {("c1-1", RECLAIM)}

    def test_preempt_from_all_cluster_queues_in_cohort(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c1-low", "c1", priority=-1, cpu="3"),
             admitted("c1-mid", "c1", priority=0, cpu="2"),
             admitted("c2-low", "c2", priority=-1, cpu="3"),
             admitted("c2-mid", "c2", priority=0, cpu="4")],
            incoming_wl(priority=0, cpu="4"), "c1", P)
        assert got == {("c1-low", IN_CQ), ("c2-low", RECLAIM)}

    def test_cannot_preempt_in_cq_when_policy_never(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("c2-low", "c2", priority=-1, cpu="3")],
            incoming_wl(priority=1, cpu="4"), "c2", P)
        assert got == set()

    def test_each_podset_preempts_a_different_flavor(self):
        cqs = preemption_fixture_cqs()
        admitted_wls = [
            admitted("low-alpha", "standalone", priority=-1, flavor="alpha",
                     memory="2Gi"),
            admitted("low-beta", "standalone", priority=-1, flavor="beta",
                     memory="2Gi")]
        incoming = incoming_wl(pod_sets=[
            ("launcher", 1, {"memory": "2Gi"}),
            ("workers", 2, {"memory": "1Gi"})])
        # per-podset flavors: launcher->alpha, workers->beta (both Preempt)
        cache = Cache()
        for f in ("default", "alpha", "beta"):
            cache.add_or_update_resource_flavor(make_flavor(f))
        for cq in cqs:
            cache.add_cluster_queue(cq)
        for wl in admitted_wls:
            cache.add_or_update_workload(wl)
        snapshot = cache.snapshot()
        info = wlpkg.Info(incoming, cluster_queue="standalone")
        requests = {FlavorResource("alpha", MEM): parse_quantity("2Gi", MEM),
                    FlavorResource("beta", MEM): parse_quantity("2Gi", MEM)}
        frs = set(requests)
        preemptor = Preemptor(clock=FakeClock(NOW))
        targets = preemptor.get_targets_internal(info, requests, frs, snapshot)
        got = {(t.workload_info.obj.metadata.name, t.reason) for t in targets}
        assert got == {("low-alpha", IN_CQ), ("low-beta", IN_CQ)}

    def test_preempt_newer_workloads_with_same_priority(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("wl1", "preventStarvation", priority=2, cpu="2"),
             admitted("wl2", "preventStarvation", priority=1, cpu="2",
                      reserved_at=NOW + 1),
             admitted("wl3", "preventStarvation", priority=1, cpu="2")],
            incoming_wl(priority=1, creation=NOW - 15, cpu="2"),
            "preventStarvation", P)
        assert got == {("wl2", IN_CQ)}

    # --- BorrowWithinCohort (preemption_test.go:977-1136) ---

    def test_bwc_preempt_lower_priority_from_other_cq_while_borrowing(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a_best_effort_low", "a_best_effort", priority=-1,
                      cpu="10"),
             admitted("b_best_effort_low", "b_best_effort", priority=-1,
                      cpu="1")],
            incoming_wl(priority=0, cpu="10"), "a_standard", P)
        assert got == {("a_best_effort_low", WHILE_BORROWING)}

    def test_bwc_no_preempt_above_threshold_if_still_borrowing(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("b_standard_wl", "b_standard", priority=1, cpu="10")],
            incoming_wl(priority=2, cpu="10"), "a_standard", P)
        assert got == set()

    def test_bwc_preempt_above_threshold_if_no_borrowing_after(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("b_standard_wl", "b_standard", priority=1, cpu="13")],
            incoming_wl(priority=2, cpu="1"), "a_standard", P)
        assert got == {("b_standard_wl", RECLAIM)}

    def test_bwc_no_preempt_lower_priority_same_cq(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a_standard_wl", "a_standard", priority=1, cpu="13")],
            incoming_wl(priority=2, cpu="1"), "a_standard", P)
        assert got == set()

    def test_bwc_preempt_in_cq_when_no_candidates_below_threshold(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a_standard_1", "a_standard", priority=1, cpu="10"),
             admitted("a_standard_2", "a_standard", priority=1, cpu="1"),
             admitted("b_standard_1", "b_standard", priority=1, cpu="1"),
             admitted("b_standard_2", "b_standard", priority=2, cpu="1")],
            incoming_wl(priority=3, cpu="1"), "b_standard", P)
        assert got == {("b_standard_1", IN_CQ)}

    def test_bwc_preempt_from_cq_and_other_cqs_below_threshold(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("b_standard_high", "b_standard", priority=2, cpu="10"),
             admitted("b_standard_mid", "b_standard", priority=1, cpu="1"),
             admitted("a_best_effort_low", "a_best_effort", priority=-1,
                      cpu="1"),
             admitted("a_best_effort_lower", "a_best_effort", priority=-2,
                      cpu="1")],
            incoming_wl(priority=2, cpu="2"), "b_standard", P)
        assert got == {("b_standard_mid", IN_CQ),
                       ("a_best_effort_lower", WHILE_BORROWING)}

    # --- lending limits (preemption_test.go:1137-1219) ---

    def test_reclaim_quota_from_lender(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("lend1-low", "lend1", priority=-1, cpu="3"),
             admitted("lend2-mid", "lend2", priority=0, cpu="3"),
             admitted("lend2-high", "lend2", priority=1, cpu="4")],
            incoming_wl(priority=1, cpu="3"), "lend1", P)
        assert got == {("lend2-mid", RECLAIM)}

    def test_preempt_from_all_cqs_in_cohort_lend(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("lend1-low", "lend1", priority=-1, cpu="3"),
             admitted("lend1-mid", "lend1", priority=0, cpu="2"),
             admitted("lend2-low", "lend2", priority=-1, cpu="3"),
             admitted("lend2-mid", "lend2", priority=0, cpu="4")],
            incoming_wl(priority=0, cpu="4"), "lend1", P)
        assert got == {("lend1-low", IN_CQ), ("lend2-low", RECLAIM)}

    def test_cannot_preempt_beyond_lending_limit(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("lend2-low", "lend2", priority=-1, cpu="10")],
            incoming_wl(priority=0, cpu="9"), "lend1", P)
        assert got == set()

    # --- exhausted-queue interplay (preemption_test.go:1220-1437) ---

    def test_preempt_in_cq_when_target_exhausted_single_resource(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a1", "a", priority=-2, cpu="1"),
             admitted("a2", "a", priority=-2, cpu="1"),
             admitted("a3", "a", priority=-1, cpu="1"),
             admitted("b1", "b", priority=0, cpu="1"),
             admitted("b2", "b", priority=0, cpu="1"),
             admitted("b3", "b", priority=0, cpu="1")],
            incoming_wl(priority=0, cpu="2"), "a", P)
        assert got == {("a1", IN_CQ), ("a2", IN_CQ)}

    def test_preempt_in_cq_when_target_exhausted_two_resources(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a1", "a", priority=-2, cpu="1", memory="1"),
             admitted("a2", "a", priority=-2, cpu="1", memory="1"),
             admitted("a3", "a", priority=-1, cpu="1", memory="1"),
             admitted("b1", "b", priority=0, cpu="1", memory="1"),
             admitted("b2", "b", priority=0, cpu="1", memory="1"),
             admitted("b3", "b", priority=0, cpu="1", memory="1")],
            incoming_wl(priority=0, cpu="2", memory="2"), "a",
            {CPU: ("default", "preempt"), MEM: ("default", "preempt")})
        assert got == {("a1", IN_CQ), ("a2", IN_CQ)}

    def test_preempt_in_cq_when_exhausted_for_one_resource_not_other(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a1", "a", priority=-2, cpu="1"),
             admitted("a2", "a", priority=-2, cpu="1"),
             admitted("a3", "a", priority=-1, cpu="1"),
             admitted("b1", "b", priority=0, cpu="1"),
             admitted("b2", "b", priority=0, cpu="1"),
             admitted("b3", "b", priority=0, cpu="1")],
            incoming_wl(priority=0, cpu="2", memory="2"), "a",
            {CPU: ("default", "preempt"), MEM: ("default", "preempt")})
        assert got == {("a1", IN_CQ), ("a2", IN_CQ)}

    def test_preempt_from_others_when_target_not_exhausted(self):
        got = run_targets(
            preemption_fixture_cqs(),
            [admitted("a1", "a", priority=-1, cpu="1"),
             admitted("b1", "b", priority=0, cpu="1"),
             admitted("b2", "b", priority=0, cpu="1"),
             admitted("b3", "b", priority=0, cpu="1"),
             admitted("b4", "b", priority=0, cpu="1"),
             admitted("b5", "b", priority=-1, cpu="1")],
            incoming_wl(priority=0, cpu="2"), "a", P)
        assert got == {("a1", IN_CQ), ("b5", RECLAIM)}


def fair_fixture_cqs(weights=None):
    """TestFairPreemptions base CQs (preemption_test.go:1483-1530)."""
    weights = weights or {}

    def cq(name):
        w = (ClusterQueueWrapper(name).cohort("all")
             .resource_group(flavor_quotas("default", cpu="3"))
             .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                         reclaim_within_cohort=api.PREEMPTION_ANY,
                         borrow_within_cohort=bwc(-3)))
        if name in weights:
            w.fair_weight(weights[name])
        return w.obj()

    preemptible = (ClusterQueueWrapper("preemptible").cohort("all")
                   .resource_group(flavor_quotas("default", cpu="0")).obj())
    return [cq("a"), cq("b"), cq("c"), preemptible]


def plain_fair_cqs(weights=None):
    """The no-borrowWithinCohort variant used by the weight cases
    (preemption_test.go:1806-1955)."""
    weights = weights or {}
    out = []
    for name in ("a", "b", "c"):
        w = (ClusterQueueWrapper(name).cohort("all")
             .resource_group(flavor_quotas("default", cpu="3"))
             .preemption(within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
                         reclaim_within_cohort=api.PREEMPTION_ANY))
        if name in weights:
            w.fair_weight(weights[name])
        out.append(w.obj())
    return out


def units(cq_name, prefix, n, start=1, priority=0):
    return [admitted(f"{prefix}{i}", cq_name, priority=priority, cpu="1")
            for i in range(start, start + n)]


class TestFairPreemptions:
    """preemption_test.go TestFairPreemptions:1479-1987."""

    def test_reclaim_nominal_from_user_using_the_most(self):
        got = run_targets(
            fair_fixture_cqs(),
            units("a", "a", 3) + units("b", "b", 5) + units("c", "c", 1),
            incoming_wl("c_incoming", cpu="1"), "c", P, fair=True)
        assert got == {("b1", FAIR)}

    def test_reclaim_from_queue_using_less_when_latest_not_enough(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="3"),
             admitted("a2", "a", cpu="1"),
             admitted("b1", "b", cpu="2"),
             admitted("b2", "b", cpu="3")],
            incoming_wl("c_incoming", cpu="3"), "c", P, fair=True)
        assert got == {("a1", FAIR)}

    def test_reclaim_borrowable_quota_from_user_using_the_most(self):
        got = run_targets(
            fair_fixture_cqs(),
            units("a", "a", 3) + units("b", "b", 5) + units("c", "c", 1),
            incoming_wl("a_incoming", cpu="1"), "a", P, fair=True)
        assert got == {("b1", FAIR)}

    def test_preempt_one_from_each_cq_borrowing(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="0.5"),
             admitted("a2", "a", cpu="0.5"),
             admitted("a3", "a", cpu="3"),
             admitted("b1", "b", cpu="0.5"),
             admitted("b2", "b", cpu="0.5"),
             admitted("b3", "b", cpu="3")],
            incoming_wl("c_incoming", cpu="2"), "c", P, fair=True)
        assert got == {("a1", FAIR), ("b1", FAIR)}

    def test_cannot_preempt_when_everyone_under_nominal(self):
        got = run_targets(
            fair_fixture_cqs(),
            units("a", "a", 3) + units("b", "b", 3) + units("c", "c", 3),
            incoming_wl("c_incoming", cpu="1"), "c", P, fair=True)
        assert got == set()

    def test_cannot_preempt_when_it_would_switch_imbalance(self):
        got = run_targets(
            fair_fixture_cqs(),
            units("a", "a", 3) + units("b", "b", 5),
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == set()

    def test_preempt_lower_priority_from_same_cq(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1_low", "a", priority=-1, cpu="1"),
             admitted("a2_low", "a", priority=-1, cpu="1"),
             admitted("a3", "a", cpu="1"),
             admitted("a4", "a", cpu="1")] + units("b", "b", 5),
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == {("a1_low", IN_CQ), ("a2_low", IN_CQ)}

    def test_preempt_combination_of_same_cq_and_highest_user(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a_low", "a", priority=-1, cpu="1"),
             admitted("a2", "a", cpu="1"),
             admitted("a3", "a", cpu="1")] + units("b", "b", 6),
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == {("a_low", IN_CQ), ("b1", FAIR)}

    def test_preempt_huge_workload_if_no_other_option(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("b1", "b", cpu="9")],
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == {("b1", FAIR)}

    def test_cannot_preempt_huge_if_incoming_also_huge(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="2"),
             admitted("b1", "b", cpu="7")],
            incoming_wl("a_incoming", cpu="5"), "a", P, fair=True)
        assert got == set()

    def test_cannot_preempt_2_smaller_if_incoming_huge(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("b1", "b", cpu="2"),
             admitted("b2", "b", cpu="2"),
             admitted("b3", "b", cpu="3")],
            incoming_wl("a_incoming", cpu="6"), "a", P, fair=True)
        assert got == set()

    def test_preempt_from_target_and_others_even_if_over_nominal(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1_low", "a", priority=-1, cpu="2"),
             admitted("a2_low", "a", priority=-1, cpu="1"),
             admitted("b1", "b", cpu="3"),
             admitted("b2", "b", cpu="3")],
            incoming_wl("a_incoming", cpu="4"), "a", P, fair=True)
        assert got == {("a1_low", IN_CQ), ("b1", FAIR)}

    def test_prefer_targets_not_making_cq_biggest_share(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("b1", "b", cpu="2"),
             admitted("b2", "b", cpu="1"),
             admitted("b3", "b", cpu="2"),
             admitted("c1", "c", cpu="1")],
            incoming_wl("a_incoming", cpu="3.5"), "a", P, fair=True)
        assert got == {("b2", FAIR)}

    def test_preempt_from_different_cqs_for_smaller_max_share(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("b1", "b", cpu="2"),
             admitted("b2", "b", cpu="2.5"),
             admitted("c1", "c", cpu="2"),
             admitted("c2", "c", cpu="2.5")],
            incoming_wl("a_incoming", cpu="3.5"), "a", P, fair=True)
        assert got == {("b1", FAIR), ("c1", FAIR)}

    def test_scenario_above_does_not_flap(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="3.5"),
             admitted("b2", "b", cpu="2.5"),
             admitted("c2", "c", cpu="2.5")],
            incoming_wl("b_incoming", cpu="2"), "b", P, fair=True)
        assert got == set()

    def test_cannot_preempt_candidate_cq_under_nominal_after_one(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("b1", "b", cpu="3"),
             admitted("b2", "b", cpu="3"),
             admitted("c1", "c", cpu="3")],
            incoming_wl("a_incoming", cpu="4"), "a", P, fair=True)
        assert got == set()

    def test_workloads_under_priority_threshold_always_preemptible(self):
        got = run_targets(
            fair_fixture_cqs(),
            units("a", "a", 3) + units("b", "b", 3)
            + units("preemptible", "preemptible", 3, priority=-3),
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == {("preemptible1", FAIR),
                       ("preemptible2", WHILE_BORROWING)}

    def test_strategy_less_than_initial_share_prefers_low_priority(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="3"),
             admitted("b_low", "b", priority=0, cpu="5"),
             admitted("b_high", "b", priority=1, cpu="1")],
            incoming_wl("a_incoming", cpu="1"), "a", P, fair=True,
            strategies=["LessThanInitialShare"])
        assert got == {("b_low", FAIR)}

    def test_strategy_final_share_prefers_non_transferring(self):
        got = run_targets(
            fair_fixture_cqs(),
            [admitted("a1", "a", cpu="3"),
             admitted("b_low", "b", priority=0, cpu="5"),
             admitted("b_high", "b", priority=1, cpu="1")],
            incoming_wl("a_incoming", cpu="1"), "a", P, fair=True,
            strategies=["LessThanOrEqualToFinalShare"])
        assert got == {("b_high", FAIR)}

    def test_cq_with_higher_weight_can_preempt_more(self):
        got = run_targets(
            plain_fair_cqs(weights={"a": 2000}),
            units("a", "a", 3) + units("b", "b", 6),
            incoming_wl("a_incoming", cpu="2"), "a", P, fair=True)
        assert got == {("b1", FAIR), ("b2", FAIR)}

    def test_can_preempt_anything_borrowing_from_zero_weight_cq(self):
        got = run_targets(
            plain_fair_cqs(weights={"b": 0}),
            units("a", "a", 3) + units("b", "b", 6),
            incoming_wl("a_incoming", cpu="3"), "a", P, fair=True)
        assert got == {("b1", FAIR), ("b2", FAIR), ("b3", FAIR)}

    def test_cannot_preempt_nominal_from_zero_weight_cq(self):
        got = run_targets(
            plain_fair_cqs(weights={"b": 0})[:2],
            units("a", "a", 3) + units("b", "b", 3),
            incoming_wl("a_incoming", cpu="1"), "a", P, fair=True)
        assert got == set()


class TestCandidatesOrdering:
    """preemption_test.go TestCandidatesOrdering:1993-2040."""

    def test_ordering(self):
        def wl(name, cq="self", priority=0, reserved_at=NOW, evicted=False,
               reserve=True):
            w = WorkloadWrapper(name).priority(priority).creation(NOW)
            w.pod_set(count=1, cpu="1")
            if reserve:
                w.reserve(cq, now=reserved_at)
            w.wl.metadata.uid = name
            if evicted:
                set_condition(w.wl.status.conditions, Condition(
                    type=api.WORKLOAD_EVICTED, status="True",
                    reason="Preempted", message=""), NOW)
            return wlpkg.Info(w.obj(), cluster_queue=cq)

        candidates = [
            wl("high", priority=10),
            wl("low", priority=-10),
            wl("other", cq="other", priority=10),
            wl("evicted", evicted=True, reserve=False),
            wl("old-a", reserved_at=NOW),
            wl("old-b", reserved_at=NOW),
            wl("current", reserved_at=NOW + 1),
        ]
        preemptor = Preemptor(clock=FakeClock(NOW))
        candidates.sort(key=preemptor._candidate_sort_key("self"))
        got = [c.obj.metadata.name for c in candidates]
        assert got == ["evicted", "other", "low", "current", "old-a",
                       "old-b", "high"]
