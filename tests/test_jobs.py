"""Job-integration tests: jobframework state machine + per-kind wrappers.

Plays the role of the reference's test/integration/controller/jobs/*
suites (SURVEY.md §4 tier 2).
"""

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import appsv1, batchv1, corev1, jobset as jobsetapi
from kueue_tpu.api import kubeflow as kf
from kueue_tpu.api import kueue as api
from kueue_tpu.api import ray as rayapi
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import Condition, FakeClock, ObjectMeta, find_condition
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.controller.jobs.pod import (
    GROUP_NAME_LABEL,
    GROUP_TOTAL_COUNT_ANNOTATION,
)
from kueue_tpu.manager import KueueManager

from tests.wrappers import (
    ClusterQueueWrapper,
    flavor_quotas,
    make_flavor,
    make_local_queue,
)

ALL_FRAMEWORKS_CFG = cfgpkg.Configuration(
    integrations=cfgpkg.Integrations(frameworks=list(cfgpkg.ALL_INTEGRATIONS)))


@pytest.fixture
def clock():
    return FakeClock(1000.0)


@pytest.fixture
def mgr(clock):
    m = KueueManager(cfg=ALL_FRAMEWORKS_CFG, clock=clock)
    m.store.create(make_flavor("default", node_labels={"zone": "a"}))
    m.store.create(ClusterQueueWrapper("cq").resource_group(
        flavor_quotas("default", cpu=4)).obj())
    m.store.create(make_local_queue("lq", "default", "cq"))
    m.run_until_idle()
    return m


def template(cpu="1"):
    return PodTemplateSpec(spec=PodSpec(containers=[
        Container(name="c", requests={"cpu": corev1.parse_quantity(cpu, "cpu")})]))


def make_job(name="j", queue="lq", parallelism=1, cpu="1", **annotations):
    job = batchv1.Job(metadata=ObjectMeta(
        name=name, namespace="default",
        labels={api.QUEUE_LABEL: queue} if queue else {},
        annotations=dict(annotations)))
    job.spec.suspend = True
    job.spec.parallelism = parallelism
    job.spec.template = template(cpu)
    return job


class TestBatchJob:
    def test_full_lifecycle(self, mgr, clock):
        mgr.store.create(make_job(parallelism=2))
        mgr.schedule_until_settled()
        wls = mgr.store.list("Workload")
        assert len(wls) == 1 and wlpkg.is_admitted(wls[0])
        assert wls[0].spec.pod_sets[0].count == 2
        job = mgr.store.get("Job", "default", "j")
        assert not job.spec.suspend
        assert job.spec.template.spec.node_selector == {"zone": "a"}
        # finish
        job.status.conditions.append(Condition(
            type=batchv1.JOB_COMPLETE, status="True", message="done"))
        mgr.store.update(job)
        mgr.run_until_idle()
        wl = mgr.store.list("Workload")[0]
        assert wlpkg.is_finished(wl)
        assert not wl.metadata.finalizers
        # delete job -> workload GC'd (sim plays the k8s GC role)
        mgr.store.delete("Job", "default", "j")
        mgr.run_until_idle()
        assert mgr.store.list("Workload") == []

    def test_job_without_queue_name_ignored(self, mgr):
        mgr.store.create(make_job(queue=None))
        mgr.schedule_until_settled()
        assert mgr.store.list("Workload") == []

    def test_manage_without_queue_name(self, clock):
        cfg = cfgpkg.Configuration(
            manage_jobs_without_queue_name=True,
            integrations=cfgpkg.Integrations(frameworks=["batch/job"]))
        m = KueueManager(cfg=cfg, clock=clock)
        m.store.create(make_flavor("default"))
        m.store.create(ClusterQueueWrapper("cq").resource_group(
            flavor_quotas("default", cpu=4)).obj())
        m.store.create(make_local_queue("lq", "default", "cq"))
        m.run_until_idle()
        m.store.create(make_job(queue=None))
        m.run_until_idle()
        # a workload is created even without the label (queue is empty ->
        # stays pending as inadmissible)
        assert len(m.store.list("Workload")) == 1

    def test_partial_admission_scales_parallelism(self, mgr):
        job = make_job(parallelism=6, **{
            "kueue.x-k8s.io/job-min-parallelism": "2"})
        mgr.store.create(job)
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        assert wlpkg.is_admitted(wl)
        # only 4 cpus -> scaled down to 4
        assert wl.status.admission.pod_set_assignments[0].count == 4
        job = mgr.store.get("Job", "default", "j")
        assert job.spec.parallelism == 4
        assert job.metadata.annotations["kueue.x-k8s.io/original-parallelism"] == "6"

    def test_eviction_stops_job_and_clears_reservation(self, mgr, clock):
        mgr.store.create(make_job(parallelism=1))
        mgr.schedule_until_settled()
        job = mgr.store.get("Job", "default", "j")
        assert not job.spec.suspend
        # evict via CQ drain
        cq = mgr.store.get("ClusterQueue", "", "cq")
        cq.spec.stop_policy = api.HOLD_AND_DRAIN
        mgr.store.update(cq)
        mgr.run_until_idle()
        job = mgr.store.get("Job", "default", "j")
        assert job.spec.suspend
        assert job.spec.template.spec.node_selector == {}  # restored
        wl = mgr.store.list("Workload")[0]
        assert not wlpkg.has_quota_reservation(wl)
        req = find_condition(wl.status.conditions, api.WORKLOAD_REQUEUED)
        assert req is not None and req.status == "False"
        assert req.reason == api.EVICTED_BY_CLUSTER_QUEUE_STOPPED

    def test_preemption_requeues_immediately(self, mgr, clock):
        cq = mgr.store.get("ClusterQueue", "", "cq")
        cq.spec.preemption = api.ClusterQueuePreemption(
            within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        mgr.store.update(cq)
        mgr.run_until_idle()
        mgr.store.create(api.WorkloadPriorityClass(
            metadata=ObjectMeta(name="high"), value=100))
        mgr.store.create(make_job(name="low", parallelism=4, cpu="1"))
        mgr.schedule_until_settled()
        assert not mgr.store.get("Job", "default", "low").spec.suspend
        high = make_job(name="high", parallelism=4, cpu="1")
        high.metadata.labels[api.PRIORITY_CLASS_LABEL] = "high"
        mgr.store.create(high)
        mgr.schedule_until_settled()
        low_wl = next(w for w in mgr.store.list("Workload")
                      if w.metadata.name.startswith("job-low"))
        high_wl = next(w for w in mgr.store.list("Workload")
                       if w.metadata.name.startswith("job-high"))
        assert high_wl.spec.priority == 100
        assert wlpkg.is_admitted(high_wl)
        assert mgr.store.get("Job", "default", "low").spec.suspend
        req = find_condition(low_wl.status.conditions, api.WORKLOAD_REQUEUED)
        # preemption evictions requeue immediately (Requeued=True)
        assert req is not None and req.status == "True"

    def test_reclaimable_pods_propagate(self, mgr):
        mgr.store.create(make_job(parallelism=3))
        mgr.schedule_until_settled()
        job = mgr.store.get("Job", "default", "j")
        job.status.succeeded = 2
        mgr.store.update(job)
        mgr.run_until_idle()
        wl = mgr.store.list("Workload")[0]
        assert wl.status.reclaimable_pods == [
            api.ReclaimablePod(name="main", count=2)]

    def test_prebuilt_workload(self, mgr):
        wl = api.Workload(metadata=ObjectMeta(name="prebuilt", namespace="default"))
        wl.spec.queue_name = "lq"
        wl.spec.pod_sets = [api.PodSet(name="main", count=1, template=template())]
        mgr.store.create(wl)
        job = make_job()
        job.metadata.labels[api.PREBUILT_WORKLOAD_LABEL] = "prebuilt"
        mgr.store.create(job)
        mgr.schedule_until_settled()
        wls = mgr.store.list("Workload")
        assert len(wls) == 1 and wls[0].metadata.name == "prebuilt"
        assert wlpkg.is_admitted(wls[0])
        assert not mgr.store.get("Job", "default", "j").spec.suspend


class TestJobSet:
    def test_multi_replicated_jobs(self, mgr):
        js = jobsetapi.JobSet(metadata=ObjectMeta(
            name="js", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        js.spec.suspend = True
        js.spec.replicated_jobs = [
            jobsetapi.ReplicatedJob(name="leader", replicas=1,
                                    template=batchv1.JobSpec(parallelism=1,
                                                             template=template())),
            jobsetapi.ReplicatedJob(name="workers", replicas=1,
                                    template=batchv1.JobSpec(parallelism=2,
                                                             template=template())),
        ]
        mgr.store.create(js)
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        assert [(ps.name, ps.count) for ps in wl.spec.pod_sets] == [
            ("leader", 1), ("workers", 2)]
        assert wlpkg.is_admitted(wl)
        js = mgr.store.get("JobSet", "default", "js")
        assert not js.spec.suspend
        for rj in js.spec.replicated_jobs:
            assert rj.template.template.spec.node_selector == {"zone": "a"}


class TestKubeflow:
    def test_pytorch_master_worker(self, mgr):
        pj = kf.PyTorchJob(metadata=ObjectMeta(
            name="pt", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        pj.spec.run_policy.suspend = True
        pj.spec.replica_specs = {
            "Worker": kf.ReplicaSpec(replicas=2, template=template()),
            "Master": kf.ReplicaSpec(replicas=1, template=template()),
        }
        mgr.store.create(pj)
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        # master ordered first
        assert [ps.name for ps in wl.spec.pod_sets] == ["master", "worker"]
        assert wlpkg.is_admitted(wl)
        pj = mgr.store.get("PyTorchJob", "default", "pt")
        assert not pj.spec.run_policy.suspend

    def test_mpijob_finishes(self, mgr, clock):
        mj = kf.MPIJob(metadata=ObjectMeta(
            name="mpi", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        mj.spec.run_policy.suspend = True
        mj.spec.replica_specs = {
            "Launcher": kf.ReplicaSpec(replicas=1, template=template()),
            "Worker": kf.ReplicaSpec(replicas=2, template=template()),
        }
        mgr.store.create(mj)
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        assert [ps.name for ps in wl.spec.pod_sets] == ["launcher", "worker"]
        mj = mgr.store.get("MPIJob", "default", "mpi")
        mj.status.conditions.append(Condition(
            type=kf.JOB_SUCCEEDED, status="True", message="done"))
        mgr.store.update(mj)
        mgr.run_until_idle()
        assert wlpkg.is_finished(mgr.store.list("Workload")[0])


class TestRay:
    def test_rayjob_head_and_workers(self, mgr):
        rj = rayapi.RayJob(metadata=ObjectMeta(
            name="ray", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        rj.spec.suspend = True
        rj.spec.ray_cluster_spec = rayapi.RayClusterSpec(
            head_group_spec=rayapi.HeadGroupSpec(template=template()),
            worker_group_specs=[rayapi.WorkerGroupSpec(
                group_name="gpu-group", replicas=2, template=template())])
        mgr.store.create(rj)
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        assert [(ps.name, ps.count) for ps in wl.spec.pod_sets] == [
            ("head", 1), ("gpu-group", 2)]
        assert wlpkg.is_admitted(wl)
        assert not mgr.store.get("RayJob", "default", "ray").spec.suspend


class TestPodIntegration:
    def make_pod(self, name, group=None, cpu="1", gated=True, total=None):
        pod = corev1.Pod(metadata=ObjectMeta(
            name=name, namespace="default",
            labels={api.QUEUE_LABEL: "lq", api.MANAGED_LABEL: "true"}))
        pod.spec = PodSpec(containers=[Container(
            name="c", requests={"cpu": corev1.parse_quantity(cpu, "cpu")})])
        if gated:
            pod.spec.scheduling_gates = [api.ADMISSION_GATE]
        if group:
            pod.metadata.labels[GROUP_NAME_LABEL] = group
            pod.metadata.annotations[GROUP_TOTAL_COUNT_ANNOTATION] = str(total)
        return pod

    def test_single_pod_gated_then_admitted(self, mgr):
        mgr.store.create(self.make_pod("p1"))
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        assert wl.metadata.name == "p1"
        assert wlpkg.is_admitted(wl)
        pod = mgr.store.get("Pod", "default", "p1")
        assert api.ADMISSION_GATE not in pod.spec.scheduling_gates
        assert pod.spec.node_selector == {"zone": "a"}

    def test_pod_group_waits_for_all_members(self, mgr):
        mgr.store.create(self.make_pod("g1-a", group="g1", total=2))
        mgr.schedule_until_settled()
        assert mgr.store.list("Workload") == []  # incomplete group
        mgr.store.create(self.make_pod("g1-b", group="g1", total=2))
        mgr.schedule_until_settled()
        wls = mgr.store.list("Workload")
        assert len(wls) == 1 and wls[0].metadata.name == "g1"
        assert sum(ps.count for ps in wls[0].spec.pod_sets) == 2
        assert wlpkg.is_admitted(wls[0])
        for name in ("g1-a", "g1-b"):
            pod = mgr.store.get("Pod", "default", name)
            assert api.ADMISSION_GATE not in pod.spec.scheduling_gates

    def test_pod_group_two_roles(self, mgr):
        mgr.store.create(self.make_pod("g2-driver", group="g2", cpu="2", total=3))
        mgr.store.create(self.make_pod("g2-w0", group="g2", cpu="1", total=3))
        mgr.store.create(self.make_pod("g2-w1", group="g2", cpu="1", total=3))
        mgr.schedule_until_settled()
        wl = mgr.store.list("Workload")[0]
        counts = sorted(ps.count for ps in wl.spec.pod_sets)
        assert counts == [1, 2]  # driver role + worker role
        assert wlpkg.is_admitted(wl)

    def test_pod_group_finishes(self, mgr):
        mgr.store.create(self.make_pod("g3-a", group="g3", total=2))
        mgr.store.create(self.make_pod("g3-b", group="g3", total=2))
        mgr.schedule_until_settled()
        for name in ("g3-a", "g3-b"):
            pod = mgr.store.get("Pod", "default", name)
            pod.status.phase = corev1.POD_SUCCEEDED
            mgr.store.update(pod)
        mgr.run_until_idle()
        assert wlpkg.is_finished(mgr.store.list("Workload")[0])


class TestDeployment:
    def test_queue_label_propagates_and_pods_queue(self, mgr):
        from kueue_tpu.controller.jobs.deployment import propagate_queue_label
        dep = appsv1.Deployment(metadata=ObjectMeta(
            name="serve", namespace="default", labels={api.QUEUE_LABEL: "lq"}))
        dep.spec.replicas = 2
        dep.spec.template = template()
        assert propagate_queue_label(dep)
        assert dep.spec.template.labels[api.QUEUE_LABEL] == "lq"
        mgr.store.create(dep)
        # the platform (replicaset controller) creates pods from the
        # template; the pod webhook gates them
        for i in range(2):
            pod = corev1.Pod(metadata=ObjectMeta(
                name=f"serve-{i}", namespace="default",
                labels=dict(dep.spec.template.labels,
                            **{api.MANAGED_LABEL: "true"})))
            pod.spec = dep.spec.template.spec
            pod.spec.scheduling_gates = [api.ADMISSION_GATE]
            mgr.store.create(pod)
        mgr.schedule_until_settled()
        wls = mgr.store.list("Workload")
        assert len(wls) == 2  # one workload per serving pod
        assert all(wlpkg.is_admitted(w) for w in wls)
