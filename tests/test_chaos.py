"""Randomized chaos suite for the resilience subsystem (ISSUE 3
acceptance): under ANY seeded injected fault schedule — dispatch
raises, collect hangs past the watchdog deadline, scatter/collect
corruption, journal-replay faults — the scheduler must

1. never deadlock (every cycle completes; the run settles within a
   bounded cycle count),
2. never poison persistent host state (the maintained snapshot stays
   bit-identical to a from-scratch rebuild; the workload encode arena
   stays bit-identical to the from-scratch encode oracle), and
3. once faults clear, admit exactly the workload set the fault-free
   oracle run admits.

The tier-1 smoke run drives one seed through a small scenario; the
`slow`-marked sweep runs multiple seeds x {sync, pipelined} x
{fit-only, preemption} (ROADMAP tier-1 stays fast).
"""

import pytest

from kueue_tpu.api import kueue as api
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.breaker import CircuitBreaker
from kueue_tpu.resilience.faultinject import FaultInjector
from kueue_tpu.resilience.watchdog import DispatchWatchdog
from tests.test_incremental_snapshot import assert_snapshots_equal
from tests.test_solver import admitted_map, build_env
from tests.wrappers import ClusterQueueWrapper, WorkloadWrapper, flavor_quotas

MAX_CYCLES = 80


def _setup(preemption=False):
    def setup(env):
        env.add_flavor("default")
        kwargs = {}
        if preemption:
            kwargs = dict(
                within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY)
        for i in range(4):
            cq = ClusterQueueWrapper(f"cq{i}").cohort("co")
            if preemption:
                cq = cq.preemption(**kwargs)
            env.add_cq(cq.resource_group(
                flavor_quotas("default", cpu="8")).obj(), f"lq-cq{i}")
    return setup


def _submit_waves(env, waves, start_wave=0, cpu="2", priority=0):
    # Uniform priority: the admitted SUBSET under contention is then
    # the earliest-created workloads per CQ, which a fault-delayed
    # retry can never change (creation order is the head order), so
    # chaos vs oracle set equality is well-defined even when not
    # everything fits.
    n = start_wave * 4
    for wave in range(start_wave, start_wave + waves):
        for i in range(4):
            w = WorkloadWrapper(f"w{wave}-{i}").queue(f"lq-cq{i}")
            env.submit(w.priority(priority).creation(float(n))
                       .pod_set(count=1, cpu=cpu).obj())
            n += 1


def _run_to_settled(env, injector=None, inject_cycles=0,
                    trickle_waves=0, max_cycles=MAX_CYCLES):
    """Drive cycles (advancing the fake clock so breaker backoffs
    elapse) until the system settles: no admission progress, nothing in
    flight, and no injector installed. Returns the cycle count; raising
    past max_cycles IS the deadlock/livelock assertion."""
    settled = 0
    for cycle in range(max_cycles):
        if injector is not None and cycle == 0:
            faultinject.install(injector)
        if injector is not None and cycle == inject_cycles:
            faultinject.uninstall()  # faults clear
        if cycle < trickle_waves:
            # mid-run arrivals keep the encode arena churning (dirty
            # rows -> the scatter site sees real traffic)
            _submit_waves(env, 1, start_wave=2 + cycle)
        before = len(env.client.applied) + len(env.client.evicted)
        env.cycle()
        env.clock.advance(1.0)
        progressed = (len(env.client.applied) + len(env.client.evicted)
                      > before)
        inflight = env.scheduler._inflight is not None
        injecting = injector is not None and cycle < inject_cycles
        settled = 0 if (progressed or inflight or injecting) else settled + 1
        if settled >= 3:
            return cycle + 1
    raise AssertionError(
        f"did not settle within {max_cycles} cycles "
        f"(faults={env.scheduler.solver_faults}, "
        f"breaker={env.scheduler.breaker.state})")


def _assert_host_state_clean(env):
    """Persistent host state is fault-free by construction: the
    maintained snapshot equals a from-scratch rebuild bit-for-bit, and
    the arena's host rows re-assemble bit-identically to the
    from-scratch encode oracle for a fresh probe batch."""
    import numpy as np
    from kueue_tpu.solver import encode
    cache = env.cache
    assert_snapshots_equal(cache.snapshot(), cache._build_snapshot(),
                           "post-chaos")
    solver = env.scheduler.solver
    snapshot = cache.snapshot()
    topo = encode.encode_topology(snapshot)
    probes = []
    for i in range(4):
        wl = (WorkloadWrapper(f"probe-{i}").queue(f"lq-cq{i}")
              .creation(10_000.0 + i).pod_set(count=1, cpu="1").obj())
        info = wlpkg.Info(wl)
        info.cluster_queue = f"cq{i}"
        probes.append(info)
    solver._arena.begin_cycle(topo)
    batch_a, _ = solver._arena.assemble(probes, snapshot, topo,
                                        solver.ordering, solver.max_podsets)
    batch_f = encode.encode_workloads(probes, snapshot, topo,
                                      ordering=solver.ordering,
                                      max_podsets=solver.max_podsets)
    for name in ("requests", "podset_active", "wl_cq", "priority",
                 "timestamp", "eligible", "solvable", "start_rank"):
        assert np.array_equal(getattr(batch_a, name),
                              getattr(batch_f, name)), name


def _chaos_vs_oracle(seed, waves=6, preemption=False, pipeline=False,
                     inject_cycles=14, rates=None, trickle_waves=4):
    """One chaos run vs its fault-free oracle twin. Both runs see the
    IDENTICAL arrival schedule; the chaos run additionally sees the
    seeded fault schedule for its first inject_cycles cycles."""
    results = {}
    for chaotic in (False, True):
        env = build_env(_setup(preemption), solver=True)
        s = env.scheduler
        s.pipeline_enabled = pipeline
        s.breaker = CircuitBreaker(threshold=2, backoff_base_s=2.0,
                                   jitter=0.0, seed=seed)
        # max_deadline is the COLD-cycle clamp: with supervised dispatch
        # (PR 5) it must clear a real jit compile inside dispatch, or
        # every cold cycle faults before the injector even fires. Warm
        # deadlines clamp to min (0.1s), so injected 0.2s hangs still
        # reliably trip.
        s.watchdog = DispatchWatchdog(safety_factor=2.0,
                                      min_deadline_s=0.1,
                                      max_deadline_s=10.0)
        _submit_waves(env, 2)
        injector = None
        if chaotic:
            injector = FaultInjector.scripted(seed, horizon=40,
                                              rates=rates, delay_s=0.2)
        try:
            cycles = _run_to_settled(
                env, injector, inject_cycles=inject_cycles,
                trickle_waves=trickle_waves)
        finally:
            faultinject.uninstall()
        results[chaotic] = (env, cycles, injector)
    oracle_env = results[False][0]
    chaos_env, cycles, injector = results[True]
    # 3: identical admitted set (and evictions) once faults cleared
    assert set(admitted_map(chaos_env)) == set(admitted_map(oracle_env))
    assert set(chaos_env.client.evicted) == set(oracle_env.client.evicted)
    # 2: persistent snapshot + arena unpoisoned
    _assert_host_state_clean(chaos_env)
    return chaos_env, cycles, injector


class TestChaosSmoke:
    def test_seeded_burst_converges_to_oracle(self):
        # Tier-1 smoke: one seed, every site scheduled hot enough that
        # faults demonstrably fired, including a breaker trip + recovery.
        env, cycles, injector = _chaos_vs_oracle(
            seed=1234,
            rates={faultinject.SITE_DISPATCH: 0.5,
                   faultinject.SITE_COLLECT: 0.3,
                   faultinject.SITE_SCATTER: 0.4,
                   faultinject.SITE_REPLAY: 0.2})
        assert injector.total_fired > 0
        assert env.scheduler.solver_faults > 0
        s = env.scheduler
        if s.breaker.trips and not s.breaker.recoveries:
            # The backlog drained / quota filled while the breaker was
            # still open — a probe with nothing to dispatch is
            # (correctly) inconclusive and re-armed. Complete a few
            # admitted workloads so the parked backlog re-heaps with
            # real device work: the next probe round-trips and closes
            # the breaker. Advance far enough per cycle to clear even a
            # several-times-doubled probe backoff (supervised dispatch
            # turns injected dispatch hangs into faults too, so failed
            # probes — and thus doublings — are more frequent than
            # before PR 5).
            for wl in list(env.client.applied.values())[:4]:
                env.cache.delete_workload(wl)
                env.queues.queue_associated_inadmissible_workloads_after(wl)
            for _ in range(10):
                env.clock.advance(10.0)
                env.cycle()
        if s.breaker.trips:
            assert s.breaker.recoveries >= 1
            assert s.cycle_counts.get("cpu-breaker", 0) >= 1


class TestDispatchHangRegression:
    def test_scripted_dispatch_hangs_trip_breaker_and_recover(self):
        # ISSUE 5 satellite: the `hang` action at the device_dispatch
        # site used to wedge the scheduler forever (PR 3's watchdog only
        # bounded collect). Supervised dispatch abandons each hang
        # within the watchdog's cold clamp, the breaker trips after N
        # faults, and recovery follows the existing half-open probe
        # path — the full outage lifecycle, scripted.
        import time as _t
        env = build_env(_setup(), solver=True)
        s = env.scheduler
        s.breaker = CircuitBreaker(threshold=2, backoff_base_s=2.0,
                                   jitter=0.0)
        _submit_waves(env, 2)
        # Warm: compile the shape buckets with an untightened watchdog
        # so the clamp below only ever fires on the injected hangs.
        env.cycle()
        env.clock.advance(1.0)
        env.cycle()
        env.clock.advance(1.0)
        assert len(admitted_map(env)) == 8
        s.watchdog = DispatchWatchdog(safety_factor=2.0,
                                      min_deadline_s=0.05,
                                      max_deadline_s=0.3)
        injector = FaultInjector(
            {faultinject.SITE_DISPATCH: {0: (faultinject.DELAY, 5.0),
                                         1: (faultinject.DELAY, 5.0)}})
        t0 = _t.perf_counter()
        with faultinject.installed(injector):
            # one fresh wave per hang cycle: both scripted hangs fire
            _submit_waves(env, 1, start_wave=2)
            env.cycle()    # hang 0: abandoned, CPU fallback admits
            env.clock.advance(1.0)
            _submit_waves(env, 1, start_wave=3)
            env.cycle()    # hang 1: abandoned -> threshold 2 trips
            env.clock.advance(1.0)
            wall = _t.perf_counter() - t0
            assert s.breaker.trips == 1
            # Quota is full (16 x 2cpu): free a wave's worth so the
            # next cycle has real work, then keep the arrivals flowing
            # so the post-backoff probe cycle isn't headless (a probe
            # needs device work to round-trip).
            deleted = 0

            def free_and_submit(wave):
                nonlocal deleted
                applied = list(env.client.applied.values())
                for wl in applied[deleted:deleted + 4]:
                    env.cache.delete_workload(wl)
                    env.queues \
                       .queue_associated_inadmissible_workloads_after(wl)
                deleted += 4
                _submit_waves(env, 1, start_wave=wave)

            free_and_submit(4)
            env.cycle()    # still inside backoff: cpu-breaker route
            env.clock.advance(3.0)
            for i in range(6):  # post-backoff probe recovers
                free_and_submit(5 + i)
                env.cycle()
                env.clock.advance(3.0)
                if s.breaker.recoveries:
                    break
        # Both 5s hangs were abandoned at the 0.3s clamp: the two hang
        # cycles took nowhere near the 10s the hangs would cost inline.
        assert wall < 5.0, wall
        assert s.solver.counters["supervised_timeouts"] == 2
        assert s.solver._supervisor.orphaned == 2
        assert s.solver_faults == 2
        # threshold 2: the hang faults tripped the breaker, outage
        # cycles routed cpu-breaker, and a post-backoff probe recovered.
        assert s.cycle_counts.get("cpu-breaker", 0) >= 1
        assert s.breaker.recoveries >= 1
        # nothing was lost: admissions kept flowing through the outage
        assert len(admitted_map(env)) >= 16
        _assert_host_state_clean(env)


class TestOverloadStorm:
    def test_storm_converges_to_fault_free_admitted_set(self):
        # ISSUE 5 satellite: an overload storm (every cycle blowing a
        # tiny budget) walks the ladder into shed/survival — and once
        # load subsides the ladder recovers and the admitted set
        # converges to the run with no ladder at all. Degradation
        # affects WHEN work admits, never WHAT admits.
        from kueue_tpu.resilience.degrade import (
            NORMAL, DegradationLadder)

        def run(budget_s):
            env = build_env(_setup(), solver=True)
            s = env.scheduler
            if budget_s:
                s.ladder = DegradationLadder(
                    budget_s=budget_s, shed_heads=2, survival_heads=1,
                    escalate_after=1, recovery_cycles=2, ewma_alpha=1.0)
            _submit_waves(env, 6)  # storm: 24 workloads at once
            for cycle in range(40):
                if 12 <= cycle < 25:
                    # identical post-storm trickle in BOTH runs: keeps
                    # heads flowing so the ladder (when present) keeps
                    # observing and can walk back down to normal
                    _submit_waves(env, 1, start_wave=6 + cycle)
                env.cycle()
                env.clock.advance(1.0)
                if budget_s and cycle == 12:
                    # load subsided: generous budget from here on
                    s.ladder.budget_s = 60.0
            return env
        clean = run(0.0)
        storm = run(1e-9)  # every cycle overloads the budget
        s = storm.scheduler
        assert s.ladder.escalations >= 1      # the ladder engaged
        assert s.ladder.cycles_shed >= 1
        assert s.shed_heads_requeued >= 1     # heads actually shed
        assert s.cycle_counts.get("cpu-survival", 0) >= 1
        assert s.ladder.state == NORMAL       # and recovered
        # convergence: identical admitted set once load subsided
        assert set(admitted_map(storm)) == set(admitted_map(clean))
        _assert_host_state_clean(storm)


class TestSpeculationAborts:
    def test_scripted_mis_speculation_falls_back_with_no_double_admission(self):
        # ISSUE 6 satellite: a scripted fault at the new
        # speculation_validate site forces mis-speculation aborts on a
        # pipelined run. Every abort must fall back to the synchronous
        # path, the admitted set must converge to the fault-free
        # oracle's, and no workload may be admitted twice.
        results = {}
        for chaotic in (False, True):
            env = build_env(_setup(), solver=True)
            env.scheduler.pipeline_enabled = True
            injector = None
            if chaotic:
                injector = FaultInjector(
                    {faultinject.SITE_SPECULATION:
                     {i: faultinject.RAISE for i in (0, 2, 3)}})
            try:
                _run_to_settled(env, injector, inject_cycles=10,
                                trickle_waves=3)
            finally:
                faultinject.uninstall()
            results[chaotic] = env
        oracle, chaos = results[False], results[True]
        s = chaos.scheduler
        assert s.speculation_aborts >= 1
        assert s.speculation_abort_reasons.get("injected", 0) >= 1
        # abort -> synchronous fallback -> identical admitted set
        assert set(admitted_map(chaos)) == set(admitted_map(oracle))
        # no double admission: one QuotaReserved event per admitted key
        reserved: dict = {}
        for key, reason in chaos.client.events:
            if reason == "QuotaReserved":
                reserved[key] = reserved.get(key, 0) + 1
        assert all(c == 1 for c in reserved.values())
        # the breaker was NOT fed: mis-speculation is not a device fault
        assert s.breaker.trips == 0 and s.solver_faults == 0
        _assert_host_state_clean(chaos)


class TestDepthTwoSpeculationAborts:
    def test_depth2_mis_speculation_aborts_both_inflight_cleanly(self):
        # ISSUE 11 satellite: at dispatch depth 2 TWO cycles ride the
        # chained device state; a scripted mis-speculation must abort
        # BOTH in-flight cycles (the younger as "chained"), fall back
        # to the synchronous path, converge to the fault-free oracle's
        # admitted set, and never double-admit. The injector installs
        # only once the pipeline has genuinely deepened to two
        # outstanding dispatches, so the abort-both path is exercised
        # deterministically.
        results = {}
        for chaotic in (False, True):
            env = build_env(_setup(), solver=True)
            s = env.scheduler
            s.pipeline_enabled = True
            s.pipeline_depth = 2
            wave = 0
            try:
                if chaotic:
                    # ramp until two cycles are in flight
                    for _ in range(8):
                        _submit_waves(env, 1, start_wave=wave)
                        wave += 1
                        env.cycle()
                        env.clock.advance(1.0)
                        if len(s._inflight_q) == 2:
                            break
                    assert len(s._inflight_q) == 2
                    # the very next validation call (the OLDEST queued
                    # token, checked before the next dispatch) raises
                    faultinject.install(FaultInjector(
                        {faultinject.SITE_SPECULATION:
                         {0: faultinject.RAISE}}))
                    _submit_waves(env, 1, start_wave=wave)
                    wave += 1
                    env.cycle()
                    env.clock.advance(1.0)
                    faultinject.uninstall()
                    assert not s._inflight_q  # both aborted, none left
                while wave < 8:  # both runs see the same total load
                    _submit_waves(env, 1, start_wave=wave)
                    wave += 1
                    env.cycle()
                    env.clock.advance(1.0)
                _run_to_settled(env, None)
            finally:
                faultinject.uninstall()
            results[chaotic] = env
        oracle, chaos = results[False], results[True]
        s = chaos.scheduler
        assert s.speculation_abort_reasons.get("injected", 0) >= 1
        # the younger in-flight cycle aborted as collateral of the
        # older one's mis-speculation — the depth-2 abort-both contract
        assert s.speculation_abort_reasons.get("chained", 0) >= 1
        assert not s._inflight_q  # nothing stranded in flight
        assert set(admitted_map(chaos)) == set(admitted_map(oracle))
        reserved: dict = {}
        for key, reason in chaos.client.events:
            if reason == "QuotaReserved":
                reserved[key] = reserved.get(key, 0) + 1
        assert all(c == 1 for c in reserved.values())
        assert s.breaker.trips == 0 and s.solver_faults == 0
        _assert_host_state_clean(chaos)


@pytest.mark.slow
class TestChaosSweep:
    @pytest.mark.parametrize("seed", [7, 99, 4242])
    def test_sync_fit(self, seed):
        _chaos_vs_oracle(seed)

    @pytest.mark.parametrize("seed", [11, 1337])
    def test_pipelined(self, seed):
        # All-fit sizing (4 waves x 2cpu == the 8cpu quota): pipelining's
        # documented deviation (heads pop before the previous cycle's
        # requeues) makes the admitted SUBSET under contention depend on
        # in-flight timing, which faults legitimately shift — the
        # invariant the chaos suite owns is convergence of the admitted
        # SET, so the pipelined variant runs where that set is total.
        env, _cycles, _inj = _chaos_vs_oracle(seed, pipeline=True,
                                              trickle_waves=2)
        assert len(admitted_map(env)) == 16  # every submitted workload

    @pytest.mark.parametrize("seed", [21, 555])
    def test_preemption(self, seed):
        # victims occupy quota; high-priority preemptors must evict the
        # SAME victims as the oracle even while faults fly
        def run(chaotic):
            env = build_env(_setup(True), solver=True)
            s = env.scheduler
            s.breaker = CircuitBreaker(threshold=2, backoff_base_s=2.0,
                                       jitter=0.0)
            s.watchdog = DispatchWatchdog(safety_factor=2.0,
                                          min_deadline_s=0.1,
                                          max_deadline_s=10.0)
            for i in range(4):
                env.admit_existing(
                    WorkloadWrapper(f"victim{i}").queue(f"lq-cq{i}")
                    .priority(0).pod_set(count=1, cpu="8")
                    .reserve(f"cq{i}").obj())
            _submit_waves(env, 2, cpu="4", priority=10)
            injector = (FaultInjector.scripted(seed, horizon=40,
                                               delay_s=0.2)
                        if chaotic else None)
            try:
                _run_to_settled(env, injector, inject_cycles=12)
            finally:
                faultinject.uninstall()
            return env
        oracle, chaos = run(False), run(True)
        assert set(chaos.client.evicted) == set(oracle.client.evicted)
        assert set(admitted_map(chaos)) == set(admitted_map(oracle))
        _assert_host_state_clean(chaos)

    def test_relentless_injection_never_deadlocks(self):
        # Faults NEVER clear: every dispatch raises, forever. The run
        # must still drain the whole backlog through the CPU fallback +
        # cpu-breaker route — containment, not availability of the
        # device, is what bounds progress.
        env = build_env(_setup(), solver=True)
        s = env.scheduler
        s.breaker = CircuitBreaker(threshold=2, backoff_base_s=4.0,
                                   jitter=0.0)
        s.watchdog = DispatchWatchdog(safety_factor=2.0,
                                      min_deadline_s=0.1,
                                      max_deadline_s=10.0)
        _submit_waves(env, 3)
        injector = FaultInjector(
            {faultinject.SITE_DISPATCH: {i: faultinject.RAISE
                                         for i in range(200)}})
        with faultinject.installed(injector):
            for _ in range(40):
                env.cycle()
                env.clock.advance(1.0)
                if len(admitted_map(env)) == 12 \
                        and s._inflight is None:
                    break
            else:
                raise AssertionError(
                    "backlog did not drain under sustained injection")
        assert s.breaker.trips >= 1
        assert s.cycle_counts.get("cpu-breaker", 0) >= 1
        _assert_host_state_clean(env)
