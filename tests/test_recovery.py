"""Crash-restart durability suite (ISSUE 10 acceptance).

Layers under test, bottom up:

1. the durable checkpoint/WAL log itself (sim/durable.py) — roundtrip
   in memory and on disk, empty-WAL / checkpoint-with-no-tail /
   torn-final-record edge cases (the torn tail falls back to the last
   intact record with a counted warning, never an exception);
2. the Store's event journaling (every committed mutation, no-op
   writes excluded, finalizer parks included);
3. kill -> restore -> convergence through the FULL KueueManager: a
   seeded ``InjectedCrash`` mid-cycle, recovery from the durable store
   (resilience/recovery.py), and the replayed run converging to the
   uncrashed oracle's exact admitted set with no lost admissions, no
   double admissions (store-vs-cache usage cross-check) and no
   stranded state — the tier-1 smoke drives one seeded kill point;
   the multi-seed kill-point sweep over EVERY injection site rides
   ``@slow`` (tools/crash_run.py --sweep is the CLI twin);
4. the ISSUE 10 satellites: abandoned in-flight speculative cycles
   release their snapshot handout and residency at shutdown (live
   handout counter), and a reused solver ``detach()``-es cleanly into
   the restored control plane.
"""

import importlib.util
import os

import pytest

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.manager import KueueManager
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import (CRASH, FaultInjector,
                                              InjectedCrash)
from kueue_tpu.sim import Store
from kueue_tpu.sim.durable import DurableLog


def _load_crash_run():
    spec = importlib.util.spec_from_file_location(
        "crash_run", os.path.join(os.path.dirname(__file__),
                                  "..", "tools", "crash_run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_injector_leak():
    yield
    faultinject.uninstall()


def make_flavor(name="f0"):
    return api.ResourceFlavor(metadata=ObjectMeta(name=name,
                                                  uid=f"rf-{name}"))


def make_cq(name, cohort=None, cpu_quota=8000):
    cq = api.ClusterQueue(metadata=ObjectMeta(name=name, uid=name))
    cq.spec.namespace_selector = LabelSelector()
    if cohort:
        cq.spec.cohort = cohort
    cq.spec.resource_groups.append(api.ResourceGroup(
        covered_resources=["cpu"],
        flavors=[api.FlavorQuotas(name="f0", resources=[
            api.ResourceQuota(name="cpu", nominal_quota=cpu_quota)])]))
    return cq


def make_lq(name, cq):
    lq = api.LocalQueue(metadata=ObjectMeta(name=name,
                                            namespace="default",
                                            uid=name))
    lq.spec.cluster_queue = cq
    return lq


def make_workload(name, lq, cpu=2000, creation=0.0):
    wl = api.Workload(metadata=ObjectMeta(
        name=name, namespace="default", uid=name,
        creation_timestamp=creation))
    wl.spec.queue_name = lq
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": cpu})]))))
    return wl


def admitted_keys(mgr):
    return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                  if wlpkg.has_quota_reservation(wl))


# ----------------------------------------------------------------------
# 1. the durable log (satellite: replay edge cases)
# ----------------------------------------------------------------------

class TestDurableLog:
    def _seeded_store(self, durable):
        s = Store(durable=durable)
        s.create(make_flavor())
        s.create(make_workload("w0", "lq0"))
        w = s.get("Workload", "default", "w0")
        w.spec.priority = 7
        s.update(w)
        return s

    def test_empty_wal(self):
        res = DurableLog().load()
        assert res.objects == {} and res.rv == 0
        assert not res.checkpoint_loaded
        assert res.records_replayed == 0 and res.torn_records == 0
        assert res.warnings == []

    def test_memory_roundtrip(self):
        d = DurableLog()
        s = self._seeded_store(d)
        res = d.load()
        assert res.records_replayed == 3 and res.torn_records == 0
        assert res.rv == s._rv
        wl = res.objects["Workload"]["default/w0"]
        assert wl.spec.priority == 7
        assert wl.metadata.resource_version == 3
        assert set(res.objects) == {"ResourceFlavor", "Workload"}

    def test_file_roundtrip(self, tmp_path):
        d = DurableLog(dir=str(tmp_path))
        self._seeded_store(d)
        # a new log object over the same dir (the real restart shape)
        res = DurableLog(dir=str(tmp_path)).load()
        assert res.records_replayed == 3
        assert res.objects["Workload"]["default/w0"].spec.priority == 7

    def test_checkpoint_with_no_tail(self):
        d = DurableLog()
        s = self._seeded_store(d)
        s.checkpoint_now()
        res = d.load()
        assert res.checkpoint_loaded
        assert res.records_replayed == 0 and res.torn_records == 0
        assert res.objects["Workload"]["default/w0"].spec.priority == 7
        assert res.rv == s._rv

    def test_checkpoint_plus_tail(self):
        d = DurableLog()
        s = self._seeded_store(d)
        s.checkpoint_now()
        s.create(make_workload("w1", "lq0"))
        res = d.load()
        assert res.checkpoint_loaded and res.records_replayed == 1
        assert set(res.objects["Workload"]) == {"default/w0",
                                                "default/w1"}

    @pytest.mark.parametrize("chop", [1, 5])
    def test_torn_tail_falls_back(self, chop):
        """A crash mid-append leaves a short/garbled final record: the
        load must fall back to the last INTACT record with a counted
        warning instead of raising (ISSUE 10 satellite)."""
        d = DurableLog()
        self._seeded_store(d)
        d.truncate_tail(chop)
        res = d.load()
        assert res.torn_records == 1
        assert res.records_replayed == 2  # the final update was torn
        assert res.objects["Workload"]["default/w0"].spec.priority != 7
        assert any("torn" in w for w in res.warnings)

    def test_torn_tail_file(self, tmp_path):
        d = DurableLog(dir=str(tmp_path))
        self._seeded_store(d)
        d.truncate_tail(3)
        res = DurableLog(dir=str(tmp_path)).load()
        assert res.torn_records == 1 and res.records_replayed == 2

    def test_corrupt_mid_record_stops_replay(self, tmp_path):
        """A flipped bit inside the WAL (not just a short tail) fails
        the CRC and stops replay at the last intact record."""
        d = DurableLog(dir=str(tmp_path))
        self._seeded_store(d)
        path = tmp_path / "wal.log"
        raw = bytearray(path.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        path.write_bytes(bytes(raw))
        res = DurableLog(dir=str(tmp_path)).load()
        assert res.torn_records == 1
        assert res.records_replayed < 3

    def test_auto_checkpoint_compacts(self):
        d = DurableLog(checkpoint_every=2)
        s = Store(durable=d)
        for i in range(5):
            s.create(make_workload(f"w{i}", "lq0"))
        assert d.checkpoints >= 2
        assert d.records_since_checkpoint < 2
        res = d.load()
        assert set(res.objects["Workload"]) == {
            f"default/w{i}" for i in range(5)}

    def test_noop_update_not_logged(self):
        d = DurableLog()
        s = Store(durable=d)
        s.create(make_workload("w0", "lq0"))
        before = d.appends
        w = s.get("Workload", "default", "w0")
        s.update(w)  # byte-identical: apiserver no-op semantics
        assert d.appends == before

    def test_delete_and_finalizer_park_logged(self):
        d = DurableLog()
        s = Store(durable=d)
        s.create(make_workload("w0", "lq0"))
        w = s.get("Workload", "default", "w0")
        w.metadata.finalizers = ["kueue.x-k8s.io/resource-in-use"]
        s.update(w)
        s.delete("Workload", "default", "w0")  # parks (finalizer)
        res = d.load()
        parked = res.objects["Workload"]["default/w0"]
        assert parked.metadata.deletion_timestamp is not None
        w = s.get("Workload", "default", "w0")
        w.metadata.finalizers = []
        s.update(w)  # final finalizer stripped -> real delete
        res = d.load()
        assert "default/w0" not in res.objects.get("Workload", {})


# ----------------------------------------------------------------------
# 2/3. kill -> restore -> convergence (tier-1 smoke: one seeded point)
# ----------------------------------------------------------------------

def _mk_manager(clock, durable=True, solver=None, pipeline=None):
    cfg = cfgpkg.Configuration()
    cfg.store.durable = durable
    if solver is not None:
        cfg.solver.enable = True
        cfg.solver.min_heads = 0
        cfg.solver.routing = "always"
        if pipeline is not None:
            cfg.solver.pipeline = pipeline
    mgr = KueueManager(cfg=cfg, clock=clock, solver=solver)
    mgr.store.create(make_flavor())
    for i in range(2):
        mgr.store.create(make_cq(f"cq{i}", cohort="co"))
        mgr.store.create(make_lq(f"lq{i}", f"cq{i}"))
    mgr.run_until_idle()
    return mgr


def _submit(mgr, waves, start=0):
    n = start * 2
    for w in range(start, start + waves):
        for i in range(2):
            mgr.store.create(make_workload(f"w{w}-{i}", f"lq{i}",
                                           creation=float(n)))
            n += 1
    mgr.run_until_idle()


def _drive(mgr, clock, cycles=8):
    for _ in range(cycles):
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle()
        clock.advance(1.0)


class TestKillRestoreSmoke:
    """Sub-second tier-1 smoke: one seeded kill point (CI satellite).
    The multi-seed, every-site sweep is TestCrashSweep (@slow)."""

    def _oracle(self):
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock, durable=False)
        _submit(mgr, 3)
        _drive(mgr, clock)
        return admitted_keys(mgr)

    @pytest.mark.parametrize("site,hit", [
        (faultinject.SITE_STORE, 9),
        (faultinject.SITE_APPLY, 1),
    ])
    def test_kill_restore_converges(self, site, hit):
        oracle = self._oracle()
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock)
        _submit(mgr, 3)
        faultinject.install(FaultInjector({site: {hit: CRASH}}))
        with pytest.raises(InjectedCrash):
            _drive(mgr, clock)
        faultinject.uninstall()
        durable = mgr.durable
        pre = sorted(
            wlpkg.key(wl)
            for wl in durable.load().objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        mgr2 = KueueManager.restore(durable, clock=clock)
        _drive(mgr2, clock)
        final = admitted_keys(mgr2)
        # convergence + never-lose + exactly-once
        assert final == oracle
        assert set(pre) <= set(final)
        crash_run = _load_crash_run()
        ok, msg = crash_run.usage_consistent(mgr2)
        assert ok, msg

    def test_recovery_surface(self):
        """The operator surface of a restore: report, /debug/recovery,
        metrics, flight-recorder trace, system event."""
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock)
        _submit(mgr, 2)
        _drive(mgr, clock, cycles=2)
        mgr.shutdown()
        mgr2 = KueueManager.restore(mgr.durable, clock=clock)
        rep = mgr2.last_recovery
        assert rep.checkpoint_loaded  # shutdown() checkpointed
        assert rep.wal_records_replayed == 0
        assert rep.admitted_restored == 4 and rep.pending_restored == 0
        assert rep.objects["Workload"] == 4
        assert mgr2.metrics.restarts_total.value() == 1
        assert mgr2.metrics.recovery_seconds.count() == 1
        from kueue_tpu.obs import DebugEndpoints
        payload = DebugEndpoints(mgr2.scheduler, mgr2.metrics).handle(
            "/debug/recovery", {})
        assert payload["restored"] and payload["admitted_restored"] == 4
        traces = [t for t in mgr2.flight_recorder.traces()
                  if t.route == "recovery"]
        assert len(traces) == 1
        spans = {name.split(".")[0] for name, _s, _d in traces[0].spans}
        assert "recovery" in spans
        names = {name for name, _s, _d in traces[0].spans}
        assert {"recovery.load", "recovery.replay",
                "recovery.settle"} <= names
        assert mgr2.recorder.by_reason("Restarted")
        assert "-- recovery --" in mgr2.dumper().dump()
        # a cold-started manager reports not-restored
        cold = DebugEndpoints(mgr.scheduler, mgr.metrics).handle(
            "/debug/recovery", {})
        assert cold.pop("generation") == \
            list(mgr.cache.generation_token())
        assert cold == {"restored": False}
        assert "-- recovery --" not in mgr.dumper().dump()

    def test_torn_tail_recovery_warns(self):
        """Crash mid-append: restore falls back to the last intact
        record, counts the torn record, and still converges once the
        lost traffic is resubmitted by its owner (jobs re-create their
        workloads; the store never lies about what it persisted)."""
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock)
        _submit(mgr, 2)
        _drive(mgr, clock, cycles=2)
        mgr.durable.truncate_tail(7)
        mgr2 = KueueManager.restore(mgr.durable, clock=clock)
        assert mgr2.last_recovery.torn_records == 1
        assert mgr2.last_recovery.warnings
        ev = mgr2.recorder.by_reason("Restarted")
        assert ev and ev[0].type == "Warning"

    def test_restore_rv_high_water_survives_deletes(self):
        """A deleted object can hold the resourceVersion high-water
        mark; the restored store must continue ABOVE it, never re-mint
        a used rv."""
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock)
        _submit(mgr, 1)
        w = mgr.store.get("Workload", "default", "w0-0")
        w.spec.priority = 9
        mgr.store.update(w)  # w0-0 now holds the max rv
        rv_max = mgr.store._rv
        mgr.store.delete("Workload", "default", "w0-0")
        mgr.run_until_idle()
        mgr2 = KueueManager.restore(mgr.durable, clock=clock)
        assert mgr2.store._rv >= rv_max
        created = mgr2.store.create(make_workload("fresh", "lq0"))
        assert created.metadata.resource_version > rv_max

    def test_restore_preserves_metadata(self):
        clock = FakeClock(1000.0)
        mgr = _mk_manager(clock)
        _submit(mgr, 1)
        _drive(mgr, clock, cycles=2)
        orig = mgr.store.get("Workload", "default", "w0-0")
        mgr2 = KueueManager.restore(mgr.durable, clock=clock)
        rest = mgr2.store.get("Workload", "default", "w0-0")
        assert rest.metadata.uid == orig.metadata.uid
        assert rest.metadata.resource_version \
            == orig.metadata.resource_version
        assert rest.metadata.creation_timestamp \
            == orig.metadata.creation_timestamp
        assert rest.status.admission is not None
        # store-side RV counter continues past the restored high-water
        w = mgr2.store.get("Workload", "default", "w0-0")
        w.spec.priority = 3
        mgr2.store.update(w)
        assert mgr2.store.get("Workload", "default",
                              "w0-0").metadata.resource_version \
            > orig.metadata.resource_version


# ----------------------------------------------------------------------
# 4. satellites: in-flight drop at shutdown, solver detach
# ----------------------------------------------------------------------

class TestInflightShutdown:
    def _pipelined_mgr(self, clock, solver):
        mgr = _mk_manager(clock, solver=solver, pipeline=True)
        mgr.scheduler.solver_sync_floor_ms = 0
        return mgr

    def test_shutdown_drops_inflight_and_releases(self):
        """ISSUE 10 satellite: a speculative cycle in flight at
        shutdown must release its snapshot handout and invalidate its
        residency/arena claims — previously both leaked until process
        exit."""
        from kueue_tpu.solver import BatchSolver
        clock = FakeClock(1000.0)
        solver = BatchSolver()
        mgr = self._pipelined_mgr(clock, solver)
        _submit(mgr, 2)
        mgr.scheduler.schedule(timeout=0)  # dispatch-only: in flight
        assert mgr.scheduler._inflight is not None
        assert mgr.cache.live_handouts == 0  # steady state leaks none
        mgr.shutdown()
        assert mgr.scheduler._inflight is None
        assert solver._resident is None
        assert mgr.cache.live_handouts == 0
        assert mgr.cache.handouts_taken == mgr.cache.handouts_released

    def test_restore_reuses_solver_after_detach(self):
        """Crash with a cycle in flight; restore with the SAME solver
        object. detach() must drop residency/arena/cache bindings so
        the restored manager's first cycles re-establish from its own
        store — and still converge to the oracle."""
        from kueue_tpu.solver import BatchSolver
        oracle_clock = FakeClock(1000.0)
        omgr = _mk_manager(oracle_clock, durable=False,
                           solver=BatchSolver(), pipeline=True)
        omgr.scheduler.solver_sync_floor_ms = 0
        _submit(omgr, 3)
        _drive(omgr, oracle_clock)
        oracle = admitted_keys(omgr)
        assert oracle  # the scenario admits

        clock = FakeClock(1000.0)
        solver = BatchSolver()
        mgr = self._pipelined_mgr(clock, solver)
        _submit(mgr, 3)
        mgr.scheduler.schedule(timeout=0)  # put a cycle in flight
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {3: CRASH}}))
        with pytest.raises(InjectedCrash):
            _drive(mgr, clock)
        faultinject.uninstall()
        mgr2 = KueueManager.restore(mgr.durable, clock=clock,
                                    solver=solver)
        assert solver._cache is mgr2.cache  # rebound to the new plane
        _drive(mgr2, clock)
        assert admitted_keys(mgr2) == oracle
        crash_run = _load_crash_run()
        ok, msg = crash_run.usage_consistent(mgr2)
        assert ok, msg
        mgr2.shutdown()
        assert mgr2.cache.live_handouts == 0


# ----------------------------------------------------------------------
# 5. the kill-point sweep: every site x many seeds (@slow; the CLI
#    twin is `tools/crash_run.py --sweep`)
# ----------------------------------------------------------------------

def _sweep_site(site, seeds=20):
    crash_run = _load_crash_run()
    import random
    import zlib
    fired = 0
    oracle_by_seed = {}
    for seed in range(seeds):
        # crc32, not hash(): string hashing is randomized per process
        rng = random.Random(
            (zlib.crc32(site.encode()) & 0xFFFF) * 100_000 + seed)
        hit = (rng.randint(5, 120) if site == faultinject.SITE_STORE
               else rng.randint(0, 8))
        if seed not in oracle_by_seed:
            oracle_by_seed[seed] = crash_run.run_oracle(seed)
        crash = crash_run.run_crash(seed, site, hit)
        v = crash_run.verdict(oracle_by_seed[seed], crash)
        fired += 1 if v["crashed"] else 0
        assert v["converged"], (site, seed, hit, crash["recovery"])
        assert not v["lost_admissions"], (site, seed, hit)
        assert not v["double_admission"], (site, seed, hit)
        assert not v["stranded"], (site, seed, hit)
    assert fired > 0, f"site {site} never fired across {seeds} seeds"


@pytest.mark.slow
@pytest.mark.parametrize("site", [
    faultinject.SITE_STORE, faultinject.SITE_APPLY,
    faultinject.SITE_DISPATCH, faultinject.SITE_COLLECT,
    faultinject.SITE_SCATTER, faultinject.SITE_REPLAY,
    faultinject.SITE_SPECULATION,
])
def test_crash_sweep(site):
    """ISSUE 10 acceptance: for every injection site and >= 20 seeds,
    kill -> restore -> replay converges to the uncrashed oracle's
    admitted set with zero double admissions, zero lost admissions,
    and zero stranded state."""
    _sweep_site(site, seeds=20)


@pytest.mark.slow
def test_crash_during_warmup_walk():
    """A crash inside the compile governor's warm body (SITE_WARMUP)
    during a synchronous walk propagates like any process death (the
    supervised worker relays BaseException) and the restored plane
    re-warms from the persistent cache."""
    from kueue_tpu.solver import BatchSolver
    clock = FakeClock(1000.0)
    solver = BatchSolver()
    mgr = _mk_manager(clock, solver=solver)
    _submit(mgr, 2)
    gov = mgr.warm_governor
    if gov is None:
        from kueue_tpu.solver.warmgov import CompileGovernor
        gov = CompileGovernor(solver, mgr.cache, metrics=mgr.metrics)
    faultinject.install(FaultInjector(
        {faultinject.SITE_WARMUP: {0: CRASH}}))
    with pytest.raises(InjectedCrash):
        gov.run_sync()
    faultinject.uninstall()
    mgr2 = KueueManager.restore(mgr.durable, clock=clock, solver=solver)
    _drive(mgr2, clock)
    assert admitted_keys(mgr2)  # the restored plane still admits


@pytest.mark.slow
def test_crash_run_cli_single():
    crash_run = _load_crash_run()
    assert crash_run.one_run(7, faultinject.SITE_STORE, 30) == 0


# ----------------------------------------------------------------------
# 6. the shard-kill/promote arm (ISSUE 20): one scoped crash inside an
#    admission shard; the shared plane survives, the promoted shard
#    converges to the single-manager oracle (tier-1 smoke here; the
#    full sites x layouts x seeds sweep is @slow — CLI twin:
#    `tools/crash_run.py --sweep`, shard arm)
# ----------------------------------------------------------------------

def test_shard_crash_smoke_survivor_and_promote_converge():
    """Tier-1: kill one of two shards mid-apply via its own faultinject
    scope; the co-resident shard keeps admitting through the outage,
    the hot-promoted replacement converges to the uncrashed oracle's
    admitted set, and nothing is lost, doubled or stranded."""
    crash_run = _load_crash_run()
    oracle = crash_run.run_oracle(0)
    crash = crash_run.run_shard(0, faultinject.SITE_APPLY, 3, 2)
    v = crash_run.verdict(oracle, crash)
    assert v["crashed"], "the scripted shard crash never fired"
    assert crash["promotions"] >= 1
    assert v["converged"], crash["recovery"]
    assert not v["lost_admissions"]
    assert not v["double_admission"]
    assert not v["stranded"]
    # Fault isolation: some admissions landed while the victim was dead
    # (the survivors') — the outage was not a full-plane stall.
    assert crash["usage_consistent"]


def _shard_sweep_arm(site, n_shards, seeds=10):
    crash_run = _load_crash_run()
    import random
    import zlib
    fired = 0
    oracle_by_seed = {}
    for seed in range(seeds):
        rng = random.Random(
            (zlib.crc32(site.encode()) & 0xFFFF) * 100_000
            + n_shards * 1000 + seed)
        hit = (rng.randint(2, 20) if site == faultinject.SITE_STORE
               else rng.randint(0, 6))
        if seed not in oracle_by_seed:
            oracle_by_seed[seed] = crash_run.run_oracle(seed)
        crash = crash_run.run_shard(seed, site, hit, n_shards)
        v = crash_run.verdict(oracle_by_seed[seed], crash)
        fired += 1 if v["crashed"] else 0
        assert v["converged"], (site, n_shards, seed, hit,
                                crash["recovery"])
        assert not v["lost_admissions"], (site, n_shards, seed, hit)
        assert not v["double_admission"], (site, n_shards, seed, hit)
        assert not v["stranded"], (site, n_shards, seed, hit)
    assert fired > 0, (f"{site}@{n_shards} shards never fired "
                       f"across {seeds} seeds")


@pytest.mark.slow
@pytest.mark.parametrize("n_shards", [2, 4])
@pytest.mark.parametrize("site", [
    faultinject.SITE_APPLY, faultinject.SITE_STORE,
])
def test_shard_crash_sweep(site, n_shards):
    """ISSUE 20 acceptance: for every shard injection site x layout and
    >= 10 seeds, a scoped mid-cycle shard crash + hot-promote converges
    to the single-manager oracle's admitted set with zero lost, zero
    double (store-vs-cache usage cross-check) and zero stranded."""
    _shard_sweep_arm(site, n_shards, seeds=10)
