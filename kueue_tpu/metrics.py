"""Prometheus-style metrics registry.

Equivalent of the reference's pkg/metrics/metrics.go:55-256. Implemented
as a dependency-free registry (counters/gauges/histograms keyed by label
tuples) with a text exposition dump; the report helpers mirror the
reference's function-per-transition API (AdmissionAttempt,
QuotaReservedWorkload, ReportEvictedWorkloads, ...), and wait-time
histograms use the same exponential 1 s -> 10,240 s buckets
(generateExponentialBuckets, metrics.go:258-260).
"""

from __future__ import annotations

import math
import threading
from typing import Optional, Sequence

# admission results (reference: metrics.go:30-36)
ADMISSION_RESULT_SUCCESS = "success"
ADMISSION_RESULT_INADMISSIBLE = "inadmissible"

# cluster-queue statuses (reference: metrics.go:40-56)
CQ_STATUS_PENDING = "pending"
CQ_STATUS_ACTIVE = "active"
CQ_STATUS_TERMINATING = "terminating"
CQ_STATUSES = [CQ_STATUS_PENDING, CQ_STATUS_ACTIVE, CQ_STATUS_TERMINATING]

# pending-workload statuses (reference: metrics.go:97-106)
PENDING_STATUS_ACTIVE = "active"
PENDING_STATUS_INADMISSIBLE = "inadmissible"


def exponential_buckets(start: float, factor: float, count: int) -> list[float]:
    return [start * factor**i for i in range(count)]


def _bucket_quantile(buckets: list, counts: list, total: int,
                     q: float) -> float:
    """Promql-style bucket interpolation shared by Histogram.percentile
    and Registry.phase_percentile; ``counts`` are per-bucket
    (non-cumulative) including the +Inf bucket. Values past the last
    finite bucket clamp to it."""
    if total == 0 or not buckets:
        return math.nan
    target = q * total
    cum = 0
    lower = 0.0
    for i, ub in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= target:
            frac = (target - prev) / counts[i] if counts[i] else 0.0
            return lower + (ub - lower) * frac
        lower = ub
    return buckets[-1]


def wait_time_buckets() -> list[float]:
    """1, 2.5, 5, 10, ... 10240 (reference: metrics.go:258-260, count=14)."""
    return [1.0] + exponential_buckets(2.5, 2, 13)


_DEFAULT_BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]

# Cycle-phase spans are sub-millisecond to seconds (a remote compile);
# finer low buckets than the default so encode/route regressions move
# the estimated percentiles.
_PHASE_BUCKETS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0]
_HEADS_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096]

# breaker_state gauge encoding (resilience.breaker state names)
BREAKER_STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}

# degraded_state gauge encoding: the ladder owns the mapping (a rung
# added there must never silently report -1 here)
from kueue_tpu.resilience.degrade import (  # noqa: E402
    STATE_CODES as DEGRADED_STATE_CODES,
)


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        return tuple(labels.get(n, "") for n in self.label_names)

    def delete_partial_match(self, match: dict) -> None:
        idxs = {self.label_names.index(k): v for k, v in match.items()}
        with self._lock:
            for key in [k for k in self._series()
                        if all(k[i] == v for i, v in idxs.items())]:
                self._delete(key)


class _ValueMetric(_Metric):
    """Shared scalar-series storage for Counter and Gauge: one float
    per label key, deltas applied under the metric's own lock
    (concurrent HTTP threads drive e.g. the in-flight-reads gauge; a
    read-modify-write through value()/set() would drop counts)."""

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self.values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self.values[key] = self.values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self.values.get(self._key(labels), 0.0)

    def _series(self):
        return list(self.values)

    def _delete(self, key):
        self.values.pop(key, None)


class Counter(_ValueMetric):
    pass


class Gauge(_ValueMetric):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self.values[self._key(labels)] = value


class Histogram(_Metric):
    def __init__(self, name, help_, label_names=(), buckets: Optional[list] = None):
        super().__init__(name, help_, label_names)
        self.buckets = list(buckets) if buckets else list(_DEFAULT_BUCKETS)
        # key -> (bucket counts incl +Inf, sum, count)
        self.series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            if key not in self.series:
                self.series[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            counts, _, _ = self.series[key]
            idx = len(self.buckets)
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    idx = i
                    break
            counts[idx] += 1
            self.series[key][1] += value
            self.series[key][2] += 1

    def count(self, **labels) -> int:
        s = self.series.get(self._key(labels))
        return s[2] if s else 0

    def sum(self, **labels) -> float:
        s = self.series.get(self._key(labels))
        return s[1] if s else 0.0

    def percentile(self, q: float, **labels) -> float:
        """Estimate the q-quantile from bucket counts (promql-style)."""
        s = self.series.get(self._key(labels))
        if not s or s[2] == 0:
            return math.nan
        counts, _, total = s
        return _bucket_quantile(self.buckets, counts, total, q)

    def _series(self):
        return list(self.series)

    def _delete(self, key):
        self.series.pop(key, None)


class Registry:
    """One instance per manager; tests construct their own
    (the reference's package-level singletons make parallel tests share
    state — avoided here)."""

    def __init__(self):
        wt = wait_time_buckets()
        self.admission_attempts_total = Counter(
            "kueue_admission_attempts_total",
            "Total number of attempts to admit workloads (label result: success|inadmissible)",
            ["result"])
        self.admission_attempt_duration = Histogram(
            "kueue_admission_attempt_duration_seconds",
            "Latency of an admission attempt", ["result"])
        self.admission_cycle_preemption_skips = Gauge(
            "kueue_admission_cycle_preemption_skips",
            "Workloads skipped in the last cycle because of overlapping preemptions",
            ["cluster_queue"])
        self.pending_workloads = Gauge(
            "kueue_pending_workloads",
            "Number of pending workloads (label status: active|inadmissible)",
            ["cluster_queue", "status"])
        self.quota_reserved_workloads_total = Counter(
            "kueue_quota_reserved_workloads_total",
            "Total number of quota-reserved workloads", ["cluster_queue"])
        self.quota_reserved_wait_time = Histogram(
            "kueue_quota_reserved_wait_time_seconds",
            "Time from creation/requeue to quota reservation",
            ["cluster_queue"], buckets=wt)
        self.admitted_workloads_total = Counter(
            "kueue_admitted_workloads_total",
            "Total number of admitted workloads", ["cluster_queue"])
        self.admission_wait_time = Histogram(
            "kueue_admission_wait_time_seconds",
            "Time from creation/requeue to admission", ["cluster_queue"], buckets=wt)
        self.admission_checks_wait_time = Histogram(
            "kueue_admission_checks_wait_time_seconds",
            "Time from quota reservation to admission", ["cluster_queue"], buckets=wt)
        self.evicted_workloads_total = Counter(
            "kueue_evicted_workloads_total",
            "Total evicted workloads by reason", ["cluster_queue", "reason"])
        self.preempted_workloads_total = Counter(
            "kueue_preempted_workloads_total",
            "Total preempted workloads by reason", ["preempting_cluster_queue", "reason"])
        self.reserving_active_workloads = Gauge(
            "kueue_reserving_active_workloads",
            "Workloads currently reserving quota", ["cluster_queue"])
        self.admitted_active_workloads = Gauge(
            "kueue_admitted_active_workloads",
            "Workloads currently admitted", ["cluster_queue"])
        self.cluster_queue_status = Gauge(
            "kueue_cluster_queue_status",
            "ClusterQueue status flags (pending|active|terminating)",
            ["cluster_queue", "status"])
        # optional per-resource metrics (reference: metrics.go:207-255)
        self.cluster_queue_resource_reservation = Gauge(
            "kueue_cluster_queue_resource_reservation",
            "Reserved quantity per CQ/flavor/resource",
            ["cohort", "cluster_queue", "flavor", "resource"])
        self.cluster_queue_resource_usage = Gauge(
            "kueue_cluster_queue_resource_usage",
            "Admitted usage per CQ/flavor/resource",
            ["cohort", "cluster_queue", "flavor", "resource"])
        self.cluster_queue_nominal_quota = Gauge(
            "kueue_cluster_queue_nominal_quota",
            "Nominal quota per CQ/flavor/resource",
            ["cohort", "cluster_queue", "flavor", "resource"])
        self.cluster_queue_borrowing_limit = Gauge(
            "kueue_cluster_queue_borrowing_limit",
            "Borrowing limit per CQ/flavor/resource",
            ["cohort", "cluster_queue", "flavor", "resource"])
        self.cluster_queue_lending_limit = Gauge(
            "kueue_cluster_queue_lending_limit",
            "Lending limit per CQ/flavor/resource",
            ["cohort", "cluster_queue", "flavor", "resource"])
        self.cluster_queue_weighted_share = Gauge(
            "kueue_cluster_queue_weighted_share",
            "Maximum weighted borrowed share (0 = within nominal quota)",
            ["cluster_queue"])
        # Device-fault containment (kueue_tpu/resilience; no reference
        # analogue): solver-path faults, watchdog timeouts, breaker
        # trips, and how long the last outage took to recover.
        self.device_faults_total = Counter(
            "kueue_solver_device_faults_total",
            "Device-path faults by site (dispatch|collect|solve|prepare)",
            ["site"])
        self.dispatch_timeouts_total = Counter(
            "kueue_solver_dispatch_timeouts_total",
            "Device collects abandoned by the dispatch watchdog deadline")
        self.breaker_trips_total = Counter(
            "kueue_solver_breaker_trips_total",
            "Circuit-breaker trips (device route suspended to cpu-breaker)")
        self.fault_recovery_cycles = Gauge(
            "kueue_solver_fault_recovery_cycles",
            "Cycles from the last breaker trip until the device route "
            "was restored by a successful half-open probe")
        # Cycle flight recorder (kueue_tpu/obs): per-cycle phase spans,
        # fed from each sealed CycleTrace so /debug/cycles and /metrics
        # reconcile by construction.
        self.cycle_phase_seconds = Histogram(
            "kueue_cycle_phase_seconds",
            "Per-cycle wall seconds by phase (snapshot|encode|route|"
            "dispatch|fetch|decode|preempt-plan|nominate|apply|requeue) "
            "and route", ["phase", "route"], buckets=_PHASE_BUCKETS)
        self.cycle_heads = Histogram(
            "kueue_cycle_heads",
            "Heads processed per admission cycle by route",
            ["route"], buckets=_HEADS_BUCKETS)
        self.breaker_state = Gauge(
            "kueue_solver_breaker_state",
            "Circuit-breaker state (0=closed, 1=half-open, 2=open)")
        # Bounded-cycle admission (kueue_tpu/resilience/degrade.py +
        # supervisor.py): degradation-ladder state, shed cycles, and
        # dispatches abandoned by the supervised worker deadline.
        self.degraded_state = Gauge(
            "kueue_scheduler_degraded_state",
            "Degradation-ladder state (0=normal, 1=shed, 2=survival)")
        self.cycles_shed_total = Counter(
            "kueue_scheduler_cycles_shed_total",
            "Admission cycles run in a degraded state (label state: "
            "shed|survival)", ["state"])
        self.dispatch_supervised_timeouts_total = Counter(
            "kueue_solver_dispatch_supervised_timeouts_total",
            "Dispatches abandoned by the supervised solver-worker "
            "deadline (hang during trace/compile/transfer)")
        # Compile governor (solver/warmgov.py + solver/COMPILE.md):
        # per-bucket compile provenance and the governor's warm-state
        # machine, plus warmup attempts that faulted.
        self.compile_events_total = Counter(
            "kueue_solver_compile_events_total",
            "Kernel programs compiled or loaded per shape bucket by "
            "source (fresh|cache-hit|jit-cache)", ["bucket", "source"])
        self.warmup_state = Gauge(
            "kueue_solver_warmup_state",
            "Compile-governor state (0=idle, 1=warming, 2=warm, "
            "3=partial)")
        self.warmup_faults_total = Counter(
            "kueue_solver_warmup_faults_total",
            "Warmup bucket attempts that faulted (compile errors or "
            "per-bucket deadline abandonments; the ladder continues)")
        # Speculative admission pipeline (scheduler/PIPELINE.md):
        # validated-and-committed speculative cycles vs mis-speculation
        # aborts by validation reason (topology-epoch | cohort-epoch |
        # flavor-spec-epoch | residency | arena-slots |
        # journal-overflow | injected).
        self.speculation_hits_total = Counter(
            "kueue_scheduler_speculation_hits_total",
            "Speculative pipelined results validated and committed")
        self.speculation_aborts_total = Counter(
            "kueue_scheduler_speculation_aborts_total",
            "Speculative pipelined results abandoned at apply-validation "
            "by reason", ["reason"])
        # Crash-restart durability (resilience/recovery.py +
        # RESILIENCE.md §6): restarts recovered from the durable store
        # and how long the rebuild (load + replay + settle) took.
        self.restarts_total = Counter(
            "kueue_manager_restarts_total",
            "Control-plane restarts recovered from the durable store")
        self.recovery_seconds = Histogram(
            "kueue_manager_recovery_seconds",
            "Wall seconds from restore() entry to a settled control "
            "plane (checkpoint load + WAL replay + reconcile drain)",
            buckets=exponential_buckets(0.005, 2.0, 16))
        # Hot-standby replication (resilience/replica.py +
        # RESILIENCE.md §7): how far the follower's WAL tail replay
        # lags the leader's append head, the fencing epoch in effect,
        # and standby-to-leader promotions.
        self.replication_lag_records = Gauge(
            "kueue_replication_lag_records",
            "WAL records appended by the leader that this standby "
            "replica has not yet applied (refreshed at every poll; 0 "
            "after a drain)")
        self.replication_lag_seconds = Gauge(
            "kueue_replication_lag_seconds",
            "Virtual seconds between the newest WAL record and the "
            "newest this replica applied")
        self.fencing_epoch_gauge = Gauge(
            "kueue_fencing_epoch",
            "The durable log's current leader-lease fencing epoch as "
            "this replica last observed it (a deposed leader's writes "
            "are rejected the moment this advances past its token)")
        self.promotions_total = Counter(
            "kueue_replica_promotions_total",
            "Standby replicas promoted to leadership (sub-cycle "
            "failover, RESILIENCE.md §7)")
        self.promotion_seconds = Histogram(
            "kueue_replica_promotion_seconds",
            "Wall seconds for a standby promotion (fence + tail drain "
            "+ settle + checkpoint)",
            buckets=exponential_buckets(0.001, 2.0, 16))
        # Snapshot-backed query plane (obs/queryplane.py): read-side
        # saturation — per-route request counts by HTTP code, request
        # latency, the sealed view's age, and reads in flight. Fed by
        # the VisibilityServer so the read plane's load shows up in the
        # SAME registry the admission metrics live in.
        self.visibility_requests_total = Counter(
            "kueue_visibility_requests_total",
            "Visibility/query-plane HTTP requests by route and status "
            "code (routes: cq_pending|lq_pending|workload|metrics|"
            "debug|unknown)", ["route", "code"])
        self.visibility_request_seconds = Histogram(
            "kueue_visibility_request_seconds",
            "Visibility/query-plane HTTP request latency by route",
            ["route"], buckets=_PHASE_BUCKETS)
        self.visibility_snapshot_age_seconds = Gauge(
            "kueue_visibility_snapshot_age_seconds",
            "Age of the query plane's sealed view (seconds since the "
            "last cycle-seal publish; 0 is written at each publish)")
        self.visibility_inflight_reads = Gauge(
            "kueue_visibility_inflight_reads",
            "Query-plane HTTP reads currently being served")
        # Workload journey ledger (obs/journey.py + ISSUE 14): per-class
        # time-to-admission SLIs folded from sealed journeys (the SAME
        # seal that feeds admission_wait_time — one emission site, so
        # /debug/journeys and /metrics reconcile by construction), the
        # requeue-amplification soak invariant (ROADMAP item 5), the
        # burn-rate evaluator's output, and the ledger's LRU pressure.
        self.journey_tta_seconds = Histogram(
            "kueue_journey_tta_seconds",
            "Time-to-admission of sealed workload journeys by SLI class",
            ["cls"], buckets=wt)
        self.journeys_completed_total = Counter(
            "kueue_journeys_completed_total",
            "Workload journeys sealed by full admission, by SLI class",
            ["cls"])
        self.requeues_per_admission = Gauge(
            "kueue_requeues_per_admission",
            "Requeue-class journey events (cycle re-heaps: requeued or "
            "shed) per sealed admission — the requeue-amplification "
            "soak invariant (ROADMAP item 5); refreshed at each cycle "
            "seal")
        self.slo_burn_rate = Gauge(
            "kueue_slo_burn_rate",
            "Per-class SLO burn rate: EWMA of the TTA-objective "
            "violation indicator divided by the error budget fraction "
            "(1.0 = burning exactly at budget; >1 = too fast)", ["cls"])
        self.journey_ledger_evictions_total = Counter(
            "kueue_journey_ledger_evictions_total",
            "Active journeys dropped by the ledger's LRU capacity bound")
        # Coarse reconciler latency (ROADMAP PR-4 follow-up: the
        # wall_s - cycle_time_total gap had no signal); fed by the sim
        # Runtime around every reconcile call.
        self.reconcile_seconds = Histogram(
            "kueue_reconcile_seconds",
            "Reconcile latency by controller", ["controller"],
            buckets=_PHASE_BUCKETS)
        # Per-event split of the reconcile latency (PR-5 left it
        # coarse): the hot reconcilers time their internal event
        # handlers and feed this alongside nested flight-recorder spans
        # (reconcile.{controller}.{event}).
        self.reconcile_event_seconds = Histogram(
            "kueue_reconcile_event_seconds",
            "Reconcile latency by controller and handled event",
            ["controller", "event"], buckets=_PHASE_BUCKETS)
        # Sharded admission control plane (parallel/shards.py +
        # RESILIENCE.md §9): per-shard lifecycle state, planner-driven
        # cohort moves, and per-shard admission throughput.
        self.shard_state = Gauge(
            "kueue_shard_state",
            "Admission shard lifecycle state "
            "(0=active 1=killed 2=fenced)", ["shard"])
        self.shard_rebalances_total = Counter(
            "kueue_shard_rebalances_total",
            "Planner-driven cohort moves between admission shards")
        self.shard_admitted_total = Counter(
            "kueue_shard_admitted_total",
            "Workloads admitted, by owning admission shard", ["shard"])
        self.shard_promotions_total = Counter(
            "kueue_shard_promotions_total",
            "Hot-promotions of a replacement shard over a killed or "
            "fenced one", ["shard"])
        self._all = [v for v in vars(self).values() if isinstance(v, _Metric)]

    # --- report helpers (reference: metrics.go:262-400) ---

    def admission_attempt(self, result: str, duration_s: float) -> None:
        self.admission_attempts_total.inc(result=result)
        self.admission_attempt_duration.observe(duration_s, result=result)

    def quota_reserved_workload(self, cq: str, wait_s: float) -> None:
        self.quota_reserved_workloads_total.inc(cluster_queue=cq)
        self.quota_reserved_wait_time.observe(wait_s, cluster_queue=cq)

    def admitted_workload(self, cq: str, wait_s: float) -> None:
        self.admitted_workloads_total.inc(cluster_queue=cq)
        self.admission_wait_time.observe(wait_s, cluster_queue=cq)

    # short aliases used by the scheduler hot path
    def quota_reserved(self, cq: str, wait_s: float) -> None:
        self.quota_reserved_workload(cq, wait_s)

    def admitted(self, cq: str, wait_s: float) -> None:
        self.admitted_workload(cq, wait_s)

    def preempted(self, preempting_cq: str, reason: str) -> None:
        self.preempted_workloads_total.inc(
            preempting_cluster_queue=preempting_cq, reason=reason)

    def preemption_skips(self, cq: str, count: int) -> None:
        self.admission_cycle_preemption_skips.set(count, cluster_queue=cq)

    def device_fault(self, site: str, timeout: bool = False,
                     tripped: bool = False,
                     supervised: bool = False) -> None:
        self.device_faults_total.inc(site=site)
        if timeout:
            self.dispatch_timeouts_total.inc()
        if supervised:
            self.dispatch_supervised_timeouts_total.inc()
        if tripped:
            self.breaker_trips_total.inc()

    def fault_recovered(self, cycles: int) -> None:
        self.fault_recovery_cycles.set(cycles)

    def compile_event(self, bucket: str, source: str, n: int = 1) -> None:
        self.compile_events_total.inc(n, bucket=bucket, source=source)

    def set_warmup_state(self, state: str) -> None:
        # Lazy import: the governor module owns the state encoding, but
        # pulls in the (jax-heavy) solver package — callers without a
        # governor must not pay that import.
        from kueue_tpu.solver.warmgov import WARMUP_STATE_CODES
        self.warmup_state.set(WARMUP_STATE_CODES.get(state, -1))

    def warmup_fault(self) -> None:
        self.warmup_faults_total.inc()

    def set_degraded_state(self, state: str) -> None:
        self.degraded_state.set(DEGRADED_STATE_CODES.get(state, -1))

    def cycle_shed(self, state: str) -> None:
        self.cycles_shed_total.inc(state=state)

    def reconcile_observed(self, controller: str, seconds: float) -> None:
        self.reconcile_seconds.observe(seconds, controller=controller)

    def reconcile_event(self, controller: str, event: str,
                        seconds: float) -> None:
        self.reconcile_event_seconds.observe(seconds,
                                             controller=controller,
                                             event=event)

    def restart_recovered(self, seconds: float) -> None:
        self.restarts_total.inc()
        self.recovery_seconds.observe(seconds)

    def replication_lag(self, records: float, seconds: float) -> None:
        self.replication_lag_records.set(records)
        self.replication_lag_seconds.set(seconds)

    def set_fencing_epoch(self, epoch: int) -> None:
        self.fencing_epoch_gauge.set(epoch)

    def set_shard_state(self, shard: str, state: str) -> None:
        # The shard module owns the encoding (like the ladder/governor
        # patterns above); lazy import keeps metrics free of the
        # manager-assembly import chain shards.py pulls in.
        from kueue_tpu.parallel.shards import SHARD_STATE_CODES
        self.shard_state.set(SHARD_STATE_CODES.get(state, -1), shard=shard)

    def shard_admitted(self, shard: str, n: int) -> None:
        if n:
            self.shard_admitted_total.inc(n, shard=shard)

    def shard_rebalanced(self) -> None:
        self.shard_rebalances_total.inc()

    def shard_promoted(self, shard: str) -> None:
        self.shard_promotions_total.inc(shard=shard)

    def replica_promoted(self, epoch: int, seconds: float) -> None:
        self.promotions_total.inc()
        self.promotion_seconds.observe(seconds)
        self.fencing_epoch_gauge.set(epoch)

    def visibility_request(self, route: str, code: int,
                           seconds: float) -> None:
        self.visibility_requests_total.inc(route=route, code=str(code))
        self.visibility_request_seconds.observe(seconds, route=route)

    def visibility_read_begin(self) -> None:
        self.visibility_inflight_reads.inc(1)

    def visibility_read_end(self) -> None:
        self.visibility_inflight_reads.inc(-1)

    def set_visibility_snapshot_age(self, seconds: float) -> None:
        self.visibility_snapshot_age_seconds.set(seconds)

    def journey_completed(self, cls: str, tta_s: float) -> None:
        self.journeys_completed_total.inc(cls=cls)
        self.journey_tta_seconds.observe(tta_s, cls=cls)

    def set_requeue_amplification(self, value: float) -> None:
        self.requeues_per_admission.set(value)

    def set_slo_burn(self, cls: str, rate: float) -> None:
        self.slo_burn_rate.set(rate, cls=cls)

    def journey_lru_evicted(self) -> None:
        self.journey_ledger_evictions_total.inc()

    def speculation_hit(self) -> None:
        self.speculation_hits_total.inc()

    def speculation_abort(self, reason: str) -> None:
        self.speculation_aborts_total.inc(reason=reason)

    def cycle_observed(self, route: str, heads: int,
                       phase_sums: dict) -> None:
        """One sealed cycle trace: head count + per-phase wall seconds
        (the trace's top-level span sums)."""
        self.cycle_heads.observe(heads, route=route)
        for phase, secs in phase_sums.items():
            self.cycle_phase_seconds.observe(secs, phase=phase, route=route)

    def set_breaker_state(self, state: str) -> None:
        self.breaker_state.set(BREAKER_STATE_CODES.get(state, -1))

    def phase_percentile(self, phase: str, q: float) -> float:
        """Estimate the q-quantile of cycle_phase_seconds for one phase,
        merged across routes (promql-style bucket interpolation). NaN
        when the phase has no observations."""
        h = self.cycle_phase_seconds
        pi = h.label_names.index("phase")
        merged = [0] * (len(h.buckets) + 1)
        total = 0
        with h._lock:
            for key, (counts, _sum, n) in h.series.items():
                if key[pi] != phase:
                    continue
                for i, c in enumerate(counts):
                    merged[i] += c
                total += n
        return _bucket_quantile(h.buckets, merged, total, q)

    def report_pending_workloads(self, cq: str, active: int, inadmissible: int) -> None:
        self.pending_workloads.set(active, cluster_queue=cq, status=PENDING_STATUS_ACTIVE)
        self.pending_workloads.set(inadmissible, cluster_queue=cq,
                                   status=PENDING_STATUS_INADMISSIBLE)

    def report_evicted_workload(self, cq: str, reason: str) -> None:
        self.evicted_workloads_total.inc(cluster_queue=cq, reason=reason)

    def report_cluster_queue_status(self, cq: str, status: str) -> None:
        for s in CQ_STATUSES:
            self.cluster_queue_status.set(1.0 if s == status else 0.0,
                                          cluster_queue=cq, status=s)

    def report_cluster_queue_quotas(self, cohort: str, cq: str, flavor: str,
                                    resource: str, nominal: float,
                                    borrowing: float, lending: float) -> None:
        lbl = dict(cohort=cohort, cluster_queue=cq, flavor=flavor, resource=resource)
        self.cluster_queue_nominal_quota.set(nominal, **lbl)
        self.cluster_queue_borrowing_limit.set(borrowing, **lbl)
        self.cluster_queue_lending_limit.set(lending, **lbl)

    def clear_cluster_queue_metrics(self, cq: str) -> None:
        """ClearClusterQueueMetrics + ClearCacheMetrics (metrics.go:295-324)."""
        for metric in self._all:
            if "cluster_queue" in metric.label_names:
                metric.delete_partial_match({"cluster_queue": cq})
        self.preempted_workloads_total.delete_partial_match(
            {"preempting_cluster_queue": cq})

    # --- exposition ---

    def dump(self) -> str:
        lines = []
        for m in self._all:
            lines.append(f"# HELP {m.name} {m.help}")
            if isinstance(m, (Counter, Gauge)):
                kind = "counter" if isinstance(m, Counter) else "gauge"
                lines.append(f"# TYPE {m.name} {kind}")
                for key, val in sorted(m.values.items()):
                    lines.append(f"{m.name}{_fmt_labels(m.label_names, key)} {val}")
            else:
                lines.append(f"# TYPE {m.name} histogram")
                for key, (counts, total, n) in sorted(m.series.items()):
                    cum = 0
                    for i, ub in enumerate(m.buckets):
                        cum += counts[i]
                        lines.append(
                            f"{m.name}_bucket{_fmt_labels(m.label_names, key, le=ub)} {cum}")
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels(m.label_names, key, le='+Inf')} {n}")
                    lines.append(f"{m.name}_sum{_fmt_labels(m.label_names, key)} {total}")
                    lines.append(f"{m.name}_count{_fmt_labels(m.label_names, key)} {n}")
        return "\n".join(lines) + "\n"


def _fmt_labels(names: tuple, key: tuple, le=None) -> str:
    pairs = [f'{n}="{v}"' for n, v in zip(names, key)]
    if le is not None:
        pairs.append(f'le="{le}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""
