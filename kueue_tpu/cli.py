"""kueuectl — the operator CLI.

Equivalent of the reference's cmd/kueuectl (app/cmd.go:79-90):
create {clusterqueue,localqueue,resourceflavor}, list {clusterqueue,
localqueue,workload,resourceflavor,pods --for kind/name}, stop/resume
{workload,clusterqueue,localqueue} (via spec.active / stopPolicy),
version, plus the pass-through verbs get/describe/delete/patch/edit
(app/passthrough/passthrough.go:33-39 — the reference delegates these to
kubectl; here the store IS the apiserver, so they execute directly, with
the same wl/cq/lq/rf aliases). The command core is the `Kueuectl` class
over a manager's store (tests drive it directly); `main()` wraps it in
argparse against a demo manager.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Optional

from kueue_tpu import version as versionpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import ObjectMeta
from kueue_tpu.core import workload as wlpkg

# pass-through resource aliases (reference: passthrough.go:35-39)
KIND_ALIASES = {
    "workload": "Workload", "wl": "Workload",
    "clusterqueue": "ClusterQueue", "cq": "ClusterQueue",
    "localqueue": "LocalQueue", "lq": "LocalQueue",
    "resourceflavor": "ResourceFlavor", "rf": "ResourceFlavor",
}
CLUSTER_SCOPED = {"ClusterQueue", "ResourceFlavor"}


def _to_dict(obj):
    if dataclasses.is_dataclass(obj):
        return {f.name: _to_dict(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: _to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_to_dict(v) for v in obj]
    return obj


def _merge_patch(target, patch: dict) -> None:
    """RFC 7386-style merge onto a typed object tree: dict values recurse
    into nested dataclasses / dicts, None deletes dict keys, everything
    else replaces. Typed lists are replaced wholesale only when the patch
    supplies plain values (the common kubectl-patch admin edits: scalars
    like spec.active, spec.stopPolicy, labels, quotas)."""
    for key, value in patch.items():
        if isinstance(target, dict):
            if value is None:
                target.pop(key, None)
            elif isinstance(value, dict) and isinstance(target.get(key), dict):
                _merge_patch(target[key], value)
            else:
                target[key] = value
            continue
        if not hasattr(target, key):
            from kueue_tpu.sim import Invalid
            raise Invalid(f"unknown field {key!r} on {type(target).__name__}")
        current = getattr(target, key)
        if isinstance(value, dict) and (dataclasses.is_dataclass(current)
                                        or isinstance(current, dict)):
            _merge_patch(current, value)
        else:
            setattr(target, key, value)


class Kueuectl:
    def __init__(self, manager, out=None):
        self.manager = manager
        self.store = manager.store
        self.out = out or sys.stdout

    def _print(self, *cols):
        print("\t".join(str(c) for c in cols), file=self.out)

    # -- create (reference: app/create/) --------------------------------

    def create_cluster_queue(self, name: str, cohort: str = "",
                             queueing_strategy: str = api.BEST_EFFORT_FIFO,
                             nominal_quota: Optional[dict] = None,
                             flavor: str = "default") -> api.ClusterQueue:
        cq = api.ClusterQueue(metadata=ObjectMeta(name=name))
        cq.spec.cohort = cohort
        cq.spec.queueing_strategy = queueing_strategy
        cq.spec.namespace_selector = api.LabelSelector()
        if nominal_quota:
            cq.spec.resource_groups = [api.ResourceGroup(
                covered_resources=list(nominal_quota),
                flavors=[api.FlavorQuotas(name=flavor, resources=[
                    api.ResourceQuota(name=res, nominal_quota=qty)
                    for res, qty in nominal_quota.items()])])]
        return self.store.create(cq)

    def create_local_queue(self, name: str, namespace: str,
                           cluster_queue: str) -> api.LocalQueue:
        lq = api.LocalQueue(metadata=ObjectMeta(name=name, namespace=namespace))
        lq.spec.cluster_queue = cluster_queue
        return self.store.create(lq)

    def create_resource_flavor(self, name: str,
                               node_labels: Optional[dict] = None) -> api.ResourceFlavor:
        rf = api.ResourceFlavor(metadata=ObjectMeta(name=name))
        if node_labels:
            rf.spec.node_labels = dict(node_labels)
        return self.store.create(rf)

    # -- list (reference: app/list/) ------------------------------------

    def list_cluster_queues(self) -> list:
        out = self.store.list("ClusterQueue")
        self._print("NAME", "COHORT", "STRATEGY", "PENDING", "ADMITTED", "ACTIVE")
        for cq in sorted(out, key=lambda c: c.metadata.name):
            from kueue_tpu.api.meta import is_condition_true
            self._print(cq.metadata.name, cq.spec.cohort,
                        cq.spec.queueing_strategy,
                        cq.status.pending_workloads,
                        cq.status.admitted_workloads,
                        is_condition_true(cq.status.conditions,
                                          api.CLUSTER_QUEUE_ACTIVE))
        return out

    def list_local_queues(self, namespace: Optional[str] = None) -> list:
        out = self.store.list("LocalQueue", namespace=namespace)
        self._print("NAMESPACE", "NAME", "CLUSTERQUEUE", "PENDING", "ADMITTED")
        for lq in sorted(out, key=lambda q: (q.metadata.namespace, q.metadata.name)):
            self._print(lq.metadata.namespace, lq.metadata.name,
                        lq.spec.cluster_queue, lq.status.pending_workloads,
                        lq.status.admitted_workloads)
        return out

    def list_workloads(self, namespace: Optional[str] = None) -> list:
        out = self.store.list("Workload", namespace=namespace)
        self._print("NAMESPACE", "NAME", "QUEUE", "STATUS", "PRIORITY")
        for wl in sorted(out, key=lambda w: (w.metadata.namespace, w.metadata.name)):
            self._print(wl.metadata.namespace, wl.metadata.name,
                        wl.spec.queue_name, wlpkg.status(wl),
                        wl.spec.priority if wl.spec.priority is not None else 0)
        return out

    def list_resource_flavors(self) -> list:
        out = self.store.list("ResourceFlavor")
        self._print("NAME", "NODELABELS")
        for rf in sorted(out, key=lambda r: r.metadata.name):
            self._print(rf.metadata.name, rf.spec.node_labels)
        return out

    # -- stop / resume (reference: app/stop, app/resume) ----------------

    def stop_workload(self, namespace: str, name: str) -> None:
        wl = self.store.get("Workload", namespace, name)
        wl.spec.active = False
        self.store.update(wl)

    def resume_workload(self, namespace: str, name: str) -> None:
        wl = self.store.get("Workload", namespace, name)
        wl.spec.active = True
        self.store.update(wl)

    def stop_cluster_queue(self, name: str, drain: bool = True) -> None:
        cq = self.store.get("ClusterQueue", "", name)
        cq.spec.stop_policy = api.HOLD_AND_DRAIN if drain else api.HOLD
        self.store.update(cq)

    def resume_cluster_queue(self, name: str) -> None:
        cq = self.store.get("ClusterQueue", "", name)
        cq.spec.stop_policy = api.STOP_POLICY_NONE
        self.store.update(cq)

    def stop_local_queue(self, namespace: str, name: str,
                         drain: bool = True) -> None:
        lq = self.store.get("LocalQueue", namespace, name)
        lq.spec.stop_policy = api.HOLD_AND_DRAIN if drain else api.HOLD
        self.store.update(lq)

    def resume_local_queue(self, namespace: str, name: str) -> None:
        lq = self.store.get("LocalQueue", namespace, name)
        lq.spec.stop_policy = api.STOP_POLICY_NONE
        self.store.update(lq)

    def list_pods_for(self, for_ref: str,
                      namespace: str = "default") -> list:
        """`kueuectl list pods --for kind/name` (reference:
        app/list/list_pods.go): the pods belonging to a job-framework
        object — matched by ownerReference to the object, or, for
        `--for pod/<name>`, the named pod's whole pod group."""
        kind, _, name = for_ref.partition("/")
        if not name:
            raise ValueError("--for requires kind/name (e.g. job/my-job)")
        kind = kind.lower()
        pods = self.store.list("Pod", namespace=namespace)
        if kind == "pod":
            from kueue_tpu.controller.jobs.pod import GROUP_NAME_LABEL
            anchor = next((p for p in pods if p.metadata.name == name), None)
            group = (anchor.metadata.labels.get(GROUP_NAME_LABEL)
                     if anchor is not None else None)
            if group:
                out = [p for p in pods
                       if p.metadata.labels.get(GROUP_NAME_LABEL) == group]
            else:
                out = [anchor] if anchor is not None else []
        else:
            out = [p for p in pods if any(
                o.kind.lower() == kind and o.name == name
                for o in p.metadata.owner_references)]
        self._print("NAME", "PHASE", "GATED")
        for p in sorted(out, key=lambda p: p.metadata.name):
            gated = api.ADMISSION_GATE in p.spec.scheduling_gates
            self._print(p.metadata.name, p.status.phase, gated)
        return out

    # -- pass-through verbs (reference: app/passthrough/passthrough.go) --

    def _resolve(self, kind: str, namespace: str):
        k = KIND_ALIASES[kind.lower()]
        ns = "" if k in CLUSTER_SCOPED else namespace
        return k, ns

    def get(self, kind: str, name: str, namespace: str = "default") -> dict:
        k, ns = self._resolve(kind, namespace)
        obj = self.store.get(k, ns, name)
        data = _to_dict(obj)
        self._print(json.dumps(data, indent=2, default=str, sort_keys=True))
        return data

    def describe(self, kind: str, name: str,
                 namespace: str = "default") -> dict:
        k, ns = self._resolve(kind, namespace)
        obj = self.store.get(k, ns, name)
        self._print(f"Name:\t{obj.metadata.name}")
        if ns:
            self._print(f"Namespace:\t{ns}")
        self._print(f"Kind:\t{k}")
        labels = getattr(obj.metadata, "labels", {})
        if labels:
            self._print(f"Labels:\t{labels}")
        status = getattr(obj, "status", None)
        for cond in getattr(status, "conditions", []):
            self._print(f"Condition:\t{cond.type}={cond.status}"
                        f" ({cond.reason}): {cond.message}")
        spec = _to_dict(obj.spec)
        self._print("Spec:")
        self._print(json.dumps(spec, indent=2, default=str, sort_keys=True))
        return spec

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        k, ns = self._resolve(kind, namespace)
        self.store.delete(k, ns, name)
        self._print(f"{k.lower()} {name!r} deleted")

    def patch(self, kind: str, name: str, patch_json: str,
              namespace: str = "default") -> None:
        k, ns = self._resolve(kind, namespace)
        obj = self.store.get(k, ns, name)
        _merge_patch(obj, json.loads(patch_json))
        try:
            self.store.update(obj)
        except (AttributeError, TypeError) as exc:
            # A merge patch replaced a typed field with plain JSON and the
            # validation webhook tripped over it — a user error, not a bug.
            from kueue_tpu.sim import Invalid
            raise Invalid(f"patch produced an invalid object: {exc}") from exc
        self._print(f"{k.lower()} {name!r} patched")

    def edit(self, kind: str, name: str, namespace: str = "default",
             stream=None) -> None:
        """Non-interactive edit: a JSON merge patch read from stdin (the
        reference shells out to `kubectl edit`/$EDITOR; there is no tty
        in this runtime)."""
        stream = stream if stream is not None else sys.stdin
        self.patch(kind, name, stream.read(), namespace=namespace)

    def version(self) -> str:
        v = f"kueuectl (kueue_tpu) {versionpkg.VERSION}"
        self._print(v)
        return v


def main(argv: Optional[list] = None, manager=None) -> int:
    parser = argparse.ArgumentParser(prog="kueuectl")
    sub = parser.add_subparsers(dest="command", required=True)
    for verb in ("create", "list", "stop", "resume"):
        p = sub.add_parser(verb)
        kinds = ["clusterqueue", "localqueue", "workload", "resourceflavor"]
        if verb == "list":
            kinds.append("pods")
        p.add_argument("kind", choices=kinds)
        p.add_argument("name", nargs="?")
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument("--cohort", default="")
        p.add_argument("--clusterqueue", default="")
        if verb == "list":
            p.add_argument("--for", dest="for_ref", default="",
                           help="list pods: owning object as kind/name")
    # pass-through verbs (reference: passthrough.go:33-39)
    for verb in ("get", "describe", "delete", "patch", "edit"):
        p = sub.add_parser(verb)
        p.add_argument("kind", choices=sorted(KIND_ALIASES))
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default="default")
        if verb == "patch":
            p.add_argument("-p", "--patch", required=True,
                           help="JSON merge patch")
    sub.add_parser("version")
    args = parser.parse_args(argv)

    if manager is None:
        from kueue_tpu.manager import KueueManager
        manager = KueueManager()
    ctl = Kueuectl(manager)

    if args.command in ("create", "stop", "resume") and not args.name:
        print(f"error: {args.command} {args.kind} requires a name",
              file=sys.stderr)
        return 1
    if (args.command == "create" and args.kind == "localqueue"
            and not args.clusterqueue):
        print("error: create localqueue requires --clusterqueue",
              file=sys.stderr)
        return 1

    from kueue_tpu.sim import AlreadyExists, Invalid, NotFound
    try:
        return _dispatch(ctl, args)
    except (Invalid, AlreadyExists, NotFound, ValueError,
            json.JSONDecodeError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1


def _dispatch(ctl: Kueuectl, args) -> int:
    if args.command == "version":
        ctl.version()
        return 0
    if args.command in ("get", "describe", "delete", "patch", "edit"):
        if args.command == "get":
            ctl.get(args.kind, args.name, namespace=args.namespace)
        elif args.command == "describe":
            ctl.describe(args.kind, args.name, namespace=args.namespace)
        elif args.command == "delete":
            ctl.delete(args.kind, args.name, namespace=args.namespace)
        elif args.command == "patch":
            ctl.patch(args.kind, args.name, args.patch,
                      namespace=args.namespace)
        else:
            ctl.edit(args.kind, args.name, namespace=args.namespace)
        return 0
    kind = args.kind
    if args.command == "list":
        if kind == "pods":
            ctl.list_pods_for(args.for_ref, namespace=args.namespace)
        elif kind == "clusterqueue":
            ctl.list_cluster_queues()
        elif kind == "localqueue":
            ctl.list_local_queues(namespace=args.namespace)
        elif kind == "workload":
            ctl.list_workloads(namespace=args.namespace)
        else:
            ctl.list_resource_flavors()
        return 0
    if args.command == "create":
        if kind == "clusterqueue":
            ctl.create_cluster_queue(args.name, cohort=args.cohort)
        elif kind == "localqueue":
            ctl.create_local_queue(args.name, args.namespace, args.clusterqueue)
        elif kind == "resourceflavor":
            ctl.create_resource_flavor(args.name)
        return 0
    if args.command == "stop":
        if kind == "workload":
            ctl.stop_workload(args.namespace, args.name)
        elif kind == "clusterqueue":
            ctl.stop_cluster_queue(args.name)
        elif kind == "localqueue":
            ctl.stop_local_queue(args.namespace, args.name)
        return 0
    if args.command == "resume":
        if kind == "workload":
            ctl.resume_workload(args.namespace, args.name)
        elif kind == "clusterqueue":
            ctl.resume_cluster_queue(args.name)
        elif kind == "localqueue":
            ctl.resume_local_queue(args.namespace, args.name)
        return 0
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
