"""kueuectl — the operator CLI.

Equivalent of the reference's cmd/kueuectl (app/cmd.go:79-90):
create {clusterqueue,localqueue,resourceflavor}, list {clusterqueue,
localqueue,workload,resourceflavor}, stop/resume {workload,clusterqueue,
localqueue} (via spec.active / stopPolicy), version. The command core is
the `Kueuectl` class over a manager's store (tests drive it directly);
`main()` wraps it in argparse against a demo manager.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from kueue_tpu import version as versionpkg
from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import ObjectMeta
from kueue_tpu.core import workload as wlpkg


class Kueuectl:
    def __init__(self, manager, out=None):
        self.manager = manager
        self.store = manager.store
        self.out = out or sys.stdout

    def _print(self, *cols):
        print("\t".join(str(c) for c in cols), file=self.out)

    # -- create (reference: app/create/) --------------------------------

    def create_cluster_queue(self, name: str, cohort: str = "",
                             queueing_strategy: str = api.BEST_EFFORT_FIFO,
                             nominal_quota: Optional[dict] = None,
                             flavor: str = "default") -> api.ClusterQueue:
        cq = api.ClusterQueue(metadata=ObjectMeta(name=name))
        cq.spec.cohort = cohort
        cq.spec.queueing_strategy = queueing_strategy
        cq.spec.namespace_selector = api.LabelSelector()
        if nominal_quota:
            cq.spec.resource_groups = [api.ResourceGroup(
                covered_resources=list(nominal_quota),
                flavors=[api.FlavorQuotas(name=flavor, resources=[
                    api.ResourceQuota(name=res, nominal_quota=qty)
                    for res, qty in nominal_quota.items()])])]
        return self.store.create(cq)

    def create_local_queue(self, name: str, namespace: str,
                           cluster_queue: str) -> api.LocalQueue:
        lq = api.LocalQueue(metadata=ObjectMeta(name=name, namespace=namespace))
        lq.spec.cluster_queue = cluster_queue
        return self.store.create(lq)

    def create_resource_flavor(self, name: str,
                               node_labels: Optional[dict] = None) -> api.ResourceFlavor:
        rf = api.ResourceFlavor(metadata=ObjectMeta(name=name))
        if node_labels:
            rf.spec.node_labels = dict(node_labels)
        return self.store.create(rf)

    # -- list (reference: app/list/) ------------------------------------

    def list_cluster_queues(self) -> list:
        out = self.store.list("ClusterQueue")
        self._print("NAME", "COHORT", "STRATEGY", "PENDING", "ADMITTED", "ACTIVE")
        for cq in sorted(out, key=lambda c: c.metadata.name):
            from kueue_tpu.api.meta import is_condition_true
            self._print(cq.metadata.name, cq.spec.cohort,
                        cq.spec.queueing_strategy,
                        cq.status.pending_workloads,
                        cq.status.admitted_workloads,
                        is_condition_true(cq.status.conditions,
                                          api.CLUSTER_QUEUE_ACTIVE))
        return out

    def list_local_queues(self, namespace: Optional[str] = None) -> list:
        out = self.store.list("LocalQueue", namespace=namespace)
        self._print("NAMESPACE", "NAME", "CLUSTERQUEUE", "PENDING", "ADMITTED")
        for lq in sorted(out, key=lambda q: (q.metadata.namespace, q.metadata.name)):
            self._print(lq.metadata.namespace, lq.metadata.name,
                        lq.spec.cluster_queue, lq.status.pending_workloads,
                        lq.status.admitted_workloads)
        return out

    def list_workloads(self, namespace: Optional[str] = None) -> list:
        out = self.store.list("Workload", namespace=namespace)
        self._print("NAMESPACE", "NAME", "QUEUE", "STATUS", "PRIORITY")
        for wl in sorted(out, key=lambda w: (w.metadata.namespace, w.metadata.name)):
            self._print(wl.metadata.namespace, wl.metadata.name,
                        wl.spec.queue_name, wlpkg.status(wl),
                        wl.spec.priority if wl.spec.priority is not None else 0)
        return out

    def list_resource_flavors(self) -> list:
        out = self.store.list("ResourceFlavor")
        self._print("NAME", "NODELABELS")
        for rf in sorted(out, key=lambda r: r.metadata.name):
            self._print(rf.metadata.name, rf.spec.node_labels)
        return out

    # -- stop / resume (reference: app/stop, app/resume) ----------------

    def stop_workload(self, namespace: str, name: str) -> None:
        wl = self.store.get("Workload", namespace, name)
        wl.spec.active = False
        self.store.update(wl)

    def resume_workload(self, namespace: str, name: str) -> None:
        wl = self.store.get("Workload", namespace, name)
        wl.spec.active = True
        self.store.update(wl)

    def stop_cluster_queue(self, name: str, drain: bool = True) -> None:
        cq = self.store.get("ClusterQueue", "", name)
        cq.spec.stop_policy = api.HOLD_AND_DRAIN if drain else api.HOLD
        self.store.update(cq)

    def resume_cluster_queue(self, name: str) -> None:
        cq = self.store.get("ClusterQueue", "", name)
        cq.spec.stop_policy = api.STOP_POLICY_NONE
        self.store.update(cq)

    def stop_local_queue(self, namespace: str, name: str,
                         drain: bool = True) -> None:
        lq = self.store.get("LocalQueue", namespace, name)
        lq.spec.stop_policy = api.HOLD_AND_DRAIN if drain else api.HOLD
        self.store.update(lq)

    def resume_local_queue(self, namespace: str, name: str) -> None:
        lq = self.store.get("LocalQueue", namespace, name)
        lq.spec.stop_policy = api.STOP_POLICY_NONE
        self.store.update(lq)

    def version(self) -> str:
        v = f"kueuectl (kueue_tpu) {versionpkg.VERSION}"
        self._print(v)
        return v


def main(argv: Optional[list] = None, manager=None) -> int:
    parser = argparse.ArgumentParser(prog="kueuectl")
    sub = parser.add_subparsers(dest="command", required=True)
    for verb in ("create", "list", "stop", "resume"):
        p = sub.add_parser(verb)
        p.add_argument("kind", choices=["clusterqueue", "localqueue",
                                        "workload", "resourceflavor"])
        p.add_argument("name", nargs="?")
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument("--cohort", default="")
        p.add_argument("--clusterqueue", default="")
    sub.add_parser("version")
    args = parser.parse_args(argv)

    if manager is None:
        from kueue_tpu.manager import KueueManager
        manager = KueueManager()
    ctl = Kueuectl(manager)

    if args.command in ("create", "stop", "resume") and not args.name:
        print(f"error: {args.command} {args.kind} requires a name",
              file=sys.stderr)
        return 1
    if (args.command == "create" and args.kind == "localqueue"
            and not args.clusterqueue):
        print("error: create localqueue requires --clusterqueue",
              file=sys.stderr)
        return 1

    from kueue_tpu.sim import AlreadyExists, Invalid, NotFound
    try:
        return _dispatch(ctl, args)
    except (Invalid, AlreadyExists, NotFound) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1


def _dispatch(ctl: Kueuectl, args) -> int:
    if args.command == "version":
        ctl.version()
        return 0
    kind = args.kind
    if args.command == "list":
        if kind == "clusterqueue":
            ctl.list_cluster_queues()
        elif kind == "localqueue":
            ctl.list_local_queues(namespace=args.namespace)
        elif kind == "workload":
            ctl.list_workloads(namespace=args.namespace)
        else:
            ctl.list_resource_flavors()
        return 0
    if args.command == "create":
        if kind == "clusterqueue":
            ctl.create_cluster_queue(args.name, cohort=args.cohort)
        elif kind == "localqueue":
            ctl.create_local_queue(args.name, args.namespace, args.clusterqueue)
        elif kind == "resourceflavor":
            ctl.create_resource_flavor(args.name)
        return 0
    if args.command == "stop":
        if kind == "workload":
            ctl.stop_workload(args.namespace, args.name)
        elif kind == "clusterqueue":
            ctl.stop_cluster_queue(args.name)
        elif kind == "localqueue":
            ctl.stop_local_queue(args.namespace, args.name)
        return 0
    if args.command == "resume":
        if kind == "workload":
            ctl.resume_workload(args.namespace, args.name)
        elif kind == "clusterqueue":
            ctl.resume_cluster_queue(args.name)
        elif kind == "localqueue":
            ctl.resume_local_queue(args.namespace, args.name)
        return 0
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
