"""State dumper: the SIGUSR2 debugging hook.

Equivalent of the reference's pkg/debugger/debugger.go:34-56: on demand
(or on SIGUSR2), log the full cache usage state and every queue's
pending dump.
"""

from __future__ import annotations

import signal
import sys


class Dumper:
    def __init__(self, cache, queues, out=None):
        self.cache = cache
        self.queues = queues
        self.out = out or sys.stderr

    def dump(self) -> str:
        lines = ["=== kueue_tpu state dump ==="]
        lines.append("-- cache (admitted/reserving usage) --")
        for name, cqc in sorted(self.cache.hm.cluster_queues.items()):
            usage = {f"{fr[0]}/{fr[1]}": q
                     for fr, q in sorted(cqc.resource_node.usage.items())}
            lines.append(f"cq {name}: cohort={cqc.cohort.name if cqc.cohort else ''} "
                         f"reserving={cqc.reserving_workloads_count()} "
                         f"admitted={cqc.admitted_workloads_count} usage={usage}")
            for key in sorted(cqc.workloads):
                lines.append(f"  workload {key}")
        lines.append("-- queues (pending heads) --")
        for name, cqh in sorted(self.queues.cluster_queues.items()):
            lines.append(f"cq {name}: strategy={cqh.queueing_strategy} "
                         f"active={cqh.pending_active()} "
                         f"inadmissible={cqh.pending_inadmissible()}")
            for info in cqh.snapshot_sorted():
                lines.append(f"  pending {info.key}")
        lines.append("-- assumed workloads --")
        for key, cq in sorted(self.cache.assumed_workloads.items()):
            lines.append(f"  {key} -> {cq}")
        return "\n".join(lines)

    def write(self) -> None:
        print(self.dump(), file=self.out, flush=True)

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        """reference: debugger.go ListenForSignal."""
        signal.signal(signum, lambda s, f: self.write())
