"""State dumper: the SIGUSR2 debugging hook.

Equivalent of the reference's pkg/debugger/debugger.go:34-56: on demand
(or on SIGUSR2), log the full cache usage state and every queue's
pending dump. With a scheduler attached, the dump also covers the
solver plane's operator surface (kueue_tpu/obs): circuit-breaker state,
adaptive-router regime samples, encode-arena slot stats, and the last
flight-recorder cycle trace — the same producers the VisibilityServer's
``/debug/*`` endpoints serve.
"""

from __future__ import annotations

import signal
import sys


class Dumper:
    def __init__(self, cache, queues, out=None, scheduler=None):
        self.cache = cache
        self.queues = queues
        self.out = out or sys.stderr
        self.scheduler = scheduler

    def dump(self) -> str:
        lines = ["=== kueue_tpu state dump ==="]
        lines.append("-- cache (admitted/reserving usage) --")
        for name, cqc in sorted(self.cache.hm.cluster_queues.items()):
            usage = {f"{fr[0]}/{fr[1]}": q
                     for fr, q in sorted(cqc.resource_node.usage.items())}
            lines.append(f"cq {name}: cohort={cqc.cohort.name if cqc.cohort else ''} "
                         f"reserving={cqc.reserving_workloads_count()} "
                         f"admitted={cqc.admitted_workloads_count} usage={usage}")
            for key in sorted(cqc.workloads):
                lines.append(f"  workload {key}")
        lines.append("-- queues (pending heads) --")
        for name, cqh in sorted(self.queues.cluster_queues.items()):
            lines.append(f"cq {name}: strategy={cqh.queueing_strategy} "
                         f"active={cqh.pending_active()} "
                         f"inadmissible={cqh.pending_inadmissible()}")
            for info in cqh.snapshot_sorted():
                lines.append(f"  pending {info.key}")
        lines.append("-- assumed workloads --")
        for key, cq in sorted(self.cache.assumed_workloads.items()):
            lines.append(f"  {key} -> {cq}")
        if self.scheduler is not None:
            lines.extend(self._dump_solver_plane())
        return "\n".join(lines)

    def _dump_solver_plane(self) -> list:
        from kueue_tpu.obs import (arena_status, breaker_status,
                                   degrade_status, pipeline_status,
                                   recovery_status, router_status,
                                   shards_status, warmup_status)
        sched = self.scheduler
        lines = []
        sh = shards_status(sched)
        if sh.get("attached"):
            lines.append("-- shards --")
            plan = sh["plan"]
            lines.append(f"n_shards={sh['n_shards']} "
                         f"plan={plan['fingerprint']} "
                         f"units={plan['units']} "
                         f"imbalance={plan['imbalance']} "
                         f"loads={plan['loads']} "
                         f"rebalances={sh['rebalances']}")
            for s in sh["shards"]:
                lines.append(f"  {s['shard']}: state={s['state']} "
                             f"epoch={s['epoch']} "
                             f"cqs={len(s['cluster_queues'])} "
                             f"backlog={s['pending_backlog']} "
                             f"cycles={s['cycles']} "
                             f"admitted={s['admitted_total']} "
                             f"promotions={s['promotions']}")
        rc = recovery_status(sched)
        if rc["restored"]:
            lines.append("-- recovery --")
            lines.append(f"restored=True duration_s={rc['duration_s']} "
                         f"checkpoint={rc['checkpoint_loaded']} "
                         f"wal_records={rc['wal_records_replayed']} "
                         f"torn={rc['torn_records']} "
                         f"admitted={rc['admitted_restored']} "
                         f"pending={rc['pending_restored']}")
        lines.append("-- breaker --")
        st = breaker_status(sched)
        lines.append(f"state={st['state']} route={st['route']} "
                     f"consecutive={st['consecutive_faults']}/"
                     f"{st['threshold']} trips={st['trips']} "
                     f"recoveries={st['recoveries']} "
                     f"next_probe_in_s={st['next_probe_in_s']} "
                     f"backoff_s={st['backoff_s']}")
        lines.append("-- degrade --")
        dg = degrade_status(sched)
        lines.append(f"state={dg['state']} enabled={dg['enabled']} "
                     f"budget_ms={dg['budget_ms']} ewma_ms={dg['ewma_ms']} "
                     f"cycles_shed={dg['cycles_shed']} "
                     f"escalations={dg['escalations']} "
                     f"recoveries={dg['recoveries']} "
                     f"heads_requeued={dg['shed_heads_requeued_total']} "
                     f"preempts_deferred={dg['preempt_plans_deferred_total']}")
        lines.append("-- pipeline --")
        pl = pipeline_status(sched)
        lines.append(f"enabled={pl['enabled']} inflight={pl['inflight']} "
                     f"hit_rate={pl['pipelined_hit_rate']} "
                     f"hits={pl['speculation_hits']} "
                     f"aborts={pl['speculation_aborts']} "
                     f"abort_reasons={pl['abort_reasons']}")
        wu = warmup_status(sched)
        if wu.get("attached"):
            lines.append("-- warmup --")
            lines.append(f"state={wu['state']} "
                         f"programs_warmed={wu['programs_warmed']} "
                         f"faults={wu['warmup_faults']} "
                         f"cpu_warmup_cycles={wu['cpu_warmup_cycles']} "
                         f"unwarm_routed={wu['unwarm_routed_cycles']} "
                         f"cache_subdir={wu['cache_subdir'] or '(none)'}")
            for b in wu["buckets"]:
                lines.append(f"  bucket width={b['width']}: "
                             f"state={b['state']} source={b['source']} "
                             f"programs={b['programs']} "
                             f"compile_ms={b['compile_ms']} "
                             f"attempts={b['attempts']}"
                             + (f" error={b['error']}" if b["error"]
                                else ""))
        lines.append("-- router --")
        rt = router_status(sched)
        lines.append(f"routing={rt['routing']} "
                     f"last_regime={rt['last_regime']} "
                     f"cycle_counts={rt['cycle_counts']}")
        for key, info in sorted(rt["regimes"].items()):
            lines.append(f"  {key}: median_rate_per_s="
                         f"{info['median_rate_per_s']} median_cycle_s="
                         f"{info['median_cycle_s']} "
                         f"samples={len(info['samples'])}")
        if sched.solver is not None:
            lines.append("-- arena --")
            a = arena_status(sched.solver)
            lines.append(" ".join(f"{k}={v}" for k, v in a.items()))
        last = sched.recorder.last()
        lines.append("-- last cycle trace --")
        if last is None:
            lines.append("  (no cycles recorded)")
        else:
            d = last.to_dict()
            lines.append(f"cycle {d['cycle']}: route={d['route']} "
                         f"regime={d['regime']} heads={d['heads']} "
                         f"admitted={d['admitted']} "
                         f"evictions={d['evictions']} "
                         f"faults={d['faults']} breaker={d['breaker']} "
                         f"duration_ms={d['duration_ms']}")
            for s in d["spans"]:
                lines.append(f"  span {s['name']}: start_ms="
                             f"{s['start_ms']} dur_ms={s['dur_ms']}")
            for a in d["annotations"]:
                lines.append(f"  note {a['kind']}: {a['message']}")
        return lines

    def write(self) -> None:
        print(self.dump(), file=self.out, flush=True)

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        """reference: debugger.go ListenForSignal."""
        signal.signal(signum, lambda s, f: self.write())
