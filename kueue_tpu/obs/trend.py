"""Aging watch: EWMA-slope trend monitors over monotone resources.

ROADMAP item 5 (long-horizon soak) gates on monotone-resource
invariants — live_handouts returning to zero between cycles, WAL size
bounded by compaction, flat arena occupancy and RSS trends, bounded
requeue amplification, zero mid-traffic compiles after warmup. Today
those exist only as ad-hoc scenario asserts; this module makes them a
live, queryable surface: each monitor samples one resource per cycle
seal, keeps an EWMA of the per-sample slope, and renders a verdict —
so the future soak harness gets its gate surface for free and an
operator can ask ``/debug/aging`` whether a week-old process is
leaking *now*.

Verdict semantics per monitor:

- ``warming`` — fewer than ``warmup`` samples; no judgement yet (a
  fresh process legitimately grows while queues fill).
- ``ok`` — slope EWMA at or below the threshold.
- ``growing`` — slope EWMA above the threshold, but not yet sustained
  for ``window`` consecutive samples (could be a storm filling up).
- ``leaking`` — slope EWMA above threshold for >= ``window``
  consecutive samples: sustained monotone growth, the aging signature.
- ``over-bound`` — the level itself exceeded the monitor's hard bound
  (e.g. WAL records past 2x the compaction interval = a compaction
  stall), regardless of slope.

The slope EWMA (not the raw delta) is what makes the detector robust
to sawtooth resources: a healthy WAL grows then drops at every
checkpoint, so its slope EWMA hovers near zero, while a stalled
compaction holds it at the append rate. Cost: one callable + a few
float ops per monitor per cycle — covered by the ``journey_overhead``
bench row's <=1% budget alongside the ledger hooks.
"""

from __future__ import annotations

from typing import Callable, Optional

VERDICT_WARMING = "warming"
VERDICT_OK = "ok"
VERDICT_GROWING = "growing"
VERDICT_LEAKING = "leaking"
VERDICT_OVER_BOUND = "over-bound"

# Verdicts that constitute an aging violation (probe/soak gate).
BAD_VERDICTS = (VERDICT_LEAKING, VERDICT_OVER_BOUND)

DEFAULT_ALPHA = 0.2
DEFAULT_WINDOW = 12
DEFAULT_WARMUP = 8


class TrendMonitor:
    """One resource's trend detector. ``slope_threshold`` is the
    per-sample growth the EWMA may sustain before the monitor calls it
    a leak (None = slope unchecked, bound-only monitor); ``bound`` is
    a hard level ceiling (None = unchecked)."""

    def __init__(self, name: str, slope_threshold: Optional[float],
                 bound: Optional[float] = None,
                 alpha: float = DEFAULT_ALPHA,
                 window: int = DEFAULT_WINDOW,
                 warmup: int = DEFAULT_WARMUP):
        if slope_threshold is None and bound is None:
            raise ValueError(f"monitor {name!r}: need a slope threshold "
                             "or a bound (or both)")
        if not 0 < alpha <= 1:
            raise ValueError("alpha must be in (0, 1]")
        if window < 1 or warmup < 0:
            raise ValueError("window must be >= 1 and warmup >= 0")
        self.name = name
        self.slope_threshold = slope_threshold
        self.bound = bound
        self.alpha = alpha
        self.window = window
        self.warmup = warmup
        self.samples = 0
        self.value: Optional[float] = None
        self.slope_ewma = 0.0
        self.sustained = 0       # consecutive samples above threshold
        self.over_bound = 0      # consecutive samples above the bound
        self.sample_errors = 0   # source raised (guarded by the watch)

    def sample(self, value: float) -> None:
        prev = self.value
        self.value = float(value)
        self.samples += 1
        if prev is not None:
            slope = self.value - prev
            self.slope_ewma += self.alpha * (slope - self.slope_ewma)
        if self.slope_threshold is not None \
                and self.samples > self.warmup \
                and self.slope_ewma > self.slope_threshold:
            self.sustained += 1
        else:
            self.sustained = 0
        if self.bound is not None and self.value > self.bound:
            self.over_bound += 1
        else:
            self.over_bound = 0

    def verdict(self) -> str:
        if self.bound is not None and self.over_bound >= 1:
            return VERDICT_OVER_BOUND
        if self.samples <= self.warmup:
            return VERDICT_WARMING
        if self.slope_threshold is None \
                or self.slope_ewma <= self.slope_threshold:
            return VERDICT_OK
        return (VERDICT_LEAKING if self.sustained >= self.window
                else VERDICT_GROWING)

    def status(self) -> dict:
        return {
            "value": self.value,
            "slope_ewma": round(self.slope_ewma, 6),
            "slope_threshold": self.slope_threshold,
            "bound": self.bound,
            "window": self.window,
            "samples": self.samples,
            "sustained": self.sustained,
            "sample_errors": self.sample_errors,
            "verdict": self.verdict(),
        }


class AgingWatch:
    """A set of trend monitors sampled once per cycle seal. Sources are
    zero-argument callables registered by the manager (cache handout
    counts, WAL stats, arena occupancy, ledger ratios, RSS); a source
    that raises is counted and skipped, never fatal — aging detection
    must not become an aging failure mode."""

    def __init__(self):
        self.monitors: dict = {}        # name -> TrendMonitor
        self._sources: dict = {}        # name -> callable
        self.samples_taken = 0

    def add(self, name: str, source: Callable[[], float],
            slope_threshold: Optional[float],
            bound: Optional[float] = None,
            alpha: float = DEFAULT_ALPHA,
            window: int = DEFAULT_WINDOW,
            warmup: int = DEFAULT_WARMUP) -> TrendMonitor:
        mon = TrendMonitor(name, slope_threshold, bound=bound, alpha=alpha,
                           window=window, warmup=warmup)
        self.monitors[name] = mon
        self._sources[name] = source
        return mon

    def sample(self) -> None:
        """One sampling pass (the scheduler calls this at every cycle
        seal). Hot-path contract: len(monitors) callable invocations
        plus a few float ops each."""
        self.samples_taken += 1
        for name, mon in self.monitors.items():
            try:
                mon.sample(self._sources[name]())
            except Exception:  # noqa: BLE001 — a dead source must not kill cycles
                mon.sample_errors += 1

    def verdicts(self) -> dict:
        return {name: mon.verdict() for name, mon in self.monitors.items()}

    @property
    def failing(self) -> list:
        """Monitors whose verdict is an aging violation, sorted."""
        return sorted(name for name, mon in self.monitors.items()
                      if mon.verdict() in BAD_VERDICTS)

    def gate(self) -> dict:
        """The machine-readable aging verdict every gate consumes —
        soak harness, scenario results and /debug/aging share THIS
        contract instead of re-deriving pass/fail from status():
        ``ok`` is True iff no monitor's verdict is in BAD_VERDICTS,
        ``failing`` lists the violators, ``verdicts`` maps every
        monitor to its current verdict (warming/ok/growing count as
        green — a fresh process is not a leaking one)."""
        failing = self.failing
        return {"ok": not failing, "failing": failing,
                "verdicts": self.verdicts()}

    def status(self) -> dict:
        """The single producer /debug/aging, the probe and tests
        share. Carries the gate() dict verbatim so a status consumer
        and a gate consumer can never disagree."""
        return {
            "samples_taken": self.samples_taken,
            "failing": self.failing,
            "gate": self.gate(),
            "monitors": {name: mon.status()
                         for name, mon in self.monitors.items()},
        }


def rss_kb() -> float:
    """This process's peak resident set in KB (ru_maxrss; a leak grows
    it continually, a healthy run plateaus after warmup). ru_maxrss is
    kilobytes on Linux but BYTES on macOS — normalize, or the KB-scaled
    slope threshold false-positives by 1024x there."""
    import resource
    import sys
    rss = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    return rss / 1024.0 if sys.platform == "darwin" else rss
