"""Snapshot-backed query plane: the read side of millions of users.

The reference ships a visibility apiserver (pkg/visibility) whose every
request walks the queue manager's LIVE heaps under the manager lock —
at storm read QPS that contends with the very admission cycle the
north-star metric measures. This module makes the read path a
first-class scaled surface (ROADMAP item 4 / ISSUE 12):

- **Sealed views, not live state.** At every admission-cycle seal the
  scheduler publishes an immutable ``SealedView``: the cycle id and
  route, the cache's structural generation token, the cycle's nominate
  order (the admission-sorted entry ranks the scheduler already built —
  the decision-only column, amortized over all readers), and — for sync
  cycles — the cycle's own copy-on-write snapshot handout, whose
  ownership TRANSFERS from the scheduler to the plane instead of being
  released (``cache/SNAPSHOTS.md``: handout consumers now include
  readers; the handout stays counted in ``live_handouts`` until the
  plane rotates it out). Readers borrow the current view under a
  refcount and serve everything from it: one snapshot, one token, no
  live-heap walks per request.

- **Lazy per-CQ position tables.** A view's per-CQ (and per-LQ)
  pending-position table is materialized at most ONCE per view — the
  first reader of a CQ in a generation pays one ordered copy of that
  CQ's pending set (taken from the queue manager per CQ, outside the
  manager-wide lock); every subsequent reader of that CQ at storm QPS
  hits the immutable cached table. The old per-request cost (ordered
  walk + manager lock) becomes a per-cycle-per-CQ cost. Freshness
  contract, stated precisely: a table FREEZES the CQ's pending order
  at its first read within the view — at or after the seal, never
  before — and stays immutable for the view's lifetime, so all
  readers of one view agree. The stamped generation token is a
  staleness FLOOR (the rows are never older than the seal), not a
  row-freshness ceiling: readers of the CURRENT view see tables at
  most one seal ahead of the stamp, while a borrow deliberately held
  across later seals may first-materialize a table from
  correspondingly newer state (holding a retired view trades bounded
  coordinates for a stable object — the stamp still names the seal
  the nominate-rank column and snapshot belong to).

- **Explicit, observable staleness.** Every response is stamped with
  the generation token the view sealed under, the cycle id, and the
  view's age; ``token_lag()`` prices the view against the live cache
  (``Cache.generation_lag``). A plane that has never sealed a cycle is
  WARMING — the HTTP server answers 503 + Retry-After instead of
  blocking or lying.

Thread contract: ``publish`` is called by the scheduler thread at cycle
seal; ``acquire``/``release`` run on any number of reader threads. The
plane lock guards only the view swap, refcounts, and the once-per-view
table fills — never a queue walk or a snapshot build.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass
from typing import Optional

from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg


@dataclass(frozen=True)
class PendingPosition:
    """One pending workload's position row (the visibility payload plus
    the query-plane columns)."""
    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int
    # This workload's rank in the sealed cycle's nominate (admission)
    # order, when it was among the cycle's heads; None otherwise. The
    # "decision-only column": readers see where the scheduler actually
    # ranked the head, not just heap order.
    nominate_rank: Optional[int] = None


class _CQTable:
    """Immutable ordered pending table for one ClusterQueue, built once
    per SealedView. ``rows`` is CQ queue order; ``by_lq`` projects LQ
    order (row indexes); ``by_key`` resolves point queries."""

    __slots__ = ("rows", "by_lq", "by_key")

    def __init__(self, rows: list):
        self.rows = tuple(rows)
        self.by_lq: dict = {}
        self.by_key: dict = {}
        for i, row in enumerate(self.rows):
            lqk = f"{row.namespace}/{row.local_queue_name}"
            self.by_lq.setdefault(lqk, []).append(i)
            self.by_key[f"{row.namespace}/{row.name}"] = i


class _SnapRef:
    """A snapshot handout shared by consecutive SealedViews (pipelined
    cycles publish without a fresh full snapshot): released back to the
    cache exactly once, when the last referencing view retires."""

    __slots__ = ("snapshot", "refs")

    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.refs = 1


class SealedView:
    """One cycle's immutable read view. Built by ``QueryPlane.publish``;
    reader threads must only touch it between a paired
    ``acquire()``/``release()``."""

    __slots__ = ("cycle_id", "route", "generation", "journal_seq",
                 "sealed_wall", "sealed_mono", "_order", "_head_ranks",
                 "_order_chain", "_chain_len", "_since_keys", "snapref",
                 "_tables", "_snap_index", "_lq_index", "borrows",
                 "retired")

    def __init__(self, cycle_id: int, route: str, generation: tuple,
                 journal_seq: int, order: tuple,
                 snapref: Optional[_SnapRef]):
        self.cycle_id = cycle_id
        self.route = route
        self.generation = generation
        self.journal_seq = journal_seq
        self.sealed_wall = _time.time()
        self.sealed_mono = _time.perf_counter()
        self._order = order
        self._head_ranks: Optional[dict] = None
        self.snapref = snapref
        self._tables: dict = {}   # cq name -> _CQTable (plane-lock filled)
        self._snap_index: Optional[dict] = None  # key -> cq name (lazy)
        self._lq_index: Optional[dict] = None    # key -> cq name (lazy)
        # Nominate orders of every cycle sealed since this view's
        # snapshot was taken (append-only list shared with the plane;
        # _chain_len freezes this view's prefix). A pipelined stretch
        # reuses one snapshot for many seals — a key nominated in ANY
        # of those cycles is known to the view even though the stale
        # snapshot cannot place it (the "transitioning" witness).
        self._order_chain: list = []
        self._chain_len = 0
        self._since_keys: Optional[set] = None
        self.borrows = 0
        self.retired = False

    @property
    def head_ranks(self) -> dict:
        """key -> rank in the sealed cycle's nominate order. Built
        LAZILY on the first reader that needs it — the seal itself
        (the admission thread) only stores the order list, so the
        per-cycle publish cost stays O(1) regardless of head count.
        The racy double-build is benign: both results are equal and
        the slot assignment is atomic."""
        hr = self._head_ranks
        if hr is None:
            hr = {key: rank for rank, key in enumerate(self._order)}
            self._head_ranks = hr
        return hr

    @property
    def since_keys(self) -> set:
        """Keys nominated by any cycle sealed since this view's
        snapshot (the view's own cycle included). Built lazily on a
        reader thread (benign-race pattern); the chain entries are
        immutable tuples, so the frozen prefix is stable."""
        sk = self._since_keys
        if sk is None:
            sk = set()
            for order in self._order_chain[:self._chain_len]:
                sk.update(order)
            self._since_keys = sk
        return sk

    @property
    def snap_index(self) -> Optional[dict]:
        """key -> CQ name over the view snapshot's admitted/reserving
        workloads, built lazily on the first point query that needs it
        (same benign-race pattern as head_ranks) — point status lookups
        cost one dict probe instead of an O(CQs) snapshot scan."""
        idx = self._snap_index
        if idx is None:
            snap = self.snapshot
            if snap is None:
                return None
            idx = {key: cq.name
                   for cq in snap.cluster_queues.values()
                   for key in cq.workloads}
            self._snap_index = idx
        return idx

    @property
    def snapshot(self):
        return self.snapref.snapshot if self.snapref is not None else None

    def age_s(self) -> float:
        return max(0.0, _time.perf_counter() - self.sealed_mono)

    def stamp(self) -> dict:
        """The staleness stamp every response carries."""
        return {"generation": list(self.generation),
                "cycle": self.cycle_id,
                "sealed_at": self.sealed_wall,
                "age_s": round(self.age_s(), 6)}


class QueryPlane:
    def __init__(self, cache, queues, metrics=None):
        self._cache = cache
        self._queues = queues
        self._metrics = metrics
        self._lock = threading.Lock()
        self._view: Optional[SealedView] = None
        # Nominate orders sealed since the last full-snapshot publish
        # (reset when a fresh snapshot arrives; old views keep the old
        # list object, so their frozen prefixes stay valid).
        self._order_chain: list = []
        # Engagement counters (status surface / tests).
        self.cycles_published = 0
        self.tables_built = 0
        self.views_borrowed = 0

    # -- producer side (the scheduler thread, at cycle seal) -----------

    def publish(self, cycle_id: int, route: str, order,
                snapshot=None) -> None:
        """Seal a new view atomically. ``order`` is the cycle's nominate
        order (workload keys, admission-sorted — the scheduler already
        built it); ``snapshot`` is the cycle's full copy-on-write
        handout, whose ownership transfers to the plane (the plane
        releases it through ``cache.release_snapshot`` when the view
        rotates out and its last borrow returns). ``snapshot=None``
        (pipelined/light cycles) re-uses the previous view's handout.
        Cost on the admission thread: one token read + the view swap —
        the nominate-rank index and every position table materialize
        lazily on reader threads."""
        generation = self._cache.generation_token()
        order_t = tuple(order or ())
        with self._lock:
            old = self._view
            if snapshot is not None:
                snapref = _SnapRef(snapshot)
                self._order_chain = [order_t]
            else:
                snapref = old.snapref if old is not None else None
                if snapref is not None:
                    snapref.refs += 1
                self._order_chain.append(order_t)
                if len(self._order_chain) > 256:
                    # A very long snapshot-less (pipelined) stretch:
                    # keep only the newest 64 orders, in a FRESH list —
                    # existing views keep the old list object, so their
                    # frozen prefixes stay valid. An O(64)-ref slice,
                    # never a key merge, so the publish stays O(1)-ish
                    # on the admission thread even at seal 257 of a
                    # stretch. Witnesses older than ~256 seals expire
                    # to the pre-feature "unknown" answer until the
                    # next full-snapshot seal — bounded memory beats
                    # unbounded retention for a days-long all-fit
                    # stretch.
                    self._order_chain = list(self._order_chain[-64:])
            view = SealedView(cycle_id, route, generation,
                              getattr(snapshot, "journal_seq",
                                      old.journal_seq if old else 0),
                              order_t, snapref)
            view._order_chain = self._order_chain
            view._chain_len = len(self._order_chain)
            self._view = view
            self.cycles_published += 1
            if old is not None:
                old.retired = True
                self._maybe_release_locked(old)
        if self._metrics is not None:
            self._metrics.set_visibility_snapshot_age(0.0)

    # -- consumer side (reader threads) --------------------------------

    def acquire(self) -> Optional[SealedView]:
        """Borrow the current sealed view (None while warming — no
        cycle has sealed yet). Callers MUST pair with ``release`` on
        every path, including error paths (try/finally)."""
        with self._lock:
            view = self._view
            if view is None:
                return None
            view.borrows += 1
            self.views_borrowed += 1
            return view

    def release(self, view: Optional[SealedView]) -> None:
        if view is None:
            return
        with self._lock:
            view.borrows -= 1
            self._maybe_release_locked(view)

    def _maybe_release_locked(self, view: SealedView) -> None:
        """Release a retired view's snapshot ref once the last borrow
        returned; the underlying handout goes back to the cache (and
        its ``live_handouts`` accounting) when its last view retires."""
        if not view.retired or view.borrows > 0:
            return
        snapref, view.snapref = view.snapref, None
        if snapref is None:
            return
        snapref.refs -= 1
        if snapref.refs == 0 and snapref.snapshot is not None:
            self._cache.release_snapshot(snapref.snapshot)

    def close(self) -> None:
        """Shut the plane: retire the current view and release its
        handout (borrowed views release on their own return). After
        close the plane warms again from the next publish."""
        with self._lock:
            old, self._view = self._view, None
            if old is not None:
                old.retired = True
                self._maybe_release_locked(old)

    # -- the read API (serve from a borrowed view) ----------------------

    def cq_table(self, view: SealedView, cq_name: str) -> _CQTable:
        """The view's position table for one ClusterQueue, materialized
        on first access (one ordered copy of that CQ's pending set) and
        immutable thereafter — the per-cycle-per-CQ amortization."""
        table = view._tables.get(cq_name)
        if table is not None:
            return table
        # Build OUTSIDE the plane lock (the sort may be large); insert
        # under it. Two racing first-readers may both build — the first
        # insert wins and both results are equivalent (same heap copy
        # semantics the live API had per request).
        rows = []
        head_ranks = view.head_ranks
        lq_pos: dict = {}
        for idx, info in enumerate(self._queues.pending_order(cq_name)):
            obj = info.obj
            lq_key = wlpkg.queue_key(obj)
            pos = lq_pos.get(lq_key, 0)
            lq_pos[lq_key] = pos + 1
            rows.append(PendingPosition(
                name=obj.metadata.name,
                namespace=obj.metadata.namespace,
                local_queue_name=obj.spec.queue_name,
                priority=prioritypkg.priority(obj),
                position_in_cluster_queue=idx,
                position_in_local_queue=pos,
                nominate_rank=head_ranks.get(info.key)))
        built = _CQTable(rows)
        with self._lock:
            table = view._tables.setdefault(cq_name, built)
            if table is built:
                self.tables_built += 1
        return table

    def pending_cq(self, view: SealedView, cq_name: str,
                   limit: int, offset: int) -> list:
        rows = self.cq_table(view, cq_name).rows
        return list(rows[offset:offset + limit])

    def pending_lq(self, view: SealedView, namespace: str, lq_name: str,
                   limit: int, offset: int) -> list:
        lq_key = f"{namespace}/{lq_name}"
        lq = self._queues.local_queues.get(lq_key)
        if lq is None:
            return []
        table = self.cq_table(view, lq.cluster_queue)
        idxs = table.by_lq.get(lq_key, [])
        return [table.rows[i] for i in idxs[offset:offset + limit]]

    def workload_status(self, view: SealedView, namespace: str,
                        name: str) -> dict:
        """Point query: one workload's admission status + queue
        positions, answered from the borrowed view. Resolution order
        keeps answers consistent WITH THE VIEW while keeping the common
        case cheap: (1) the live LQ index names the owning CQ (O(LQs)
        dict probes, never a heap walk) and that ONE table is probed;
        (2) a miss falls back to the view's already-materialized tables
        — a workload this view lists as pending answers pending even
        if it admitted (and left the live index) after the seal; (3)
        the view snapshot's lazily-indexed admitted/reserving
        membership (one dict probe, not an O(CQs) scan); (4) a key the
        sealed cycle NOMINATED (the order column — accumulated across
        every seal since the view's snapshot, so a pipelined stretch's
        admissions stay witnessable) or that the live index still
        knows, but that none of the view's data can place, is reported
        ``transitioning`` — it changed state around this view's seal
        and a later full-snapshot view resolves it. Only a key unknown
        everywhere answers ``unknown``."""
        key = f"{namespace}/{name}"
        cq_name = self._lq_index(view).get(key)
        if cq_name is not None:
            table = self.cq_table(view, cq_name)
            i = table.by_key.get(key)
            if i is not None:
                return self._pending_payload(table, i, cq_name)
        for tbl_cq, table in list(view._tables.items()):
            i = table.by_key.get(key)
            if i is not None:
                return self._pending_payload(table, i, tbl_cq)
        idx = view.snap_index
        snap = view.snapshot
        if idx is not None and snap is not None:
            snap_cq = idx.get(key)
            if snap_cq is not None:
                cq = snap.cluster_queues.get(snap_cq)
                info = cq.workloads.get(key) if cq is not None else None
                if info is not None:
                    admitted = wlpkg.is_admitted(info.obj)
                    return {"found": True,
                            "status": "admitted" if admitted
                            else "reserving",
                            "cluster_queue": snap_cq,
                            "position_in_cluster_queue": None,
                            "position_in_local_queue": None,
                            "nominate_rank":
                                view.head_ranks.get(key)}
        rank = view.head_ranks.get(key)
        nominated = rank is not None or key in view.since_keys
        if cq_name is not None or nominated:
            # The live index or the sealed cycle's own nominate order
            # knows this key, but none of the view's data can place it
            # — it changed state around the seal (e.g. nominated and
            # admitted in the sealed cycle: the seal-time snapshot
            # predates the apply, and admission removed it from the
            # pending set). Distinguishable from a nonexistent name;
            # the next sealed view resolves it.
            return {"found": True, "status": "transitioning",
                    "cluster_queue": cq_name,
                    "position_in_cluster_queue": None,
                    "position_in_local_queue": None,
                    "nominate_rank": rank}
        return {"found": False, "status": "unknown",
                "cluster_queue": None}

    def _lq_index(self, view: SealedView) -> dict:
        """key -> owning CQ over the live LQ membership, built at most
        ONCE per view (benign-race pattern, reader threads): point
        queries cost one dict probe instead of an O(LQs) scan per
        request. Same freshness contract as the lazy tables: frozen at
        first use within the view. Unlike head_ranks/snap_index (whose
        inputs are immutable, so a double-build race is benign), this
        builds from LIVE queue state — two racing first builds can
        differ, so the FIRST insert wins under the plane lock (the
        cq_table pattern), keeping every reader of one view on one
        index."""
        idx = view._lq_index
        if idx is None:
            built = {}
            # list() first: the reconcilers mutate the LQ dict
            # concurrently and a live .values() iteration can see a
            # resize mid-walk; the items dicts are read via list(keys).
            for lq in list(self._queues.local_queues.values()):
                cq = lq.cluster_queue
                for key in list(lq.items):
                    built[key] = cq
            with self._lock:
                if view._lq_index is None:
                    view._lq_index = built
                idx = view._lq_index
        return idx

    @staticmethod
    def _pending_payload(table: _CQTable, i: int, cq_name: str) -> dict:
        row = table.rows[i]
        return {"found": True, "status": "pending",
                "cluster_queue": cq_name,
                "position_in_cluster_queue": row.position_in_cluster_queue,
                "position_in_local_queue": row.position_in_local_queue,
                "nominate_rank": row.nominate_rank}

    # -- observability ---------------------------------------------------

    def token_lag(self) -> Optional[int]:
        """Structural generations the current view lags the live cache
        (0 = the view's token IS the live token); None while warming."""
        view = self._view
        if view is None:
            return None
        return self._cache.generation_lag(view.generation)

    @property
    def warming(self) -> bool:
        return self._view is None

    def status(self) -> dict:
        """The /debug/queryplane producer (one producer per subsystem —
        obs/status.py convention)."""
        with self._lock:
            view = self._view
            holds_snapshot = view is not None and view.snapref is not None
            borrows = view.borrows if view is not None else 0
            tables = len(view._tables) if view is not None else 0
        out = {
            "warming": view is None,
            "cycles_published": self.cycles_published,
            "views_borrowed": self.views_borrowed,
            "tables_built": self.tables_built,
            "borrows_inflight": borrows,
            "tables_cached": tables,
            "holds_snapshot_handout": holds_snapshot,
        }
        if view is not None:
            out.update(view.stamp())
            out["route"] = view.route
            out["token_lag"] = self._cache.generation_lag(view.generation)
        return out
