"""Operator debug surface: structured status snapshots of the solver
plane (breaker, adaptive router, encode arena, flight recorder), shared
by the ``VisibilityServer``'s ``/debug/*`` endpoints, the SIGUSR2
``Dumper``, and tests — one producer per subsystem so every consumer
shows the same numbers.
"""

from __future__ import annotations

from typing import Optional


def breaker_status(scheduler) -> dict:
    """Circuit-breaker state for operators (ROADMAP PR-3 follow-up):
    the route the breaker currently pins, consecutive faults, and the
    next-probe backoff — plus the outage/recovery counters."""
    b = scheduler.breaker
    st = b.status()
    st["route"] = "device" if st["state"] == "closed" else "cpu-breaker"
    st["next_probe_in_s"] = (
        0.0 if st["state"] == "closed"
        else max(0.0, round(st["retry_at"] - scheduler.clock.now(), 3)))
    st["solver_faults_total"] = scheduler.solver_faults
    st["cpu_breaker_cycles"] = scheduler.cycle_counts.get("cpu-breaker", 0)
    solver = scheduler.solver
    if solver is not None and hasattr(solver, "_supervisor"):
        # Supervised-dispatch worker (resilience/supervisor.py): how
        # many dispatches were handed off / abandoned.
        st["supervised_dispatch"] = solver._supervisor.status()
        st["supervised_timeouts"] = solver.counters.get(
            "supervised_timeouts", 0)
    return st


def degrade_status(scheduler) -> dict:
    """Degradation-ladder state for operators (/debug/degrade): the
    rung, cycle-time EWMA vs budget, hysteresis/recovery knobs, and the
    shed bookkeeping — the SAME producer the flight-recorder
    annotations and the degraded_state gauge are fed from, so every
    consumer shows the same numbers."""
    st = scheduler.ladder.status()
    st["shed_heads_requeued_total"] = scheduler.shed_heads_requeued
    st["preempt_plans_deferred_total"] = scheduler.preempt_plans_deferred
    st["survival_cycles"] = scheduler.cycle_counts.get("cpu-survival", 0)
    return st


def router_status(scheduler) -> dict:
    """Adaptive-router internals: per (engine, regime) progress/secs
    samples with the median rate the next routing decision will use."""
    regimes = {}
    # Materialize before iterating: this runs on the HTTP/dumper thread
    # while the scheduler thread inserts samples — list() is atomic
    # under the GIL, a Python-level loop over the live dict is not.
    for (engine, regime), samples in list(scheduler._route_stats.items()):
        samples = list(samples)
        rates = sorted(a / max(t, 1e-9) for a, t in samples)
        secs = sorted(t for _a, t in samples)
        regimes[f"{engine}/{regime}"] = {
            "samples": [[a, round(t, 6)] for a, t in samples],
            "median_rate_per_s": (round(rates[len(rates) // 2], 3)
                                  if rates else None),
            "median_cycle_s": (round(secs[len(secs) // 2], 6)
                               if secs else None),
        }
    return {
        "routing": scheduler.solver_routing,
        "last_regime": scheduler._last_regime,
        "explore_counts": dict(scheduler._route_explore),
        "cycle_counts": dict(scheduler.cycle_counts),
        "regimes": regimes,
        # last device preempt-plan solve: candidate pool size, prefix
        # scanned / heap pops, fill-back auction rounds, filled back —
        # per program (minimal / fair); {} until a batched preemption
        # cycle has run (solver/PREEMPT.md)
        "preempt_plan": dict(getattr(scheduler, "last_preempt_plan", {})),
    }


def pipeline_status(scheduler) -> dict:
    """Speculative-pipeline state (/debug/pipeline): coverage (how many
    device cycles were overlapped), speculation hit/abort outcomes by
    validation reason, and whether a cycle is in flight right now —
    fed from the same counters the perf artifacts report, so the
    ``pipelined_hit_rate`` story is checkable live."""
    counts = scheduler.cycle_counts
    pipelined = counts.get("device-pipelined", 0)
    device_sync = counts.get("device", 0)
    total = pipelined + device_sync
    return {
        "enabled": scheduler.pipeline_enabled,
        "inflight": scheduler._inflight is not None,
        "depth": scheduler.pipeline_depth,
        "inflight_depth": len(scheduler._inflight_q),
        "cooldown": scheduler._pipeline_cooldown,
        "pipelined_cycles": pipelined,
        "sync_device_cycles": device_sync,
        "pipelined_hit_rate": (round(pipelined / total, 3)
                               if total else None),
        "speculation_hits": scheduler.speculation_hits,
        "speculation_aborts": scheduler.speculation_aborts,
        "abort_reasons": dict(scheduler.speculation_abort_reasons),
        "allow_pipeline_degraded": scheduler.ladder.allow_pipeline,
    }


def warmup_status(scheduler) -> dict:
    """Compile-governor state (/debug/warmup): the warm-state machine,
    the per-bucket ladder with compile provenance (fresh / cache-hit /
    jit-cache), warmup faults, and how many cycles the route gate
    diverted to cpu-warmup — the same producer tools/warm_probe.py and
    the SIGUSR2 dumper print, so every consumer shows the same numbers.
    See solver/COMPILE.md."""
    gov = getattr(scheduler, "warm_gov", None)
    if gov is None:
        return {"attached": False}
    st = gov.status()
    st["attached"] = True
    st["cpu_warmup_cycles"] = scheduler.cycle_counts.get("cpu-warmup", 0)
    return st


def recovery_status(scheduler) -> dict:
    """Crash-restart recovery report (/debug/recovery): what the last
    restore() rebuilt from the durable store — checkpoint/WAL replay
    provenance (incl. torn-tail fallbacks), restored object counts,
    admitted-vs-pending workload split, and the rebuild duration
    (RESILIENCE.md §6). ``restored`` False = this process never
    recovered (a cold start)."""
    rep = scheduler.last_recovery
    out = {"restored": rep is not None}
    if rep is not None:
        out.update(rep)
    # Hot-standby surface (resilience/replica.py + RESILIENCE.md §7):
    # a StandbyReplica wires its status producer onto the scheduler —
    # on the follower it reports role/lag/cursor, and it carries
    # through promotion (role flips to "leader"); promote() stamps its
    # own report alongside. Absent = no replication regime.
    std = getattr(scheduler, "standby_status", None)
    if std is not None:
        out["standby"] = std()
    prom = getattr(scheduler, "last_promotion", None)
    if prom is not None:
        out["promotion"] = prom
    return out


def queryplane_status(scheduler) -> dict:
    """Snapshot-backed query plane state (/debug/queryplane): the
    sealed view's cycle/generation/age, token lag vs the live cache,
    reader borrow/table counters, and whether the plane still holds a
    snapshot handout — the same producer tools/visibility_probe.py and
    tests read, so every consumer shows the same numbers. ``attached``
    False = reads fall back to the live visibility API."""
    plane = getattr(scheduler, "query_plane", None)
    if plane is None:
        return {"attached": False}
    st = plane.status()
    st["attached"] = True
    return st


def journey_status(scheduler) -> dict:
    """Workload journey ledger state (/debug/journeys, without
    exemplars — the endpoint adds those): retention counters, the
    requeue-amplification ratio and per-class burn rates, from the
    SAME producer tools/journey_probe.py and tests read. ``attached``
    False = no ledger wired (observability.journeyEnable off)."""
    led = getattr(scheduler, "journeys", None)
    if led is None:
        return {"attached": False}
    st = led.status()
    st["attached"] = True
    return st


def aging_status(scheduler) -> dict:
    """Aging-watch verdicts (/debug/aging): per-monitor value, slope
    EWMA and verdict over the monotone resources ROADMAP item 5 gates
    on (live handouts, WAL compaction, arena occupancy, requeue
    amplification, mid-traffic compiles, RSS), plus the machine-
    readable ``gate`` dict ({ok, failing, verdicts}) the soak harness
    and scenario results consume — one green/red contract, whether
    read over HTTP or in-process. ``attached`` False = no watch wired
    (bare scheduler)."""
    watch = getattr(scheduler, "aging", None)
    if watch is None:
        return {"attached": False}
    st = watch.status()
    st["attached"] = True
    return st


def shards_status(scheduler) -> dict:
    """Sharded-control-plane layout (/debug/shards): the live shard
    plan (fingerprint, unit->shard bins, load imbalance), rebalance
    count, and per-shard state/epoch/backlog/admission counters — the
    SAME producer tools/shard_probe.py and the SIGUSR2 dumper read, so
    every consumer shows the same numbers (RESILIENCE.md §9). The
    plane wires its status() onto the scheduler it fronts; ``attached``
    False = this process runs a single unsharded manager."""
    prod = getattr(scheduler, "shards_status", None)
    if prod is None:
        return {"attached": False}
    st = prod()
    st["attached"] = True
    return st


def arena_status(solver) -> dict:
    """Encode-arena slot occupancy and churn counters."""
    arena = getattr(solver, "_arena", None)
    if arena is None:
        return {"bound": False}
    free = len(arena.free)
    return {
        "bound": getattr(solver, "_queues", None) is not None,
        "cap": arena.cap,
        "high_water": arena.size,
        "occupied": arena.size - free,
        "free": free,
        "dirty": len(arena.dirty),
        "encoded_rows": arena.encoded_rows,
        "gathers": arena.gathers,
        "full_uploads": arena.full_uploads,
        "row_uploads": arena.row_uploads,
        "device_twin": arena.dev is not None,
    }


class DebugEndpoints:
    """Route table for the VisibilityServer's operator endpoints.

    ``handle(path, params)`` returns a JSON-able payload, None for an
    unknown ``/debug/*`` path (404), and raises ValueError on bad query
    parameters (400). ``metrics_text()`` backs ``/metrics``.
    """

    def __init__(self, scheduler, metrics=None):
        self.scheduler = scheduler
        self.metrics = metrics

    def metrics_text(self) -> Optional[str]:
        return self.metrics.dump() if self.metrics is not None else None

    def handle(self, path: str, params: dict) -> Optional[dict]:
        payload = self._dispatch(path, params)
        if payload is not None:
            # Every /debug payload reports the structural generation
            # token it rendered under (ISSUE 12 satellite): operators
            # correlating a debug dump against query-plane responses
            # need the same staleness coordinate system on both.
            payload.setdefault(
                "generation",
                list(self.scheduler.cache.generation_token()))
        return payload

    def _dispatch(self, path: str, params: dict) -> Optional[dict]:
        if path == "/debug/cycles":
            return self._cycles(params)
        if path == "/debug/breaker":
            return breaker_status(self.scheduler)
        if path == "/debug/degrade":
            return degrade_status(self.scheduler)
        if path == "/debug/router":
            return router_status(self.scheduler)
        if path == "/debug/pipeline":
            return pipeline_status(self.scheduler)
        if path == "/debug/warmup":
            return warmup_status(self.scheduler)
        if path == "/debug/recovery":
            return recovery_status(self.scheduler)
        if path == "/debug/queryplane":
            return queryplane_status(self.scheduler)
        if path == "/debug/journeys":
            return self._journeys(params)
        if path == "/debug/aging":
            return aging_status(self.scheduler)
        if path == "/debug/shards":
            return shards_status(self.scheduler)
        if path == "/debug/arena":
            if self.scheduler.solver is None:
                return {"bound": False}
            return arena_status(self.scheduler.solver)
        return None

    def _journeys(self, params: dict):
        """/debug/journeys: the ledger's status + slowest-exemplar and
        violation timelines (``?n=K`` limits exemplars), or one full
        journey with ``?wl=<ns/name|name>``. Bad ``n`` -> ValueError
        (400); unknown workload (or no ledger) -> None (404) — the
        same DebugEndpoints contract every other route honors."""
        led = getattr(self.scheduler, "journeys", None)
        wl = params.get("wl")
        if wl is not None:
            if led is None:
                return None
            # journey_dict serializes under the ledger lock: an ACTIVE
            # journey mutates on the scheduler thread mid-flood.
            j = led.journey_dict(wl)
            if j is None:
                return None  # 404: unknown workload
            return {"journey": j}
        payload = journey_status(self.scheduler)
        if led is None:
            return payload
        n = int(params.get("n", led.exemplars))   # ValueError -> 400
        if n < 0:
            raise ValueError("n must be >= 0")
        # n=0 means zero exemplars, not "all" (slicing with [:0]/[-0:]
        # would invert the limit).
        payload["slowest"] = [j.to_dict() for j in led.slowest()[:n]]
        viol = led.violations()[-n:] if n > 0 else []
        payload["violations"] = [j.to_dict() for j in viol]
        return payload

    def _cycles(self, params: dict) -> dict:
        rec = self.scheduler.recorder
        slowest = int(params.get("slowest", 0))   # ValueError -> 400
        n = int(params.get("n", 0))
        if slowest < 0 or n < 0:
            raise ValueError("slowest/n must be >= 0")
        traces = rec.slowest(slowest) if slowest else rec.traces(n)
        return {
            "enabled": rec.enabled,
            "capacity": rec.capacity,
            "cycles_recorded": rec.cycles_recorded,
            "order": "slowest-first" if slowest else "oldest-first",
            "cycles": [t.to_dict() for t in traces],
        }
