"""Workload journey ledger: end-to-end admission tracing + SLIs.

The north-star metric is time-to-admission at 50k pending x 2k CQs x
32 flavors, yet every other observability surface is cycle-centric
(FlightRecorder/CycleTrace), read-side (the query plane) or aggregate
(per-CQ wait-time histograms). When a workload takes 40 cycles to
admit, none of them can say *where those 40 cycles went* — requeue-
backoff loops, shed-rung deferrals, preempt-victim churn, MultiKueue
plan expiry. This module gives every workload a causally-stamped span
timeline:

    queued -> requeued(cycle, reason) ... -> shed
           -> quota-reserved(cycle) -> admitted
           -> evicted(reason) / preempted(by, reason) -> queued ...
           -> mk-planned(cluster) / mk-executed / mk-expired

(deferred preempt planning appears as requeued spans whose message
names the shedding — see the note above quota_reserved)

fed from the hook points that already exist (the queue manager's
workload delta feed, the scheduler's admit/requeue/shed sites, the
workload controller's eviction paths, MultiKueueController's planned-
mirror lifecycle). Every span is stamped with the **cycle id**, the
cache's structural **generation token**, and the cycle's **route**, so
a journey stays causal against /debug/cycles and the query plane's
staleness coordinate system.

Retention is bounded by construction:

- **Active journeys** live in an LRU of ``capacity`` entries (knob
  ``observability.journeyLedgerCapacity``); a 50k-workload storm
  evicts the oldest-touched journeys instead of growing without bound
  (``lru_evictions`` counts them).
- **Completed journeys fold into SLIs**: at seal (full admission) the
  TTA lands in the per-class ``kueue_journey_tta_seconds{class}``
  histogram AND the existing per-CQ ``kueue_admission_wait_time`` /
  ``kueue_quota_reserved_wait_time`` — this ledger is the ONE emission
  site for those observations (the scheduler/controller delegate when
  a ledger is attached), so ``/debug/journeys`` and ``/metrics`` can
  never disagree, the way PR-4 reconciled cycle spans with the phase
  histograms.
- **Exemplar retention**: only the ``exemplars`` slowest completed
  journeys plus recent SLO-violating ones are retained in full for
  ``/debug/journeys`` and ``tools/trace_dump.py --journey``.

A **burn-rate evaluator** prices the live SLI stream against
SLOSpec-derived objectives (``perf.checker.journey_objectives``): per
class, an EWMA of the violation indicator (1 when a sealed journey's
TTA exceeds its objective) divided by the error budget fraction —
burn rate 1.0 means violations are arriving exactly at the budgeted
rate, >1 means the budget is burning faster than allowed. Exposed as
``kueue_slo_burn_rate{class}``.

Cost contract (mirrors the flight recorder): with the ledger DISABLED
the scheduler/controller hooks are one attribute load plus an
``is None`` compare (the manager simply wires no ledger); enabled,
each hook is a span append under one lock. The ``journey_overhead``
bench row pins both at <=1% of a cycle.
"""

from __future__ import annotations

import heapq
import threading
import time as _time
from collections import OrderedDict, deque
from typing import Optional

DEFAULT_JOURNEY_CAPACITY = 8192
DEFAULT_JOURNEY_EXEMPLARS = 8

# Hard per-journey span bound: a pathological workload requeued for
# thousands of cycles must not grow its timeline without limit. The
# repeat-collapse below (identical consecutive requeue spans merge
# into one span with a repeat count + covered-cycle range) keeps real
# journeys far below this; hitting the cap drops the oldest
# non-arrival span and counts it.
MAX_SPANS_PER_JOURNEY = 512

# Burn-rate evaluator defaults: the error budget is the fraction of
# sealed journeys allowed to miss their class objective (SRE-style);
# the EWMA alpha sets the evaluator's memory (~1/alpha journeys).
DEFAULT_ERROR_BUDGET = 0.05
DEFAULT_BURN_ALPHA = 0.1

# The scenario suite's priority-class label (sim/scenarios.py); plain
# deployments fall back to the workload's priorityClassName.
CLASS_LABEL = "scenario.kueue-tpu/class"
DEFAULT_CLASS = "standard"


_REASON_NAMES: dict = {}


def _reason_name(reason) -> str:
    """Memoized RequeueReason -> name (the enum descriptor lookup is
    measurable on the per-entry hot path)."""
    name = _REASON_NAMES.get(reason)
    if name is None:
        name = getattr(reason, "name", None) or str(reason)
        _REASON_NAMES[reason] = name
    return name


def workload_class(obj) -> str:
    """The SLI class of a workload: the scenario class label when
    present, else the priority class name, else "standard"."""
    labels = getattr(obj.metadata, "labels", None) or {}
    cls = labels.get(CLASS_LABEL)
    if cls:
        return cls
    cls = getattr(obj.spec, "priority_class_name", "") or ""
    return cls or DEFAULT_CLASS


class JourneySpan:
    """One step of a workload's admission journey. ``cycle`` is the
    scheduler attempt id the span was stamped under (0 = outside any
    cycle, e.g. an arrival before the first cycle), ``generation`` the
    cache's structural token at that cycle's start, ``route`` the
    cycle's route when known. ``sig`` is the internal repeat-collapse
    identity (requeue hot path), never serialized."""

    __slots__ = ("kind", "t", "cycle", "generation", "route", "fields",
                 "sig")

    def __init__(self, kind: str, t: float, cycle: int, generation: tuple,
                 route: str, fields: Optional[dict] = None):
        self.kind = kind
        self.t = t
        self.cycle = cycle
        self.generation = generation
        self.route = route
        self.fields = fields
        self.sig = None

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "t": round(self.t, 6),
             "cycle": self.cycle, "generation": list(self.generation),
             "route": self.route}
        if self.fields:
            d.update(self.fields)
        return d


class WorkloadJourney:
    __slots__ = ("key", "cluster_queue", "class_name", "created_t",
                 "spans", "sealed_t", "tta_s", "requeues", "admissions",
                 "dropped_spans")

    def __init__(self, key: str, cluster_queue: str, class_name: str,
                 created_t: float):
        self.key = key
        self.cluster_queue = cluster_queue
        self.class_name = class_name
        self.created_t = created_t
        self.spans: list = []
        self.sealed_t: Optional[float] = None
        self.tta_s: Optional[float] = None
        self.requeues = 0      # requeued/shed/deferred events
        self.admissions = 0    # seals (re-admissions after eviction)
        self.dropped_spans = 0  # spans shed by MAX_SPANS_PER_JOURNEY

    def to_dict(self) -> dict:
        return {
            "workload": self.key,
            "cluster_queue": self.cluster_queue,
            "class": self.class_name,
            "created_t": round(self.created_t, 6),
            "sealed": self.sealed_t is not None,
            "tta_s": (round(self.tta_s, 6)
                      if self.tta_s is not None else None),
            "requeues": self.requeues,
            "admissions": self.admissions,
            "dropped_spans": self.dropped_spans,
            "spans": [s.to_dict() for s in self.spans],
        }

    def timeline_complete(self) -> tuple:
        """(ok, why): the acceptance contract for an admitted journey —
        it starts at an anchor (``queued`` for an arrival; ``evicted``/
        ``preempted`` for a journey that begins at a post-admission
        eviction, whose pre-eviction life sealed and folded into the
        SLIs; any first span marked ``resumed`` for a journey whose
        arrival the LRU capacity bound shed), ends admitted, every
        span carries a cycle id and generation token, and both time
        and cycle ids are monotone (no gaps between arrival and
        admission: every step of the 40 cycles is accounted for by a
        stamped span)."""
        if not self.spans:
            return False, "no spans"
        first = self.spans[0]
        if first.kind not in ("queued", "evicted", "preempted") \
                and not (first.fields or {}).get("resumed"):
            return False, (f"first span is {first.kind!r}, "
                           "not an arrival/eviction/resumed anchor")
        last = self.spans[-1]
        if last.kind not in ("quota-reserved", "admitted"):
            return False, f"last span is {last.kind!r}, not an admission"
        prev_t, prev_c = None, None
        for s in self.spans:
            if not isinstance(s.cycle, int) or not s.generation:
                return False, f"span {s.kind!r} missing cycle/generation"
            if prev_t is not None and s.t < prev_t - 1e-9:
                return False, f"span {s.kind!r} out of time order"
            if prev_c is not None and s.cycle < prev_c:
                return False, f"span {s.kind!r} cycle id went backwards"
            prev_t, prev_c = s.t, s.cycle
        return True, ""


class JourneyLedger:
    """Bounded journey store + the SLI/burn-rate fold. Thread-safe:
    hooks arrive from the scheduler thread, the runtime's reconcilers
    and HTTP readers."""

    def __init__(self, capacity: int = DEFAULT_JOURNEY_CAPACITY,
                 exemplars: int = DEFAULT_JOURNEY_EXEMPLARS,
                 metrics=None, clock=None, generation_source=None,
                 error_budget: float = DEFAULT_ERROR_BUDGET,
                 burn_alpha: float = DEFAULT_BURN_ALPHA):
        if capacity < 1:
            raise ValueError("journey ledger capacity must be >= 1")
        if exemplars < 1:
            raise ValueError("journey exemplars must be >= 1")
        self.capacity = capacity
        self.exemplars = exemplars
        self.metrics = metrics
        self.clock = clock
        # Zero-arg callable returning the live structural generation
        # token (manager wires cache.generation_token): spans recorded
        # BEFORE the first cycle stamps one (arrivals pre-traffic)
        # fetch it lazily so every span carries a token.
        self.generation_source = generation_source
        self.error_budget = error_budget
        self.burn_alpha = burn_alpha
        self._lock = threading.Lock()
        self._active: OrderedDict = OrderedDict()   # key -> journey (LRU)
        self._slow: list = []        # min-heap of (tta, seq, journey)
        self._violations: deque = deque(maxlen=max(4 * exemplars, 32))
        self._seq = 0
        # Cycle context stamped onto every span (begin_cycle/set_route).
        # _cycle_t is read once per cycle and reused by the per-entry
        # hot hooks — a clock read per span would price the requeue
        # flood (spans within one cycle share the cycle's timestamp by
        # construction anyway).
        self._cycle = 0
        self._cycle_t = 0.0
        self._generation: tuple = ()
        self._route = ""
        # Lifetime counters (survive LRU eviction and exemplar folds).
        self.journeys_started = 0
        self.journeys_completed = 0
        self.requeues_total = 0
        self.quota_reservations = 0
        self.lru_evictions = 0
        self.unstamped_spans = 0     # spans recorded before any cycle
        # Burn-rate evaluator state: class -> (objective_s) and
        # class -> violation-indicator EWMA.
        self._objectives: dict = {}
        self._burn_ewma: dict = {}

    # -- wiring ----------------------------------------------------------

    def set_objectives(self, objectives: dict) -> None:
        """class -> target TTA seconds (perf.checker.journey_objectives
        derives these from an SLOSpec). Sealing a journey whose TTA
        exceeds its class objective counts against the error budget and
        retains the journey as a violation exemplar."""
        with self._lock:
            self._objectives = dict(objectives or {})

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else _time.time()

    # -- cycle context (scheduler) --------------------------------------

    def begin_cycle(self, cycle_id: int, generation: tuple) -> None:
        """Stamp the context every span in this cycle carries: the
        scheduler attempt id, the cache's structural generation token
        read at cycle start, and the cycle's timestamp."""
        self._cycle = cycle_id
        self._cycle_t = self._now()
        self._generation = tuple(generation)
        self._route = ""

    def set_route(self, route: str) -> None:
        self._route = route

    def seal_cycle(self) -> None:
        """Cycle end: refresh the derived gauges once per cycle (not
        per span — a requeue flood must not pay a gauge write per
        entry)."""
        m = self.metrics
        if m is not None:
            m.set_requeue_amplification(self.requeues_per_admission)

    @property
    def requeues_per_admission(self) -> float:
        """ROADMAP item 5's soak invariant: requeue-class spans per
        sealed admission. Rises without bound when a backlog churns
        without admitting (requeue pile-up); ~N for a healthy system
        whose entries wait N cycles."""
        return self.requeues_total / max(self.journeys_completed, 1)

    # -- journey mutation ------------------------------------------------

    def _journey(self, key: str, cluster_queue: str = "",
                 class_name: str = "", created_t: Optional[float] = None):
        """The active journey for ``key``, created (and LRU-touched) on
        demand. Caller holds the lock."""
        j = self._active.get(key)
        if j is not None:
            self._active.move_to_end(key)
            if cluster_queue and not j.cluster_queue:
                j.cluster_queue = cluster_queue
            if class_name and j.class_name == DEFAULT_CLASS:
                # A journey re-created mid-life (LRU eviction dropped
                # the arrival) learns its real SLI class from the first
                # hook that carries the workload object — the TTA must
                # fold into the right histogram/objective.
                j.class_name = class_name
            return j
        t = created_t if created_t is not None else self._now()
        j = WorkloadJourney(key, cluster_queue,
                            class_name or DEFAULT_CLASS, t)
        self._active[key] = j
        self.journeys_started += 1
        while len(self._active) > self.capacity:
            self._active.popitem(last=False)
            self.lru_evictions += 1
            if self.metrics is not None:
                self.metrics.journey_lru_evicted()
        return j

    @staticmethod
    def _append_span(j: WorkloadJourney, span: "JourneySpan") -> None:
        spans = j.spans
        if len(spans) >= MAX_SPANS_PER_JOURNEY:
            # Keep the arrival span (index 0) — the timeline's anchor —
            # and shed the oldest step after it.
            del spans[1]
            j.dropped_spans += 1
        if spans and span.t < spans[-1].t:
            # Monotone-by-construction: append order IS the causal
            # order; timestamps are best-effort coordinates (a workload
            # created mid-cycle carries a creation time later than the
            # cycle-start stamp its first requeue reuses) — clamp so
            # the timeline never reads backwards.
            span.t = spans[-1].t
        spans.append(span)

    def _span(self, j: WorkloadJourney, kind: str,
              fields: Optional[dict] = None,
              t: Optional[float] = None) -> None:
        if not j.spans and kind not in ("queued", "evicted", "preempted"):
            # First span of a journey created mid-life: the arrival was
            # dropped (LRU eviction under a storm past the capacity
            # bound). Mark the truncation honestly — timeline_complete
            # accepts a resumed first span as an anchor instead of
            # minting a false "incomplete" verdict for evidence the
            # bounded ledger was DESIGNED to shed.
            fields = dict(fields) if fields else {}
            fields["resumed"] = True
        if not self._generation:
            # Before the first cycle no stamped token exists yet:
            # fetch the live one so pre-traffic arrivals stay causal
            # (cycle 0 = before the first cycle, by construction).
            src = self.generation_source
            if src is not None:
                try:
                    self._generation = tuple(src())
                except Exception:  # noqa: BLE001 — stamping must not kill hooks
                    pass
            if not self._generation:
                self.unstamped_spans += 1
        self._append_span(j, JourneySpan(
            kind, t if t is not None else self._now(),
            self._cycle, self._generation, self._route, fields))

    # -- hooks: queue delta feed (queue.Manager.add_journey_listener) ----

    def note_queue_delta(self, kind: str, key: str, info) -> None:
        """'upsert' = the workload entered (or re-entered) the pending
        set; 'del' = it left. Called under the queue-manager lock —
        this only appends under the ledger's own lock, never calls
        back. Upserts of an already-tracked journey are object
        replacements (status patches) and record nothing; deletes are
        left to the LRU (an admission-driven delete precedes the
        quota-reserved span, so the key alone cannot distinguish a
        cancel from an admit here)."""
        if kind != "upsert" or info is None:
            return
        with self._lock:
            j = self._active.get(key)
            if j is not None:
                self._active.move_to_end(key)
                if j.spans and j.spans[-1].kind in ("evicted",
                                                    "preempted"):
                    # Re-entry to the pending set after an eviction:
                    # the re-admission loop's own arrival marker.
                    self._span(j, "queued", {"cq": j.cluster_queue})
                return
            obj = info.obj
            created = getattr(obj.metadata, "creation_timestamp", None)
            j = self._journey(key, getattr(info, "cluster_queue", "") or "",
                              workload_class(obj),
                              created_t=created)
            # Anchor the arrival at the journey's creation time (the
            # queued-wait clock the TTA is measured from), not the
            # notification wall time.
            self._span(j, "queued", {"cq": j.cluster_queue},
                       t=j.created_t)

    # -- hooks: scheduler ------------------------------------------------

    def requeued(self, info, status: str, reason, msg: str = "") -> None:
        """A cycle considered this entry and re-heaped it (the
        requeue_and_update choke point every non-admitted entry on
        every route passes through). ``status`` is the entry status
        ("" = failed validation/assignment before nomination).

        Hot-path contract: this fires once per non-admitted entry per
        cycle — a requeue flood's dominant hook. Consecutive identical
        requeues (same status/reason/message, the flood shape) COLLAPSE
        into the previous span: ``repeats`` counts them and
        ``last_cycle`` closes the covered range, so a 40-cycle backoff
        loop reads as one span spanning cycles [n, n+40] instead of 40
        allocations — bounded timelines AND an allocation-free flood
        path (the journey_overhead bench pins it)."""
        status = status or "not-nominated"
        reason_name = _reason_name(reason)
        msg = msg[:160] if msg else ""
        sig = (status, reason_name, msg)
        with self._lock:
            active = self._active
            j = active.get(info.key)
            if j is None:
                j = self._journey(info.key, info.cluster_queue or "")
            else:
                active.move_to_end(info.key)
            spans = j.spans
            if spans:
                last = spans[-1]
                if last.sig == sig:
                    f = last.fields
                    f["repeats"] = f.get("repeats", 1) + 1
                    f["last_cycle"] = self._cycle
                    j.requeues += 1
                    self.requeues_total += 1
                    return
            fields = {"status": status, "reason": reason_name}
            if msg:
                fields["msg"] = msg
            self._span(j, "requeued", fields, t=self._cycle_t)
            spans[-1].sig = sig
            j.requeues += 1
            self.requeues_total += 1

    def shed(self, info) -> None:
        """Head re-heaped by the degradation ladder's cap before
        nomination (deferred by shedding, not by fit). Same collapse
        as requeued — a shed storm repeats identically."""
        with self._lock:
            j = self._journey(info.key, info.cluster_queue or "")
            spans = j.spans
            if spans and spans[-1].kind == "shed" \
                    and spans[-1].fields is not None:
                f = spans[-1].fields
                f["repeats"] = f.get("repeats", 1) + 1
                f["last_cycle"] = self._cycle
            else:
                self._span(j, "shed", {"repeats": 1}, t=self._cycle_t)
            j.requeues += 1
            self.requeues_total += 1

    # NOTE: deferred preempt planning (the ladder's shed/survival rung)
    # carries NO separate span kind: the deferred entry still passes
    # through requeue_and_update the same cycle, and its requeued span's
    # message ("Preemption planning deferred (load shedding)") IS the
    # deferral evidence — identical messages collapse, so a long
    # deferral loop reads as one span instead of two-per-cycle
    # interleaved kinds that neither collapse could absorb.

    def quota_reserved(self, wl, cq: str, wait_s: float,
                       admitted: bool) -> None:
        """THE emission site for the reservation-time SLIs (satellite:
        reconcile-by-construction): observes
        kueue_quota_reserved_wait_time (+ admission_wait_time when the
        workload admits in the same write) and stamps the journey, so
        /metrics and /debug/journeys share one producer. ``admitted``
        seals the journey."""
        from kueue_tpu.core import workload as wlpkg
        key = wlpkg.key(wl)
        m = self.metrics
        if m is not None:
            m.quota_reserved(cq, wait_s)
            if admitted:
                m.admitted(cq, wait_s)
        with self._lock:
            j = self._journey(key, cq, workload_class(wl))
            self.quota_reservations += 1
            self._span(j, "quota-reserved", {"cq": cq,
                                             "wait_s": round(wait_s, 6)})
            if admitted:
                self._seal(j, wait_s)

    def admitted_after_checks(self, wl, cq: str, wait_s: float,
                              checks_wait_s: float) -> None:
        """THE emission site for check-gated admissions (the workload
        controller's Admitted flip): observes admission_wait_time +
        admission_checks_wait_time and seals the journey."""
        from kueue_tpu.core import workload as wlpkg
        key = wlpkg.key(wl)
        m = self.metrics
        if m is not None:
            # Observe even with an unknown CQ (empty label — the LQ/CQ
            # was deleted between reservation and the Admitted flip):
            # the reconcile-by-construction invariant is
            # histogram-count == completed-journeys, and a seal without
            # its observation would break exactly the parity this
            # emission site exists to guarantee.
            m.admitted_workload(cq, wait_s)
            m.admission_checks_wait_time.observe(checks_wait_s,
                                                 cluster_queue=cq)
        with self._lock:
            j = self._journey(key, cq, workload_class(wl))
            self._span(j, "admitted",
                       {"cq": cq, "wait_s": round(wait_s, 6),
                        "checks_wait_s": round(checks_wait_s, 6)})
            self._seal(j, wait_s)

    def evicted(self, key: str, cq: str, reason: str) -> None:
        """Eviction re-opens the workload's journey. When the previous
        life already sealed (folded into the SLIs and dropped from the
        active set), this starts a NEW journey anchored at the
        eviction — the re-queue that follows appends its own ``queued``
        span (note_queue_delta), and the next seal counts the
        re-admission."""
        with self._lock:
            j = self._journey(key, cq)
            j.sealed_t = None
            self._span(j, "evicted", {"cq": cq, "reason": reason})

    def preempted(self, key: str, preempting_cq: str, reason: str) -> None:
        """Like evicted(): the victim's journey (or its fresh
        post-admission successor) records who preempted it and why."""
        with self._lock:
            j = self._journey(key)
            j.sealed_t = None
            self._span(j, "preempted", {"by": preempting_cq,
                                        "reason": reason})

    # -- hooks: MultiKueue planned-mirror lifecycle ----------------------

    def mk_event(self, key: str, event: str, cluster: str = "") -> None:
        """event in ("planned", "executed", "expired"): the batched
        cross-cluster placement lifecycle, stamped with the cluster so
        journeys stay causal across the mesh (post-PR-13)."""
        with self._lock:
            j = self._journey(key)
            fields = {"cluster": cluster} if cluster else None
            self._span(j, f"mk-{event}", fields)

    # -- seal + exemplar fold --------------------------------------------

    def _seal(self, j: WorkloadJourney, tta_s: float) -> None:
        """Full admission: fold the journey into the SLIs, retain it as
        an exemplar if it is among the K slowest or violates its class
        objective, and drop it from the active LRU. Caller holds the
        lock."""
        j.sealed_t = self._now()
        j.tta_s = tta_s
        j.admissions += 1
        self.journeys_completed += 1
        m = self.metrics
        if m is not None:
            m.journey_completed(j.class_name, tta_s)
        # Burn rate: EWMA of the violation indicator vs the budget.
        obj = self._objectives.get(j.class_name)
        if obj is not None:
            hit = 1.0 if tta_s > obj else 0.0
            prev = self._burn_ewma.get(j.class_name, 0.0)
            ewma = prev + self.burn_alpha * (hit - prev)
            self._burn_ewma[j.class_name] = ewma
            if m is not None:
                m.set_slo_burn(j.class_name,
                               ewma / max(self.error_budget, 1e-9))
            if hit:
                self._violations.append(j)
        # K-slowest exemplars (min-heap on TTA).
        self._seq += 1
        entry = (tta_s, self._seq, j)
        if len(self._slow) < self.exemplars:
            heapq.heappush(self._slow, entry)
        elif tta_s > self._slow[0][0]:
            heapq.heapreplace(self._slow, entry)
        self._active.pop(j.key, None)

    # -- consumers (/debug/journeys, probe, tests) -----------------------

    def journey_dict(self, key: str) -> Optional[dict]:
        """Point lookup serialized UNDER the ledger lock: an active
        journey mutates on the scheduler thread (span appends, collapse
        field updates, the span-cap del), so HTTP readers must
        materialize the wire form while holding the lock — handing the
        live object out and serializing it later tears mid-flood."""
        with self._lock:
            j = self._journey_locked(key)
            return j.to_dict() if j is not None else None

    def journey(self, key: str) -> Optional[WorkloadJourney]:
        """Point lookup: the active journey first, else the MOST RECENT
        retained one (a re-admitted workload can have several sealed
        lives among the exemplars — the newest is the one an operator
        is asking about). Accepts a full "ns/name" key or a bare name.
        NOTE: an active journey keeps mutating — use journey_dict()
        from reader threads."""
        with self._lock:
            return self._journey_locked(key)

    def _journey_locked(self, key: str) -> Optional[WorkloadJourney]:
        j = self._active.get(key)
        if j is None and "/" not in key:
            for k, cand in self._active.items():
                if k.split("/", 1)[-1] == key:
                    j = cand
                    break
        if j is not None:
            return j

        def matches(cand):
            return (cand.key == key
                    or cand.key.split("/", 1)[-1] == key)

        best = None
        for _tta, _seq, cand in self._slow:
            if matches(cand) and (best is None
                                  or cand.sealed_t > best.sealed_t):
                best = cand
        for cand in self._violations:
            if matches(cand) and (best is None
                                  or cand.sealed_t > best.sealed_t):
                best = cand
        return best

    def slowest(self, n: int = 0) -> list:
        """The retained slowest completed journeys, slowest first."""
        with self._lock:
            out = [j for _tta, _seq, j in sorted(self._slow, reverse=True)]
        return out[:n] if n > 0 else out

    def violations(self) -> list:
        with self._lock:
            return list(self._violations)

    def burn_rates(self) -> dict:
        with self._lock:
            return self._burn_rates_locked()

    def _burn_rates_locked(self) -> dict:
        return {cls: round(e / max(self.error_budget, 1e-9), 4)
                for cls, e in self._burn_ewma.items()}

    @property
    def retained(self) -> int:
        """Journeys currently held (active + exemplars + violations) —
        the leak detector: zero after close()."""
        with self._lock:
            return len(self._active) + len(self._slow) + len(self._violations)

    def status(self) -> dict:
        """The single producer /debug/journeys, the SIGUSR2 dumper,
        tools/journey_probe.py and tests share."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "exemplars": self.exemplars,
                "active": len(self._active),
                "started": self.journeys_started,
                "completed": self.journeys_completed,
                "quota_reservations": self.quota_reservations,
                "requeues": self.requeues_total,
                "requeues_per_admission": round(
                    self.requeues_per_admission, 4),
                "lru_evictions": self.lru_evictions,
                "unstamped_spans": self.unstamped_spans,
                "violations_retained": len(self._violations),
                "objectives": dict(self._objectives),
                "burn_rates": self._burn_rates_locked(),
                "cycle": self._cycle,
            }

    def close(self) -> None:
        """Shutdown: drop every retained journey (active, exemplars,
        violations) — the ledger's leak contract is zero retained
        journeys after shutdown, mirroring cache.live_handouts."""
        with self._lock:
            self._active.clear()
            self._slow.clear()
            self._violations.clear()
