"""Cycle flight recorder: a queryable black box for the admission loop.

Every scheduler cycle produces a structured ``CycleTrace`` — route mode
(device / device-pipelined / cpu / cpu-forced / cpu-strict /
cpu-breaker / cpu-survival / cpu-warmup / drain), regime,
degradation-ladder rung,
head/admit/evict counts, fault and
breaker annotations, and the cycle's phase spans (snapshot, encode,
route, dispatch, fetch, decode, preempt-plan, apply, requeue, plus
nested sub-spans like ``dispatch.scatter``) — held in a bounded ring
buffer of the last N cycles. The recorder is the single source both
the ``/debug/cycles`` endpoint and the ``cycle_phase_seconds``
histograms are fed from, so their per-cycle sums reconcile by
construction.

Cost contract (mirrors ``resilience.faultinject``): with the recorder
DISABLED, ``begin_cycle`` returns None and every ``span``/``annotate``
call is one attribute load plus an ``is None`` compare; the
``trace_overhead`` bench row pins both the disabled and the enabled
per-cycle cost at <=1% of a fault-free cycle. Span capture itself is a
tuple append — no allocation beyond the tuple, no locking on the hot
path (the scheduler thread is the only writer; readers copy under the
ring lock).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

DEFAULT_CAPACITY = 256


class CycleTrace:
    """One cycle's trace. ``spans`` holds ``(name, start_s, dur_s)``
    tuples with starts relative to the cycle's own t0; a dot in the
    name nests it under its prefix phase (``dispatch.scatter`` is part
    of ``dispatch`` and excluded from per-phase sums to avoid
    double-counting)."""

    __slots__ = ("cycle_id", "t_wall", "t0", "duration_s", "route",
                 "regime", "heads", "admitted", "evictions", "faults",
                 "breaker", "degraded", "tag", "spans", "annotations",
                 "upload_bytes", "fetch_bytes", "dispatches", "collects")

    def __init__(self, cycle_id: int, t_wall: float, t0: float):
        self.cycle_id = cycle_id
        self.t_wall = t_wall          # epoch seconds at cycle start
        self.t0 = t0                  # perf_counter base for span offsets
        self.duration_s = 0.0
        self.route = ""
        self.regime = ""
        self.heads = 0
        self.admitted: Optional[int] = None
        self.evictions = 0
        self.faults = 0
        self.breaker = ""
        self.degraded = ""            # ladder rung the cycle ran under
        self.tag = ""                 # driver context (scenario phase)
        # Per-cycle host<->device transport (solver counter deltas over
        # the cycle): bytes on the wire and round trips — the steady-
        # state contract is ONE dispatch + ONE collect per device cycle
        # with a decision-sized fetch, and these fields make every
        # violation visible per trace (tools/transport_probe.py).
        self.upload_bytes = 0
        self.fetch_bytes = 0
        self.dispatches = 0
        self.collects = 0
        self.spans: list = []         # (name, start_s, dur_s)
        self.annotations: list = []   # dicts: {"kind", "message", ...}

    def phase_sums(self) -> dict:
        """Per-phase wall seconds, top-level spans only (nested
        ``a.b`` spans are already inside their parent's time)."""
        sums: dict = {}
        for name, _start, dur in self.spans:
            if "." in name:
                continue
            sums[name] = sums.get(name, 0.0) + dur
        return sums

    def to_dict(self) -> dict:
        return {
            "cycle": self.cycle_id,
            "t_wall": self.t_wall,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "route": self.route,
            "regime": self.regime,
            "heads": self.heads,
            "admitted": self.admitted,
            "evictions": self.evictions,
            "faults": self.faults,
            "breaker": self.breaker,
            "degraded": self.degraded,
            "tag": self.tag,
            "upload_bytes": self.upload_bytes,
            "fetch_bytes": self.fetch_bytes,
            "dispatches": self.dispatches,
            "collects": self.collects,
            "spans": [{"name": n, "start_ms": round(s * 1e3, 3),
                       "dur_ms": round(d * 1e3, 3)}
                      for n, s, d in self.spans],
            "annotations": list(self.annotations),
        }


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        self._ring: list = []      # completed traces, oldest first
        self._current: Optional[CycleTrace] = None
        self.cycles_recorded = 0   # lifetime count (ring is bounded)
        # Driver-owned context tag stamped onto every trace begun while
        # it is set: scenario drivers (sim/scenarios.py) label cycles
        # with the traffic phase ("ramp"/"storm"/"recovery") so SLO
        # evaluation can window the trace stream without guessing from
        # timestamps. Empty outside scenario runs.
        self.tag = ""

    def set_tag(self, tag: str) -> None:
        """Set the phase tag stamped onto subsequent traces ("" clears)."""
        self.tag = tag

    # --- producer side (the scheduler thread) ---

    def begin_cycle(self, cycle_id: int) -> Optional[CycleTrace]:
        """Start a trace (None when disabled — all subsequent span/
        annotate calls become single-compare no-ops). An unfinished
        previous trace (a cycle that died mid-flight) is discarded."""
        if not self.enabled:
            self._current = None
            return None
        tr = CycleTrace(cycle_id, time.time(), time.perf_counter())
        tr.tag = self.tag
        self._current = tr
        return tr

    def span(self, name: str, t0: float, dur_s: float) -> None:
        """Record a phase span; ``t0`` is the span's perf_counter start.
        Hot path: no-op unless a trace is open."""
        tr = self._current
        if tr is None:
            return
        tr.spans.append((name, t0 - tr.t0, dur_s))

    def annotate(self, kind: str, message: str, **fields) -> None:
        """Attach a fault/timeout/breaker annotation to the open trace."""
        tr = self._current
        if tr is None:
            return
        tr.annotations.append({"kind": kind, "message": message, **fields})

    def finish(self, trace: Optional[CycleTrace]) -> None:
        """Seal the trace and append it to the ring."""
        if trace is None:
            return
        trace.duration_s = time.perf_counter() - trace.t0
        if self._current is trace:
            self._current = None
        with self._lock:
            self._ring.append(trace)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]
            self.cycles_recorded += 1

    # --- consumer side (endpoints, dumper, tests) ---

    def traces(self, n: int = 0) -> list:
        """The last ``n`` completed traces (all retained when n<=0),
        oldest first."""
        with self._lock:
            out = list(self._ring)
        return out[-n:] if n > 0 else out

    def last(self) -> Optional[CycleTrace]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def slowest(self, k: int) -> list:
        """The k slowest retained cycles, slowest first."""
        with self._lock:
            out = list(self._ring)
        out.sort(key=lambda t: t.duration_s, reverse=True)
        return out[: max(k, 0)]
