"""Observability plane: the per-cycle flight recorder and the operator
debug surface it feeds (see OBSERVABILITY.md)."""

from kueue_tpu.obs.recorder import (
    DEFAULT_CAPACITY,
    CycleTrace,
    FlightRecorder,
)
from kueue_tpu.obs.queryplane import QueryPlane, SealedView
from kueue_tpu.obs.status import (
    DebugEndpoints,
    arena_status,
    breaker_status,
    degrade_status,
    pipeline_status,
    queryplane_status,
    recovery_status,
    router_status,
    warmup_status,
)

__all__ = [
    "DEFAULT_CAPACITY",
    "CycleTrace",
    "FlightRecorder",
    "QueryPlane",
    "SealedView",
    "DebugEndpoints",
    "arena_status",
    "breaker_status",
    "degrade_status",
    "pipeline_status",
    "queryplane_status",
    "recovery_status",
    "router_status",
    "warmup_status",
]
