"""Observability plane: the per-cycle flight recorder and the operator
debug surface it feeds (see OBSERVABILITY.md)."""

from kueue_tpu.obs.recorder import (
    DEFAULT_CAPACITY,
    CycleTrace,
    FlightRecorder,
)
from kueue_tpu.obs.journey import JourneyLedger, WorkloadJourney
from kueue_tpu.obs.queryplane import QueryPlane, SealedView
from kueue_tpu.obs.status import (
    DebugEndpoints,
    aging_status,
    arena_status,
    breaker_status,
    degrade_status,
    journey_status,
    pipeline_status,
    queryplane_status,
    recovery_status,
    router_status,
    shards_status,
    warmup_status,
)
from kueue_tpu.obs.trend import AgingWatch, TrendMonitor

__all__ = [
    "DEFAULT_CAPACITY",
    "AgingWatch",
    "CycleTrace",
    "FlightRecorder",
    "JourneyLedger",
    "QueryPlane",
    "SealedView",
    "TrendMonitor",
    "WorkloadJourney",
    "DebugEndpoints",
    "aging_status",
    "arena_status",
    "breaker_status",
    "degrade_status",
    "journey_status",
    "pipeline_status",
    "queryplane_status",
    "recovery_status",
    "router_status",
    "shards_status",
    "warmup_status",
]
