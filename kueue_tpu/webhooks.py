"""Admission-time defaulting + validation for core types and jobs.

Equivalent of the reference's pkg/webhooks (workload_webhook.go:333,
clusterqueue_webhook.go:231, resourceflavor_webhook.go:130) and the
per-job webhooks in pkg/controller/jobs/*/\\*_webhook.go (suspend
enforcement on create, queue-name immutability while unsuspended, pod
scheduling-gate injection — pod_webhook.go:180-190). All rules are pure
functions returning error-string lists; `setup_webhooks` installs them
as sim-store admission hooks so writes are rejected the way a real
webhook would.
"""

from __future__ import annotations

import re
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.sim import Invalid, Store

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_PODSETS = 8
MAX_RESOURCE_GROUPS = 16
MAX_FLAVORS_PER_GROUP = 16
MAX_RESOURCES_PER_GROUP = 16


def _valid_name(name: str) -> bool:
    return bool(name) and len(name) <= 63 and _DNS1123.match(name) is not None


# ---------------------------------------------------------------------------
# Workload (reference: workload_webhook.go)
# ---------------------------------------------------------------------------

def default_workload(wl: api.Workload) -> None:
    """reference: WorkloadWebhook.Default (:57-75) — name the only
    podset "main"."""
    if len(wl.spec.pod_sets) == 1 and not wl.spec.pod_sets[0].name:
        wl.spec.pod_sets[0].name = api.DEFAULT_PODSET_NAME


def validate_workload(wl: api.Workload) -> list:
    errs = []
    variable_count = 0
    names = set()
    if not wl.spec.pod_sets:
        errs.append("spec.podSets: at least one podSet is required")
    if len(wl.spec.pod_sets) > MAX_PODSETS:
        errs.append(f"spec.podSets: must have at most {MAX_PODSETS} podSets")
    for i, ps in enumerate(wl.spec.pod_sets):
        path = f"spec.podSets[{i}]"
        if not _valid_name(ps.name):
            errs.append(f"{path}.name: invalid podSet name {ps.name!r}")
        if ps.name in names:
            errs.append(f"{path}.name: duplicate podSet name {ps.name!r}")
        names.add(ps.name)
        if ps.count < 0:
            errs.append(f"{path}.count: must be >= 0")
        if ps.min_count is not None:
            variable_count += 1
            if not (0 < ps.min_count <= ps.count):
                errs.append(f"{path}.minCount: must be in (0, count]")
        for c in ps.template.spec.containers + ps.template.spec.init_containers:
            if "pods" in c.requests:
                errs.append(f"{path}: the 'pods' resource is reserved for "
                            "internal kueue use")
    if variable_count > 1:
        errs.append("spec.podSets: at most one podSet can use minCount")
    if wlpkg.has_quota_reservation(wl):
        errs.extend(_validate_admission(wl))
    errs.extend(_validate_reclaimable(wl))
    return errs


def _validate_admission(wl: api.Workload) -> list:
    errs = []
    adm = wl.status.admission
    if adm is None:
        return ["status.admission: required once QuotaReserved"]
    ps_by_name = {ps.name: ps for ps in wl.spec.pod_sets}
    if {psa.name for psa in adm.pod_set_assignments} != set(ps_by_name):
        errs.append("status.admission.podSetAssignments: must have one "
                    "assignment per podSet")
        return errs
    for psa in adm.pod_set_assignments:
        ps = ps_by_name[psa.name]
        count = psa.count if psa.count is not None else ps.count
        for res, usage in psa.resource_usage.items():
            if count and usage % count != 0:
                # usage must be divisible by pod count (reference: :234)
                errs.append(
                    f"status.admission.podSetAssignments[{psa.name}]."
                    f"resourceUsage[{res}]: {usage} is not a multiple of {count}")
    return errs


def _validate_reclaimable(wl: api.Workload) -> list:
    errs = []
    counts = {ps.name: ps.count for ps in wl.spec.pod_sets}
    for rp in wl.status.reclaimable_pods:
        if rp.count < 0:
            errs.append(f"status.reclaimablePods[{rp.name}].count: must be >= 0")
        if rp.name not in counts:
            errs.append(f"status.reclaimablePods[{rp.name}]: no such podSet")
        elif rp.count > counts[rp.name]:
            errs.append(f"status.reclaimablePods[{rp.name}].count: should be "
                        f"less or equal to {counts[rp.name]}")
    return errs


def validate_workload_update(new: api.Workload, old: api.Workload) -> list:
    """reference: ValidateWorkloadUpdate (:269-287)."""
    errs = validate_workload(new)
    if wlpkg.has_quota_reservation(old) and \
            _podsets_shape(new.spec.pod_sets) != _podsets_shape(old.spec.pod_sets):
        errs.append("spec.podSets: field is immutable while quota is reserved")
    if (new.status.admission is not None and old.status.admission is not None
            and new.status.admission != old.status.admission):
        errs.append("status.admission: field is immutable; it can only be "
                    "set or unset")
    if wlpkg.has_quota_reservation(new) and wlpkg.has_quota_reservation(old):
        old_counts = {rp.name: rp.count for rp in old.status.reclaimable_pods}
        for rp in new.status.reclaimable_pods:
            floor = old_counts.get(rp.name, 0)
            if rp.count < floor:
                errs.append(f"status.reclaimablePods[{rp.name}].count: cannot "
                            f"be less than {floor}")
    return errs


def _podsets_shape(pod_sets: list) -> list:
    return [(ps.name, ps.count, ps.min_count) for ps in pod_sets]


# ---------------------------------------------------------------------------
# ClusterQueue (reference: clusterqueue_webhook.go)
# ---------------------------------------------------------------------------

def validate_cluster_queue(cq: api.ClusterQueue) -> list:
    errs = []
    spec = cq.spec
    if spec.cohort and not _valid_name(spec.cohort):
        errs.append(f"spec.cohort: invalid cohort name {spec.cohort!r}")
    if spec.queueing_strategy not in (api.STRICT_FIFO, api.BEST_EFFORT_FIFO):
        errs.append(f"spec.queueingStrategy: unsupported value "
                    f"{spec.queueing_strategy!r}")
    # reclaimWithinCohort=Never is incompatible with borrowWithinCohort
    # (reference: validatePreemption :121-129)
    p = spec.preemption
    if (p.reclaim_within_cohort == api.PREEMPTION_NEVER
            and p.borrow_within_cohort is not None
            and p.borrow_within_cohort.policy != api.BORROW_WITHIN_COHORT_NEVER):
        errs.append("spec.preemption: reclaimWithinCohort=Never and "
                    "borrowWithinCohort.Policy!=Never")
    # checks XOR strategy (reference: validateCQAdmissionChecks :131-138)
    if spec.admission_checks and spec.admission_checks_strategy:
        errs.append("spec: either admissionChecks or admissionChecksStrategy "
                    "can be set, but not both")
    errs.extend(_validate_resource_groups(spec))
    if spec.fair_sharing is not None and spec.fair_sharing.weight < 0:
        errs.append("spec.fairSharing.weight: must be >= 0")
    return errs


def _validate_resource_groups(spec: api.ClusterQueueSpec) -> list:
    errs = []
    if len(spec.resource_groups) > MAX_RESOURCE_GROUPS:
        errs.append(f"spec.resourceGroups: must have at most "
                    f"{MAX_RESOURCE_GROUPS} groups")
    seen_resources = set()
    seen_flavors = set()
    for i, rg in enumerate(spec.resource_groups):
        path = f"spec.resourceGroups[{i}]"
        if not rg.covered_resources:
            errs.append(f"{path}.coveredResources: at least one resource required")
        if len(rg.covered_resources) > MAX_RESOURCES_PER_GROUP:
            errs.append(f"{path}.coveredResources: at most "
                        f"{MAX_RESOURCES_PER_GROUP} resources")
        if len(rg.flavors) > MAX_FLAVORS_PER_GROUP:
            errs.append(f"{path}.flavors: at most {MAX_FLAVORS_PER_GROUP} flavors")
        for res in rg.covered_resources:
            if res in seen_resources:
                errs.append(f"{path}.coveredResources: resource {res!r} already "
                            "covered by another resource group")
            seen_resources.add(res)
        for j, fq in enumerate(rg.flavors):
            fpath = f"{path}.flavors[{j}]"
            if fq.name in seen_flavors:
                errs.append(f"{fpath}.name: flavor {fq.name!r} already used in "
                            "another resource group")
            seen_flavors.add(fq.name)
            quota_names = [q.name for q in fq.resources]
            if quota_names != list(rg.covered_resources):
                errs.append(f"{fpath}.resources: must match coveredResources "
                            "in the same order")
            for q in fq.resources:
                qpath = f"{fpath}.resources[{q.name}]"
                if q.nominal_quota < 0:
                    errs.append(f"{qpath}.nominalQuota: must be >= 0")
                if q.borrowing_limit is not None:
                    if q.borrowing_limit < 0:
                        errs.append(f"{qpath}.borrowingLimit: must be >= 0")
                    if not spec.cohort:
                        errs.append(f"{qpath}.borrowingLimit: must be nil when "
                                    "cohort is empty")
                if q.lending_limit is not None:
                    if q.lending_limit < 0:
                        errs.append(f"{qpath}.lendingLimit: must be >= 0")
                    if not spec.cohort:
                        errs.append(f"{qpath}.lendingLimit: must be nil when "
                                    "cohort is empty")
                    elif q.lending_limit > q.nominal_quota:
                        errs.append(f"{qpath}.lendingLimit: must be less than "
                                    "or equal to the nominalQuota")
    return errs


# ---------------------------------------------------------------------------
# ResourceFlavor / LocalQueue (reference: resourceflavor_webhook.go:130)
# ---------------------------------------------------------------------------

def validate_resource_flavor(rf: api.ResourceFlavor) -> list:
    errs = []
    for k, v in rf.spec.node_labels.items():
        if not k:
            errs.append("spec.nodeLabels: empty label key")
        if len(v) > 63:
            errs.append(f"spec.nodeLabels[{k}]: label value too long")
    for i, taint in enumerate(rf.spec.node_taints):
        if not taint.key:
            errs.append(f"spec.nodeTaints[{i}].key: required")
        if taint.effect not in ("NoSchedule", "PreferNoSchedule", "NoExecute"):
            errs.append(f"spec.nodeTaints[{i}].effect: unsupported value "
                        f"{taint.effect!r}")
    return errs


def validate_local_queue(lq: api.LocalQueue) -> list:
    errs = []
    if not _valid_name(lq.spec.cluster_queue):
        errs.append(f"spec.clusterQueue: invalid name {lq.spec.cluster_queue!r}")
    return errs


def validate_local_queue_update(new: api.LocalQueue, old: api.LocalQueue) -> list:
    errs = validate_local_queue(new)
    if new.spec.cluster_queue != old.spec.cluster_queue:
        errs.append("spec.clusterQueue: field is immutable")
    return errs


# ---------------------------------------------------------------------------
# Job webhooks (reference: pkg/controller/jobs/*/\*_webhook.go)
# ---------------------------------------------------------------------------

def default_batch_job(job) -> None:
    """Jobs with a queue label are created suspended
    (reference: job_webhook.go Default)."""
    if job.metadata.labels.get(api.QUEUE_LABEL):
        job.spec.suspend = True


def validate_batch_job_update(new, old) -> list:
    """Queue name is immutable while unsuspended
    (reference: job_webhook.go ValidateUpdate)."""
    errs = []
    old_q = old.metadata.labels.get(api.QUEUE_LABEL, "")
    new_q = new.metadata.labels.get(api.QUEUE_LABEL, "")
    if old_q != new_q and not old.spec.suspend:
        errs.append("metadata.labels[kueue.x-k8s.io/queue-name]: must not be "
                    "changed while the job is not suspended")
    return errs


def default_pod(pod, namespace_excludes: Optional[list] = None) -> None:
    """Gate queue-labeled pods at creation
    (reference: pod_webhook.go:180-190)."""
    excludes = namespace_excludes or []
    if pod.metadata.namespace in excludes:
        return
    if not pod.metadata.labels.get(api.QUEUE_LABEL):
        return
    if pod.status.phase not in ("", "Pending"):
        return
    pod.metadata.labels[api.MANAGED_LABEL] = "true"
    if api.ADMISSION_GATE not in pod.spec.scheduling_gates:
        pod.spec.scheduling_gates.append(api.ADMISSION_GATE)


# ---------------------------------------------------------------------------
# wiring
# ---------------------------------------------------------------------------

def _raise_if(errs: list, kind: str, name: str) -> None:
    if errs:
        raise Invalid(f"{kind} {name!r} is invalid: " + "; ".join(errs))


def setup_webhooks(store: Store, cfg=None) -> None:
    """Install the defaulting/validating hooks on the sim store
    (reference: webhooks.Setup, webhooks.go:25-37 + per-job
    SetupWebhook calls in jobframework.setup)."""

    def workload_hook(op, obj, old):
        default_workload(obj)
        errs = (validate_workload(obj) if op == "CREATE"
                else validate_workload_update(obj, old))
        _raise_if(errs, "Workload", obj.metadata.name)

    def cluster_queue_hook(op, obj, old):
        _raise_if(validate_cluster_queue(obj), "ClusterQueue", obj.metadata.name)

    def resource_flavor_hook(op, obj, old):
        _raise_if(validate_resource_flavor(obj), "ResourceFlavor",
                  obj.metadata.name)

    def local_queue_hook(op, obj, old):
        errs = (validate_local_queue(obj) if op == "CREATE"
                else validate_local_queue_update(obj, old))
        _raise_if(errs, "LocalQueue", obj.metadata.name)

    def job_hook(op, obj, old):
        if op == "CREATE":
            default_batch_job(obj)
        else:
            _raise_if(validate_batch_job_update(obj, old), "Job",
                      obj.metadata.name)

    excludes = list(cfg.integrations.pod_options.namespace_selector_exclude) \
        if cfg is not None else []

    def pod_hook(op, obj, old):
        if op == "CREATE":
            default_pod(obj, excludes)

    def deployment_hook(op, obj, old):
        from kueue_tpu.controller.jobs.deployment import propagate_queue_label
        propagate_queue_label(obj)

    store.add_admission_hook("Workload", workload_hook)
    store.add_admission_hook("ClusterQueue", cluster_queue_hook)
    store.add_admission_hook("ResourceFlavor", resource_flavor_hook)
    store.add_admission_hook("LocalQueue", local_queue_hook)
    store.add_admission_hook("Job", job_hook)
    store.add_admission_hook("Pod", pod_hook)
    store.add_admission_hook("Deployment", deployment_hook)
