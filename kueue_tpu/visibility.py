"""Visibility extension API: live pending-workloads views.

Equivalent of the reference's pkg/visibility (server.go:46-98,
api/rest/pending_workloads_cq.go, pending_workloads_lq.go) and
apis/visibility/v1alpha1 (types.go:64-98): positions in queue with
limit/offset pagination, served straight from the queue manager's live
state. `VisibilityServer` optionally exposes the same payloads over
HTTP (the reference registers an aggregated apiserver on :8082).
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg

DEFAULT_LIMIT = 1000


@dataclass
class PendingWorkload:
    """reference: apis/visibility/v1alpha1/types.go:64-83"""
    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    items: list = field(default_factory=list)


class VisibilityAPI:
    def __init__(self, queues):
        self.queues = queues

    def pending_workloads_cq(self, cq_name: str, limit: int = DEFAULT_LIMIT,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """reference: pending_workloads_cq.go:36+ — full, ordered pending
        list with per-LQ positions."""
        infos = self.queues.pending_workloads_info(cq_name)
        lq_positions: dict = {}
        items = []
        for idx, info in enumerate(infos):
            if len(items) >= limit:
                break
            lq_key = wlpkg.queue_key(info.obj)
            lq_pos = lq_positions.get(lq_key, 0)
            lq_positions[lq_key] = lq_pos + 1
            if idx < offset:
                continue
            items.append(PendingWorkload(
                name=info.obj.metadata.name,
                namespace=info.obj.metadata.namespace,
                local_queue_name=info.obj.spec.queue_name,
                priority=prioritypkg.priority(info.obj),
                position_in_cluster_queue=idx,
                position_in_local_queue=lq_pos))
        return PendingWorkloadsSummary(items=items)

    def pending_workloads_lq(self, namespace: str, lq_name: str,
                             limit: int = DEFAULT_LIMIT,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """reference: pending_workloads_lq.go — the LQ view is a filtered
        projection of its CQ's list. Computed in one pass over the CQ's
        ordered infos WITHOUT materializing a PendingWorkload for every
        unrelated LQ (the old limit=10**9 full-summary build was O(CQ
        pending) allocations per request at the 50k-pending shape)."""
        lq_key = f"{namespace}/{lq_name}"
        lq = self.queues.local_queues.get(lq_key)
        if lq is None:
            return PendingWorkloadsSummary()
        infos = self.queues.pending_workloads_info(lq.cluster_queue)
        items = []
        lq_pos = 0
        for idx, info in enumerate(infos):
            obj = info.obj
            if (obj.metadata.namespace != namespace
                    or obj.spec.queue_name != lq_name):
                continue
            pos = lq_pos
            lq_pos += 1
            if pos < offset:
                continue
            if len(items) >= limit:
                break
            items.append(PendingWorkload(
                name=obj.metadata.name,
                namespace=obj.metadata.namespace,
                local_queue_name=obj.spec.queue_name,
                priority=prioritypkg.priority(obj),
                position_in_cluster_queue=idx,
                position_in_local_queue=pos))
        return PendingWorkloadsSummary(items=items)


class VisibilityServer:
    """Serve the visibility API over HTTP (reference: server on :8082).

    GET /apis/visibility.kueue.x-k8s.io/v1alpha1/clusterqueues/<cq>/pendingworkloads
    GET /apis/visibility.kueue.x-k8s.io/v1alpha1/namespaces/<ns>/localqueues/<lq>/pendingworkloads
    Query params: limit, offset.

    With a ``debug`` surface wired (obs.DebugEndpoints — the manager's
    ``serve_visibility`` does this), the server additionally exposes the
    operator endpoints:

    GET /metrics           Prometheus text exposition (Registry.dump)
    GET /debug/cycles      recent flight-recorder traces (?n=K | ?slowest=K)
    GET /debug/breaker     circuit-breaker state + next-probe backoff
    GET /debug/degrade     degradation-ladder state + shed bookkeeping
    GET /debug/router      adaptive-router regime samples/medians
    GET /debug/pipeline    speculative-pipeline coverage + abort reasons
    GET /debug/warmup      compile-governor state + per-bucket provenance
    GET /debug/arena       encode-arena slot occupancy + churn

    Unknown paths are 404; malformed query parameters are 400.
    """

    def __init__(self, api: VisibilityAPI, port: int = 0, debug=None):
        self.api = api
        self.port = port
        self.debug = debug
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        api = self.api
        debug = self.debug

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _respond(self, code: int, body: bytes = b"",
                         content_type: str = "application/json"):
                self.send_response(code)
                if body:
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit
                parsed = urlsplit(self.path)
                path = parsed.path
                params = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
                if debug is not None and path == "/metrics":
                    text = debug.metrics_text()
                    if text is None:
                        return self._respond(404)
                    return self._respond(200, text.encode(),
                                         "text/plain; version=0.0.4")
                if debug is not None and path.startswith("/debug/"):
                    try:
                        payload = debug.handle(path, params)
                    except ValueError as exc:
                        return self._respond(400, str(exc).encode(),
                                             "text/plain")
                    if payload is None:
                        return self._respond(404)
                    return self._respond(200, json.dumps(payload).encode())
                try:
                    limit = int(params.get("limit", DEFAULT_LIMIT))
                    offset = int(params.get("offset", 0))
                    if limit < 0 or offset < 0:
                        raise ValueError
                except ValueError:
                    return self._respond(
                        400, b"limit/offset must be non-negative integers",
                        "text/plain")
                parts = [p for p in path.split("/") if p]
                summary = None
                if (len(parts) >= 5 and parts[0] == "apis"
                        and parts[3] == "clusterqueues"
                        and parts[5:6] == ["pendingworkloads"]):
                    summary = api.pending_workloads_cq(parts[4], limit, offset)
                elif (len(parts) >= 8 and parts[3] == "namespaces"
                        and parts[5] == "localqueues"
                        and parts[7] == "pendingworkloads"):
                    summary = api.pending_workloads_lq(parts[4], parts[6],
                                                       limit, offset)
                if summary is None:
                    return self._respond(404)
                self._respond(200, json.dumps(asdict(summary)).encode())

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
