"""Visibility extension API: pending-workloads views.

Equivalent of the reference's pkg/visibility (server.go:46-98,
api/rest/pending_workloads_cq.go, pending_workloads_lq.go) and
apis/visibility/v1alpha1 (types.go:64-98): positions in queue with
limit/offset pagination.

Two serving modes (ISSUE 12 — the snapshot-backed query plane):

- ``VisibilityAPI`` computes LIVE off the queue manager's heaps — the
  reference's behavior, kept as the conformance path and the fallback
  when no query plane is wired (bare ``VisibilityServer``).
- With a ``QueryPlane`` attached (``KueueManager.serve_visibility``
  wires it), every pending-position/status request is served from the
  plane's current SEALED view — an immutable per-cycle publication
  backed by the cycle's own copy-on-write snapshot handout — so a read
  storm never contends with the admission cycle's live state. Every
  response then carries the staleness stamp (``generation`` token,
  ``cycle`` id, ``age_s``); while the plane is still warming (no cycle
  sealed yet) the server answers 503 with a Retry-After header instead
  of blocking.

``VisibilityServer`` also exposes the operator debug surface
(``/metrics`` + ``/debug/*``, obs.DebugEndpoints) and feeds the
read-side saturation metrics (``visibility_requests_total{route,code}``,
request-latency histograms, snapshot-age and in-flight-reads gauges)
into the same Registry ``/metrics`` serves from.
"""

from __future__ import annotations

import json
import threading
import time as _time
from dataclasses import asdict, dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg

DEFAULT_LIMIT = 1000

# Retry-After seconds the server suggests while the query plane warms
# (no sealed cycle yet — one admission cycle away from serving).
WARMING_RETRY_AFTER_S = 1


@dataclass
class PendingWorkload:
    """reference: apis/visibility/v1alpha1/types.go:64-83"""
    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    items: list = field(default_factory=list)


class VisibilityAPI:
    def __init__(self, queues):
        self.queues = queues

    def pending_workloads_cq(self, cq_name: str, limit: int = DEFAULT_LIMIT,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """reference: pending_workloads_cq.go:36+ — full, ordered pending
        list with per-LQ positions."""
        infos = self.queues.pending_workloads_info(cq_name)
        lq_positions: dict = {}
        items = []
        for idx, info in enumerate(infos):
            if len(items) >= limit:
                break
            lq_key = wlpkg.queue_key(info.obj)
            lq_pos = lq_positions.get(lq_key, 0)
            lq_positions[lq_key] = lq_pos + 1
            if idx < offset:
                continue
            items.append(PendingWorkload(
                name=info.obj.metadata.name,
                namespace=info.obj.metadata.namespace,
                local_queue_name=info.obj.spec.queue_name,
                priority=prioritypkg.priority(info.obj),
                position_in_cluster_queue=idx,
                position_in_local_queue=lq_pos))
        return PendingWorkloadsSummary(items=items)

    def pending_workloads_lq(self, namespace: str, lq_name: str,
                             limit: int = DEFAULT_LIMIT,
                             offset: int = 0) -> PendingWorkloadsSummary:
        """reference: pending_workloads_lq.go — the LQ view is a filtered
        projection of its CQ's list. Computed in one pass over the CQ's
        ordered infos WITHOUT materializing a PendingWorkload for every
        unrelated LQ (the old limit=10**9 full-summary build was O(CQ
        pending) allocations per request at the 50k-pending shape)."""
        lq_key = f"{namespace}/{lq_name}"
        lq = self.queues.local_queues.get(lq_key)
        if lq is None:
            return PendingWorkloadsSummary()
        infos = self.queues.pending_workloads_info(lq.cluster_queue)
        items = []
        lq_pos = 0
        for idx, info in enumerate(infos):
            obj = info.obj
            if (obj.metadata.namespace != namespace
                    or obj.spec.queue_name != lq_name):
                continue
            pos = lq_pos
            lq_pos += 1
            if pos < offset:
                continue
            if len(items) >= limit:
                break
            items.append(PendingWorkload(
                name=obj.metadata.name,
                namespace=obj.metadata.namespace,
                local_queue_name=obj.spec.queue_name,
                priority=prioritypkg.priority(obj),
                position_in_cluster_queue=idx,
                position_in_local_queue=pos))
        return PendingWorkloadsSummary(items=items)


def _row_payload(row) -> dict:
    """A query-plane PendingPosition as the wire item: the reference
    fields plus the nominate-rank column (omitted when None so the
    payload stays backward-shaped for rows that weren't cycle heads)."""
    item = {
        "name": row.name,
        "namespace": row.namespace,
        "local_queue_name": row.local_queue_name,
        "priority": row.priority,
        "position_in_cluster_queue": row.position_in_cluster_queue,
        "position_in_local_queue": row.position_in_local_queue,
    }
    if row.nominate_rank is not None:
        item["nominate_rank"] = row.nominate_rank
    return item


class VisibilityServer:
    """Serve the visibility API over HTTP (reference: server on :8082).

    GET /apis/visibility.kueue.x-k8s.io/v1alpha1/clusterqueues/<cq>/pendingworkloads
    GET /apis/visibility.kueue.x-k8s.io/v1alpha1/namespaces/<ns>/localqueues/<lq>/pendingworkloads
    GET /apis/visibility.kueue.x-k8s.io/v1alpha1/namespaces/<ns>/workloads/<wl>
    Query params: limit, offset (pendingworkloads routes).

    With a ``query_plane`` wired (KueueManager.serve_visibility), the
    pending/status routes serve from the plane's sealed view — 503 +
    Retry-After while warming — and stamp every response with the
    generation token / cycle / age. The workloads route exists only on
    the plane (404 without one). With a ``debug`` surface wired
    (obs.DebugEndpoints) the server additionally exposes the operator
    endpoints:

    GET /metrics           Prometheus text exposition (Registry.dump)
    GET /debug/cycles      recent flight-recorder traces (?n=K | ?slowest=K)
    GET /debug/breaker     circuit-breaker state + next-probe backoff
    GET /debug/degrade     degradation-ladder state + shed bookkeeping
    GET /debug/router      adaptive-router regime samples/medians
    GET /debug/pipeline    speculative-pipeline coverage + abort reasons
    GET /debug/warmup      compile-governor state + per-bucket provenance
    GET /debug/queryplane  sealed-view state + token lag + read counters
    GET /debug/arena       encode-arena slot occupancy + churn

    Unknown paths are 404; malformed query parameters are 400. Every
    request (all codes, all routes) lands in the read-side saturation
    metrics when a Registry is wired.
    """

    def __init__(self, api: VisibilityAPI, port: int = 0, debug=None,
                 query_plane=None, metrics=None):
        self.api = api
        self.port = port
        self.debug = debug
        self.query_plane = query_plane
        self.metrics = metrics
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        api = self.api
        debug = self.debug
        plane = self.query_plane
        metrics = self.metrics

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _respond(self, code: int, body: bytes = b"",
                         content_type: str = "application/json",
                         headers: tuple = ()):
                # Record the OUTCOME before the socket write: a client
                # dropping mid-response must not turn a served 200 into
                # a phantom 500 in visibility_requests_total.
                self._code = code
                self.send_response(code)
                for name, value in headers:
                    self.send_header(name, value)
                if body:
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_GET(self):
                # Read-side saturation accounting wraps EVERY path —
                # including 4xx and handler exceptions — so /metrics
                # reflects the true request mix under a storm.
                self._code = 500
                self._route = "unknown"
                t0 = _time.perf_counter()
                if metrics is not None:
                    metrics.visibility_read_begin()
                try:
                    self._serve()
                except ConnectionError:
                    # Reader went away mid-write (BrokenPipeError or
                    # ECONNRESET): not a server error, and letting it
                    # escape would traceback-spam stderr per dropped
                    # connection at storm QPS.
                    pass
                finally:
                    if metrics is not None:
                        metrics.visibility_read_end()
                        metrics.visibility_request(
                            self._route, self._code,
                            _time.perf_counter() - t0)

            def _serve(self):
                from urllib.parse import parse_qs, urlsplit
                parsed = urlsplit(self.path)
                path = parsed.path
                params = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
                if debug is not None and path == "/metrics":
                    self._route = "metrics"
                    if plane is not None and metrics is not None:
                        # Refresh the snapshot-age gauge at scrape time
                        # (a publish writes 0; scrapes carry the decay).
                        view = plane.acquire()
                        try:
                            if view is not None:
                                metrics.set_visibility_snapshot_age(
                                    view.age_s())
                        finally:
                            plane.release(view)
                    text = debug.metrics_text()
                    if text is None:
                        return self._respond(404)
                    return self._respond(200, text.encode(),
                                         "text/plain; version=0.0.4")
                if debug is not None and path.startswith("/debug/"):
                    self._route = "debug"
                    try:
                        payload = debug.handle(path, params)
                    except ValueError as exc:
                        return self._respond(400, str(exc).encode(),
                                             "text/plain")
                    if payload is None:
                        return self._respond(404)
                    return self._respond(200, json.dumps(payload).encode())
                try:
                    limit = int(params.get("limit", DEFAULT_LIMIT))
                    offset = int(params.get("offset", 0))
                    if limit < 0 or offset < 0:
                        raise ValueError
                except ValueError:
                    self._route = self._classify(path)
                    return self._respond(
                        400, b"limit/offset must be non-negative integers",
                        "text/plain")
                parts = [p for p in path.split("/") if p]
                route = self._route = self._classify(path, parts)
                if route == "unknown":
                    return self._respond(404)
                if plane is not None:
                    return self._serve_from_plane(route, parts, limit,
                                                  offset)
                if route == "workload":
                    # Point status queries exist only on the query plane.
                    return self._respond(404)
                if route == "cq_pending":
                    summary = api.pending_workloads_cq(parts[4], limit,
                                                       offset)
                else:
                    summary = api.pending_workloads_lq(parts[4], parts[6],
                                                       limit, offset)
                self._respond(200, json.dumps(asdict(summary)).encode())

            @staticmethod
            def _classify(path: str, parts: Optional[list] = None):
                if parts is None:
                    parts = [p for p in path.split("/") if p]
                if not (parts and parts[0] == "apis"):
                    return "unknown"
                if (len(parts) >= 6 and parts[3] == "clusterqueues"
                        and parts[5] == "pendingworkloads"):
                    return "cq_pending"
                if (len(parts) >= 8 and parts[3] == "namespaces"
                        and parts[5] == "localqueues"
                        and parts[7] == "pendingworkloads"):
                    return "lq_pending"
                if (len(parts) == 7 and parts[3] == "namespaces"
                        and parts[5] == "workloads"):
                    return "workload"
                return "unknown"

            def _serve_from_plane(self, route, parts, limit, offset):
                # Reader-held handout contract (ISSUE 12 satellite): the
                # borrow is returned on EVERY path out of here — 503,
                # 200, or a handler exception — via try/finally, so a
                # read storm can never strand snapshot handouts
                # (cache.live_handouts stays zero after shutdown).
                view = plane.acquire()
                if view is None:
                    return self._respond(
                        503, b"query plane warming: no admission cycle "
                             b"sealed yet", "text/plain",
                        headers=(("Retry-After",
                                  str(WARMING_RETRY_AFTER_S)),))
                try:
                    if route == "cq_pending":
                        rows = plane.pending_cq(view, parts[4], limit,
                                                offset)
                        payload = {"items": [_row_payload(r)
                                             for r in rows]}
                    elif route == "lq_pending":
                        rows = plane.pending_lq(view, parts[4], parts[6],
                                                limit, offset)
                        payload = {"items": [_row_payload(r)
                                             for r in rows]}
                    else:  # workload status point query
                        payload = plane.workload_status(view, parts[4],
                                                        parts[6])
                    payload.update(view.stamp())
                    self._respond(200, json.dumps(payload).encode())
                finally:
                    plane.release(view)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
