// Native batched admission solve — the CPU-native backend of the solver
// plane (the runtime fallback when no accelerator is attached, and the
// conformance twin of the jitted kernel in kueue_tpu/solver/kernel.py).
//
// Semantics are a line-for-line port of solve_cycle_impl (kernel.py):
//   Phase A: per-(workload, podset, resource-group) flavor choice over the
//            snapshot availability, honoring eligibility masks, borrowing
//            limits and whenCanBorrow=TryNextFlavor
//            (reference: pkg/scheduler/flavorassigner/flavorassigner.go:406-537)
//   Phase B: sequential admit in borrow -> priority -> FIFO order with
//            intra-cycle usage accounting and cohort bubbling
//            (reference: pkg/scheduler/scheduler.go:234-335)
//
// Exposed via a C ABI and loaded with ctypes (no pybind11 in this image).
// Differentially tested against the jitted kernel in tests/test_native.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

namespace {
constexpr int64_t NO_LIMIT = int64_t(1) << 62;
constexpr int64_t BORROW_CAP = NO_LIMIT / 4;

inline int64_t imax(int64_t a, int64_t b) { return a > b ? a : b; }
inline int64_t imin(int64_t a, int64_t b) { return a < b ? a : b; }
}  // namespace

extern "C" int kueue_solve_cycle(
    // dimensions
    int64_t Q, int64_t C, int64_t F, int64_t R, int64_t W, int64_t P,
    // topology
    const int32_t* cq_cohort,         // [Q]
    const int64_t* nominal,           // [Q,F,R]
    const int64_t* borrow_limit,      // [Q,F,R]
    const int64_t* guaranteed,        // [Q,F,R]
    const uint8_t* offered,           // [Q,F,R]
    const int32_t* group_id,          // [Q,R]
    const int32_t* flavor_group,      // [Q,F]
    const int32_t* flavor_rank,       // [Q,F]
    const uint8_t* prefer_no_borrow,  // [Q]
    const int64_t* cohort_subtree,    // [C,F,R]
    // state (mutated in place: post-cycle usage)
    int64_t* usage,                   // [Q,F,R]
    int64_t* cohort_usage,            // [C,F,R]
    // workload batch
    const int64_t* requests,          // [W,P,R]
    const uint8_t* podset_active,     // [W,P]
    const int32_t* wl_cq,             // [W]
    const int64_t* priority,         // [W]
    const double* timestamp,         // [W]
    const uint8_t* eligible,          // [W,P,F]
    const uint8_t* solvable,          // [W]
    // outputs
    uint8_t* admitted,                // [W]
    int32_t* chosen,                  // [W,P,R]
    uint8_t* borrows,                 // [W]
    uint8_t* fit                      // [W]
) {
  (void)C;  // cohort count is implicit in the array extents
  const int64_t FR = F * R;

  // ---- availability under the snapshot usage (kernel.py::_available) ----
  std::vector<int64_t> avail(size_t(Q) * FR);
  for (int64_t q = 0; q < Q; ++q) {
    const int32_t c = cq_cohort[q];
    for (int64_t fr = 0; fr < FR; ++fr) {
      const size_t idx = size_t(q) * FR + fr;
      if (c < 0) {
        avail[idx] = nominal[idx] - usage[idx];
      } else {
        const int64_t local = imax(0, guaranteed[idx] - usage[idx]);
        const int64_t parent_avail =
            cohort_subtree[size_t(c) * FR + fr] - cohort_usage[size_t(c) * FR + fr];
        const int64_t cap = (nominal[idx] - guaranteed[idx]) -
                            imax(0, usage[idx] - guaranteed[idx]) +
                            imin(borrow_limit[idx], BORROW_CAP);
        avail[idx] = local + imin(parent_avail, cap);
      }
    }
  }

  // ---- Phase A: flavor assignment ----
  // asg_usage: per-workload [F,R] accumulation across its podsets
  std::vector<int64_t> asg_usage(size_t(W) * FR, 0);
  std::fill(chosen, chosen + size_t(W) * P * R, int32_t(-1));

  for (int64_t w = 0; w < W; ++w) {
    const int32_t q = wl_cq[w];
    bool ok_all = true;
    bool borrow_all = false;
    bool any_active = false;
    int64_t* asg_w = asg_usage.data() + size_t(w) * FR;

    for (int64_t p = 0; p < P; ++p) {
      if (!podset_active[size_t(w) * P + p]) continue;
      any_active = true;
      const int64_t* req = requests + (size_t(w) * P + p) * R;
      const uint8_t* elig = eligible + (size_t(w) * P + p) * F;

      // groups touched by this podset's requests
      for (int64_t r0 = 0; r0 < R; ++r0) {
        if (req[r0] <= 0) continue;
        const int32_t g = group_id[size_t(q) * R + r0];
        // only resolve each group once: at its first requested resource
        bool first_of_group = true;
        for (int64_t rp = 0; rp < r0; ++rp) {
          if (req[rp] > 0 && group_id[size_t(q) * R + rp] == g) {
            first_of_group = false;
            break;
          }
        }
        if (!first_of_group) continue;
        if (g < 0) { ok_all = false; continue; }

        // pick the flavor for group g: first fit by rank; TryNextFlavor
        // prefers the first no-borrow fit over an earlier borrowing fit
        int32_t best_rank = INT32_MAX, best_f = -1;
        int32_t best_nb_rank = INT32_MAX, best_nb_f = -1;
        bool best_borrows = false;
        for (int64_t f = 0; f < F; ++f) {
          if (flavor_group[size_t(q) * F + f] != g) continue;
          if (!elig[f]) continue;
          bool fits = true, borrows_f = false, any_rel = false;
          for (int64_t r = 0; r < R; ++r) {
            if (req[r] <= 0 || group_id[size_t(q) * R + r] != g) continue;
            any_rel = true;
            const size_t idx = size_t(q) * FR + size_t(f) * R + r;
            const int64_t val = req[r] + asg_w[size_t(f) * R + r];
            if (!offered[idx] || val > avail[idx]) { fits = false; break; }
            if (usage[idx] + val > nominal[idx]) borrows_f = true;
          }
          if (!any_rel || !fits) continue;
          const int32_t rank = flavor_rank[size_t(q) * F + f];
          if (rank < best_rank) { best_rank = rank; best_f = int32_t(f);
                                  best_borrows = borrows_f; }
          if (!borrows_f && rank < best_nb_rank) { best_nb_rank = rank;
                                                   best_nb_f = int32_t(f); }
        }
        int32_t pick = best_f;
        bool pick_borrows = best_borrows;
        if (prefer_no_borrow[q] && best_nb_f >= 0) {
          pick = best_nb_f;
          pick_borrows = false;
        }
        if (pick < 0) { ok_all = false; continue; }
        for (int64_t r = 0; r < R; ++r) {
          if (req[r] <= 0 || group_id[size_t(q) * R + r] != g) continue;
          chosen[(size_t(w) * P + p) * R + r] = pick;
          asg_w[size_t(pick) * R + r] += req[r];
        }
        if (pick_borrows) borrow_all = true;
      }
    }
    borrows[w] = borrow_all ? 1 : 0;
    fit[w] = (ok_all && solvable[w] && any_active) ? 1 : 0;
  }

  // ---- Phase B: sequential admit (kernel.py admit_step) ----
  std::vector<int64_t> order(static_cast<size_t>(W));
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    if (fit[a] != fit[b]) return fit[a] > fit[b];
    if (borrows[a] != borrows[b]) return borrows[a] < borrows[b];
    if (priority[a] != priority[b]) return priority[a] > priority[b];
    return timestamp[a] < timestamp[b];
  });

  std::memset(admitted, 0, size_t(W));
  for (int64_t oi = 0; oi < W; ++oi) {
    const int64_t w = order[oi];
    if (!fit[w]) continue;
    const int32_t q = wl_cq[w];
    const int32_t c = cq_cohort[q];
    const int64_t* au = asg_usage.data() + size_t(w) * FR;
    int64_t* usage_q = usage + size_t(q) * FR;
    const int64_t* nom_q = nominal + size_t(q) * FR;
    const int64_t* guar_q = guaranteed + size_t(q) * FR;
    const int64_t* bl_q = borrow_limit + size_t(q) * FR;

    bool still_fits = true;
    for (int64_t fr = 0; fr < FR && still_fits; ++fr) {
      if (au[fr] == 0) continue;
      int64_t avail_fr;
      if (c < 0) {
        avail_fr = nom_q[fr] - usage_q[fr];
      } else {
        const int64_t local = imax(0, guar_q[fr] - usage_q[fr]);
        const int64_t parent_avail = cohort_subtree[size_t(c) * FR + fr] -
                                     cohort_usage[size_t(c) * FR + fr];
        const int64_t cap = (nom_q[fr] - guar_q[fr]) -
                            imax(0, usage_q[fr] - guar_q[fr]) +
                            imin(bl_q[fr], BORROW_CAP);
        avail_fr = local + imin(parent_avail, cap);
      }
      if (au[fr] > avail_fr) still_fits = false;
    }
    if (!still_fits) continue;

    admitted[w] = 1;
    for (int64_t fr = 0; fr < FR; ++fr) {
      if (au[fr] == 0 && c < 0) { continue; }
      const int64_t old_over = imax(0, usage_q[fr] - guar_q[fr]);
      usage_q[fr] += au[fr];
      if (c >= 0) {
        const int64_t new_over = imax(0, usage_q[fr] - guar_q[fr]);
        cohort_usage[size_t(c) * FR + fr] += new_over - old_over;
      }
    }
  }
  return 0;
}

extern "C" int kueue_native_abi_version() { return 1; }
