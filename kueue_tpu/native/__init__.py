"""Native (C++) solver backend, loaded via ctypes.

The compute path of the framework is JAX/XLA on TPU; this module is the
native runtime fallback — the same batched admission solve compiled to
machine code for hosts without an accelerator, and a conformance twin
for the jitted kernel. Built on demand with g++ (`make` in this
directory); `available()` gates all use so environments without a
toolchain fall back to the jit/CPU paths transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkueue_native.so")
_lock = threading.Lock()
_lib = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-s", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            if lib.kueue_native_abi_version() != 1:
                _load_failed = True
                return None
            lib.kueue_solve_cycle.restype = ctypes.c_int
            _lib = lib
        except OSError:
            _load_failed = True
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def solve_cycle_native(topo, usage: np.ndarray, cohort_usage: np.ndarray,
                       requests: np.ndarray, podset_active: np.ndarray,
                       wl_cq: np.ndarray, priority: np.ndarray,
                       timestamp: np.ndarray, eligible: np.ndarray,
                       solvable: np.ndarray) -> Optional[dict]:
    """Same contract as kernel.solve_cycle, on numpy arrays. `topo` is the
    numpy encode.Topology. Returns None if the native library is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    Q, F, R = topo.nominal.shape
    C = topo.cohort_subtree.shape[0]
    W, P, _ = requests.shape

    def prep(a, dtype):
        return np.ascontiguousarray(a, dtype=dtype)

    cq_cohort = prep(topo.cq_cohort, np.int32)
    nominal = prep(topo.nominal, np.int64)
    borrow_limit = prep(topo.borrow_limit, np.int64)
    guaranteed = prep(topo.guaranteed, np.int64)
    offered = prep(topo.offered, np.uint8)
    group_id = prep(topo.group_id, np.int32)
    flavor_group = prep(topo.flavor_group, np.int32)
    flavor_rank = prep(topo.flavor_rank, np.int32)
    prefer_nb = prep(topo.prefer_no_borrow, np.uint8)
    cohort_subtree = prep(topo.cohort_subtree, np.int64)
    usage_out = prep(usage, np.int64).copy()
    cohort_out = prep(cohort_usage, np.int64).copy()
    requests_c = prep(requests, np.int64)
    podset_active_c = prep(podset_active, np.uint8)
    wl_cq_c = prep(wl_cq, np.int32)
    priority_c = prep(priority, np.int64)
    timestamp_c = prep(timestamp, np.float64)
    eligible_c = prep(eligible, np.uint8)
    solvable_c = prep(solvable, np.uint8)

    admitted = np.zeros(W, np.uint8)
    chosen = np.full((W, P, R), -1, np.int32)
    borrows = np.zeros(W, np.uint8)
    fit = np.zeros(W, np.uint8)

    rc = lib.kueue_solve_cycle(
        ctypes.c_int64(Q), ctypes.c_int64(C), ctypes.c_int64(F),
        ctypes.c_int64(R), ctypes.c_int64(W), ctypes.c_int64(P),
        _ptr(cq_cohort, ctypes.c_int32), _ptr(nominal, ctypes.c_int64),
        _ptr(borrow_limit, ctypes.c_int64), _ptr(guaranteed, ctypes.c_int64),
        _ptr(offered, ctypes.c_uint8), _ptr(group_id, ctypes.c_int32),
        _ptr(flavor_group, ctypes.c_int32), _ptr(flavor_rank, ctypes.c_int32),
        _ptr(prefer_nb, ctypes.c_uint8), _ptr(cohort_subtree, ctypes.c_int64),
        _ptr(usage_out, ctypes.c_int64), _ptr(cohort_out, ctypes.c_int64),
        _ptr(requests_c, ctypes.c_int64), _ptr(podset_active_c, ctypes.c_uint8),
        _ptr(wl_cq_c, ctypes.c_int32), _ptr(priority_c, ctypes.c_int64),
        _ptr(timestamp_c, ctypes.c_double), _ptr(eligible_c, ctypes.c_uint8),
        _ptr(solvable_c, ctypes.c_uint8),
        _ptr(admitted, ctypes.c_uint8), _ptr(chosen, ctypes.c_int32),
        _ptr(borrows, ctypes.c_uint8), _ptr(fit, ctypes.c_uint8))
    if rc != 0:
        return None
    return {"admitted": admitted.astype(bool), "chosen": chosen,
            "borrows": borrows.astype(bool), "fit": fit.astype(bool),
            "usage": usage_out, "cohort_usage": cohort_out}
