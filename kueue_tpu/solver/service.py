"""BatchSolver: the TPU solve plugged into the admission path.

Integration contract (mirrors how the reference's AdmissionCheck
controllers plug in, per BASELINE.json's north star): the Scheduler hands
the cycle's validated heads + snapshot to the solver; the solver returns
full fit-mode admissions (flavor assignments + usage) computed on device;
entries it could not admit fall through to the CPU path (preemption,
partial admission, detailed status messages).

Equivalence class vs the reference: for cycles where every nominated
entry is fit-mode, the solver's result is identical to the sequential
scheduler (same ordering, same intra-cycle accounting — differentially
tested in tests/test_solver.py). When preemption is involved, fit-mode
entries are accounted before preempt-mode entries instead of interleaved
by the global order; preemptors then run against the post-admission
snapshot. The CPU path (solver=None) remains the strict-conformance mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kueue_tpu import features
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.scheduler import flavorassigner as fa
from kueue_tpu.solver import encode
import jax

from kueue_tpu.solver.kernel import (
    max_rank_bound,
    solve_cycle_fused,
    topo_to_device,
)


class BatchSolver:
    def __init__(self, max_podsets: int = 4, ordering: Optional[wlpkg.Ordering] = None,
                 mesh=None, backend: str = "jit"):
        """backend: "jit" (XLA on the configured platform — the TPU path)
        or "native" (the C++ solve in kueue_tpu.native — the accelerator-
        free runtime; falls back to jit when the library is unavailable)."""
        self.max_podsets = max_podsets
        self.ordering = ordering or wlpkg.Ordering()
        self.mesh = mesh  # optional jax.sharding.Mesh for multi-chip solve
        self.backend = backend
        self._topo_cache = None
        self._topo_key = None

    # --- encoding with topology caching across cycles ---

    def _topology(self, snapshot: Snapshot):
        # cohort_epoch: cohort re-parents / quota edits don't bump any
        # CQ's generation but change the encoded tree. flavor_spec_epoch:
        # ResourceFlavor taint/label edits change eligibility rows without
        # bumping any CQ generation.
        key = (snapshot.cohort_epoch, snapshot.flavor_spec_epoch) + tuple(sorted(
            (name, cq.allocatable_resource_generation)
            for name, cq in snapshot.cluster_queues.items()))
        if key != self._topo_key:
            self._topo_key = key
            topo = encode.encode_topology(snapshot)
            self._topo_cache = (topo, topo_to_device(topo))
        return self._topo_cache

    def solve(self, snapshot: Snapshot, entries: list,
              fair_sharing: bool = False) -> dict:
        """entries: list of workload Info. Returns
        {entry index -> (fa.Assignment, admitted)} for every entry the
        solver could fully assign (fit mode). admitted=False means the
        assignment no longer fit after intra-cycle accounting — the
        scheduler skips it exactly like the reference's sequential
        re-check (scheduler.go:266-273) instead of re-assigning flavors
        against post-cycle usage."""
        if not entries:
            return {}
        topo, topo_dev = self._topology(snapshot)
        state = encode.encode_state(snapshot, topo)
        batch = encode.encode_workloads(entries, snapshot, topo,
                                        ordering=self.ordering,
                                        max_podsets=self.max_podsets)
        if not batch.solvable.any():
            return {}

        result = None
        start_rank = batch.start_rank if batch.start_rank.any() else None
        # The native ABI encodes the flat (single-level) cohort forest and
        # no fair-share sort key, flavor-resume state, or per-resource
        # borrow flags (needed for TryNextFlavor resume decode); those go
        # through the jit path.
        if (self.backend == "native" and self.mesh is None
                and topo.cq_chain.shape[1] == 1 and not fair_sharing
                and start_rank is None and not topo.prefer_no_borrow.any()):
            from kueue_tpu import native
            result = native.solve_cycle_native(
                topo, state.usage, state.cohort_usage, batch.requests,
                batch.podset_active, batch.wl_cq, batch.priority,
                batch.timestamp, batch.eligible, batch.solvable)
        if result is None:
            if self.mesh is not None:
                from kueue_tpu.parallel.mesh import solve_cycle_sharded
                result = solve_cycle_sharded(self.mesh, topo_dev, state, batch,
                                             self.max_podsets,
                                             fair_sharing=fair_sharing,
                                             start_rank=start_rank)
            else:
                # fused cohort-parallel cycle: Phase A + device-built
                # order grid + row-parallel Phase B in ONE dispatch; scan
                # length = max workloads per conflict domain instead of
                # the whole batch
                result = solve_cycle_fused(
                    topo_dev, state.usage, state.cohort_usage,
                    batch.requests, batch.podset_active, batch.wl_cq,
                    batch.priority, batch.timestamp, batch.eligible,
                    batch.solvable, num_podsets=self.max_podsets,
                    max_rank=max_rank_bound(batch.wl_cq, topo.cq_cohort,
                                            topo.cohort_root),
                    fair_sharing=fair_sharing, start_rank=start_rank)

        # One execute, one sync: all outputs come from the same device
        # program, so the first fetch pays the tunnel round trip and the
        # rest are free.
        fetched = jax.device_get({k: result[k] for k in
                                  ("admitted", "fit", "chosen", "borrows",
                                   "chosen_borrow") if k in result})
        return self._decode_batch(entries, snapshot, topo, batch, fetched)

    def _decode_batch(self, entries: list, snapshot: Snapshot,
                      topo: encode.Topology, batch, fetched: dict) -> dict:
        """Decode device output into the scheduler's Assignment form,
        including the LastTriedFlavorIdx resume state exactly as the CPU
        assigner stores it (reference: flavorassigner.go:289-324): the
        rank where the search ended, -1 when the list was exhausted
        (chosen == last flavor, or a TryNextFlavor CQ settling for a
        borrowing fit after scanning the whole list).

        All numeric work (rank, group exhaustion, borrow flags) runs as
        one vectorized numpy pass over the admitted rows; the per-entry
        loop only assembles the Assignment objects from Python lists."""
        from kueue_tpu.api.corev1 import RESOURCE_PODS
        n = batch.n
        fit = np.asarray(fetched["fit"])[:n]
        idx = np.flatnonzero(fit)
        if idx.size == 0:
            return {}
        admitted = np.asarray(fetched["admitted"])[:n][idx]     # [M]
        chosen = np.asarray(fetched["chosen"])[:n][idx]          # [M,P,R]
        borrows = np.asarray(fetched["borrows"])[:n][idx]        # [M]
        cb = fetched.get("chosen_borrow")
        chosen_borrow = (np.asarray(cb)[:n][idx] if cb is not None
                         else np.zeros_like(chosen, dtype=bool))  # [M,P,R]
        qi_arr = batch.wl_cq[idx]                                 # [M]

        # With FlavorFungibility off the CPU assigner never writes the
        # tried index (stays at the dataclass default 0).
        fungibility_on = features.enabled(features.FLAVOR_FUNGIBILITY)
        fi_safe = np.maximum(chosen, 0)
        rank = topo.flavor_rank[qi_arr[:, None, None], fi_safe]   # [M,P,R]
        gi = topo.group_id[qi_arr]                                # [M,R]
        gsize = topo.group_size[qi_arr[:, None], np.maximum(gi, 0)]  # [M,R]
        exhausted = rank == gsize[:, None, :] - 1
        prefer_nb = topo.prefer_no_borrow[qi_arr]                 # [M]
        # TryNextFlavor CQs scanned the whole list looking for a no-borrow
        # fit before settling for this borrowing one.
        exhausted |= prefer_nb[:, None, None] & chosen_borrow
        if fungibility_on:
            tried = np.where(exhausted | (chosen < 0), -1, rank)
        else:
            tried = np.zeros_like(rank)

        chosen_l = chosen.tolist()
        tried_l = tried.tolist()
        borrows_l = borrows.tolist()
        admitted_l = admitted.tolist()
        flavor_names = topo.flavors
        resource_index = topo.resource_index

        # last_state generations per CQ, read fresh per cycle: the cohort
        # generation is the cache's global capacity version, which moves
        # on events (e.g. workload removal) that never rebuild the
        # topology, so caching it across cycles would hand out stale
        # resume state.
        gen_cache: dict = {}
        out = {}
        for row, wi in enumerate(idx.tolist()):
            info = entries[wi]
            gens = gen_cache.get(info.cluster_queue)
            if gens is None:
                cq = snapshot.cluster_queues[info.cluster_queue]
                gens = (cq.allocatable_resource_generation,
                        cq.cohort.allocatable_resource_generation
                        if cq.cohort else 0)
                gen_cache[info.cluster_queue] = gens
            assignment = fa.Assignment(borrowing=bool(borrows_l[row]))
            assignment.last_state = wlpkg.AssignmentClusterQueueState(
                cluster_queue_generation=gens[0], cohort_generation=gens[1])
            covers_pods = topo.covers_pods[batch.wl_cq[wi]]
            usage = assignment.usage
            for pi, psr in enumerate(info.total_requests):
                reqs = dict(psr.requests)
                if covers_pods:
                    reqs[RESOURCE_PODS] = psr.count
                chosen_p = chosen_l[row][pi]
                tried_p = tried_l[row][pi]
                flavors = {}
                flavor_idx = {}
                for r, v in reqs.items():
                    ri = resource_index[r]
                    fi = chosen_p[ri]
                    if v > 0 and fi < 0:
                        raise AssertionError(
                            "solver admitted workload without flavor")
                    fname = flavor_names[fi] if fi >= 0 else flavor_names[0]
                    t = tried_p[ri]
                    flavors[r] = fa.FlavorAssignment(name=fname, mode=fa.FIT,
                                                     tried_flavor_idx=t)
                    flavor_idx[r] = t
                    fr = FlavorResource(fname, r)
                    usage[fr] = usage.get(fr, 0) + v
                assignment.pod_sets.append(fa.PodSetAssignmentResult(
                    name=psr.name, flavors=flavors, requests=reqs,
                    count=psr.count))
                assignment.last_state.last_tried_flavor_idx.append(flavor_idx)
            out[wi] = (assignment, bool(admitted_l[row]))
        return out
